"""Txid-correlated spans — the Dapper-style trace tree, in-process.

One :class:`Tracer` per process holds finished spans in a bounded ring.
A span is opened with :meth:`Tracer.span` (a context manager) or
recorded point-in-time with :meth:`Tracer.instant`; nesting is tracked
per thread, and the cross-thread / cross-plane correlator is the
transaction id carried in ``txid`` — the coordinator, log, device
plane, inter-DC sender/deliverer, and dependency gate all stamp the
same txid, so one committed transaction's spans assemble into a tree
spanning every plane it touched (ISSUE 1 tentpole).

Sampling is DETERMINISTIC per txid (crc32, not ``hash()`` — the latter
is salted per process, and a federation's DCs must agree on which
transactions are traced so a sampled txn's tree is complete across
processes).  Untagged spans (batched device flushes, GC, heartbeats)
are thinned to ~rate by a hashed call counter at partial rates —
enough background context around the per-txn trees without letting a
hot untagged path flood the ring — and recorded on every call only
when the rate is 1.0.

Export is Chrome ``trace_event`` JSON ("X" complete events), loadable
in Perfetto / chrome://tracing next to the JAX profiler captures
(antidote_tpu/obs/prof.py); ``ts`` is epoch microseconds so captures
from several processes align on one timeline.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional

from antidote_tpu.config import Config as _Config
from antidote_tpu.obs.events import _jsonable

#: single source for the tracer knob defaults — Config declares them,
#: the process-global tracer below starts from them, and Node pushes
#: only non-default Config values (obs.configure)
_CFG_DEFAULTS = _Config()


class Span:
    """One finished span (immutable once in the ring)."""

    __slots__ = ("span_id", "parent_id", "name", "cat", "txid",
                 "start_us", "dur_us", "tid", "args")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 cat: str, txid, start_us: int, dur_us: int, tid: int,
                 args: Dict[str, Any]):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.cat = cat
        self.txid = txid
        self.start_us = start_us
        self.dur_us = dur_us
        self.tid = tid
        self.args = args

    def __repr__(self) -> str:  # test/debug ergonomics
        return (f"Span({self.name!r}, cat={self.cat!r}, "
                f"txid={self.txid!r}, dur_us={self.dur_us})")

    def to_trace_event(self) -> Dict[str, Any]:
        args = {k: _jsonable(v) for k, v in self.args.items()}
        if self.txid is not None:
            args["txid"] = _jsonable(self.txid)
        return {"name": self.name, "cat": self.cat, "ph": "X",
                "ts": self.start_us, "dur": self.dur_us,
                "pid": os.getpid(), "tid": self.tid, "args": args}


_SPAN_IDS = itertools.count(1)
_tls = threading.local()


def txid_decision(txid, rate: float) -> bool:
    """The deterministic per-txid sampling decision at ``rate`` —
    crc32 of the txid repr, stable across processes.  Exposed as a
    module function because the wire's trace header (ISSUE 7) carries
    the ORIGIN's sample rate: a receiver replays the origin's decision
    through this same function so a sampled txn's remote-side spans
    record even when the local rate differs."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return (zlib.crc32(repr(txid).encode()) % 10_000) < rate * 10_000


class _NullSpan:
    """Shared no-op context for unsampled call sites (zero alloc)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _LiveSpan:
    """Open span: context manager pushing itself on the thread's stack."""

    __slots__ = ("_tracer", "name", "cat", "txid", "args",
                 "_start_ns", "_parent", "span_id")

    def __init__(self, tracer: "Tracer", name: str, cat: str, txid,
                 args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.txid = txid
        self.args = args
        self.span_id = next(_SPAN_IDS)

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        # the stack holds the LIVE span objects (not bare ids): the
        # kernel-span layer (obs/prof.py) reads the innermost open
        # span's txid/span_id via Tracer.current to attach device
        # kernels to the active txn's tree
        self._parent = stack[-1].span_id if stack else None
        stack.append(self)
        self._start_ns = time.time_ns()
        return self

    def __exit__(self, *exc):
        dur_us = (time.time_ns() - self._start_ns) // 1000
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        self._tracer._add(Span(
            self.span_id, self._parent, self.name, self.cat, self.txid,
            self._start_ns // 1000, dur_us, threading.get_ident(),
            self.args))
        return False


class Tracer:
    """Bounded ring of finished spans + the sampling decision."""

    def __init__(self,
                 capacity: int = _CFG_DEFAULTS.trace_capacity,
                 sample_rate: float = _CFG_DEFAULTS.trace_sample_rate):
        #: memoized per-txid decisions — a txn's id is checked at every
        #: plane it crosses (~8 call sites), and the crc32-of-repr is
        #: the dominant cost of an UNsampled txn's whole trace overhead
        self._decision_cache: Dict[Any, bool] = {}
        #: thins untagged (txid-less) spans at partial sample rates
        self._untagged_seq = itertools.count()
        self.sample_rate = sample_rate
        self._capacity = capacity
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    @property
    def sample_rate(self) -> float:
        return self._sample_rate

    @sample_rate.setter
    def sample_rate(self, rate: float) -> None:
        # cached decisions embed the old rate — drop them with it
        self._sample_rate = float(rate)
        self._decision_cache.clear()

    # -------------------------------------------------------- configuration

    @property
    def capacity(self) -> int:
        """Ring capacity (the /healthz occupancy denominator)."""
        return self._capacity

    def set_capacity(self, capacity: int) -> None:
        if capacity == self._capacity:
            return
        with self._lock:
            self._capacity = capacity
            self._spans = deque(self._spans, maxlen=capacity)

    # ------------------------------------------------------------- sampling

    def sampled(self, txid) -> bool:
        """Deterministic per-txid decision (crc32 of the txid repr —
        stable across processes, unlike the salted builtin hash), so
        every plane of every DC traces the SAME transactions and a
        sampled txn's tree is complete.  Untagged spans (background
        stages, non-transactional reads) are thinned to ~rate by
        hashing a call counter: at partial rates they would otherwise
        record on EVERY call and a hot untagged path (e.g. device-
        served value reads) would evict the sampled transactions' trees
        from the ring; hashing (vs a plain modulo) keeps a periodic
        call pattern from phase-locking one call site out of the ring
        entirely."""
        rate = self.sample_rate
        if rate <= 0.0:
            return False
        if rate >= 1.0:
            return True
        if txid is None:
            n = next(self._untagged_seq)
            return (zlib.crc32(n.to_bytes(8, "little")) % 10_000
                    < rate * 10_000)
        cache = self._decision_cache
        hit = cache.get(txid)
        if hit is None:
            hit = txid_decision(txid, rate)
            if len(cache) >= 8192:  # txids are transient; drop en masse
                cache.clear()
            cache[txid] = hit
        return hit

    def adopt(self, txid, decision: bool) -> None:
        """Seed the decision cache with the ORIGIN DC's sampling
        decision for a replicated txn (computed from the wire trace
        header's carried sample rate, ISSUE 7) so the remote halves of
        a sampled txn's tree record even when the local rate differs.
        Only consulted at partial local rates: rate 0 stays fully off
        (the operator turned tracing off) and rate 1 already records
        everything — both short-circuit before the cache."""
        cache = self._decision_cache
        if len(cache) >= 8192:
            cache.clear()
        cache[txid] = bool(decision)

    def adopt_from_wire(self, hdr, txns) -> None:
        """Replay the ORIGIN's deterministic sampling decisions from a
        wire trace header ``(sample permille, ship wall µs)`` over a
        frame's txns — the ONE receive-side adoption rule
        (interdc/dc.py and cluster/federation.py both route here).

        Skip rules: no header means no origin decision to replay; a
        permille of 0 means the origin wasn't tracing, so there is no
        origin decision either — seeding False would silently override
        THIS DC's own partial-rate sampling for that origin's whole
        stream.  And only partial local rates consult the cache at
        all (0 stays off, 1 records everything), so the crc32 loop is
        skipped outside that regime.  The permille is clamped to 1000:
        the decode layer rejects out-of-range values from the wire,
        but in-process senders are not the only callers."""
        if hdr is None or hdr[0] <= 0 \
                or not 0.0 < self.sample_rate < 1.0:
            return
        rate = min(hdr[0], 1000) / 1000.0
        for txn in txns:
            txid = (getattr(txn.records[-1], "txid", None)
                    if txn.records else None)
            if txid is not None:
                self.adopt(txid, txid_decision(txid, rate))

    # ------------------------------------------------------------ recording

    def span(self, name: str, cat: str = "host", txid=None, **args):
        """Context manager timing the enclosed block; no-op (shared
        null object) when the txid is unsampled or tracing is off."""
        if not self.sampled(txid):
            return _NULL
        return _LiveSpan(self, name, cat, txid, args)

    def instant(self, name: str, cat: str = "host", txid=None,
                **args) -> None:
        """Zero-duration span — a point event on the trace timeline
        (device stage, txn abort); same sampling rule as :meth:`span`."""
        if not self.sampled(txid):
            return
        stack = getattr(_tls, "stack", None)
        self._add(Span(
            next(_SPAN_IDS), stack[-1].span_id if stack else None, name,
            cat, txid, time.time_ns() // 1000, 0, threading.get_ident(),
            args))

    def current(self):
        """The calling thread's innermost OPEN span, or None.  Only
        call sites that passed the sampling decision push onto the
        stack (unsampled sites get the shared null context), so a
        non-None result means "this call chain is being traced" — the
        hook the kernel-span layer (obs/prof.py) uses to decide whether
        to time completion and attach a kernel child-span."""
        stack = getattr(_tls, "stack", None)
        return stack[-1] if stack else None

    def record_span(self, name: str, cat: str, txid, start_us: int,
                    dur_us: int, parent_id: Optional[int] = None,
                    **args) -> None:
        """Record an externally timed, already-finished span — the
        kernel-span layer measures dispatch→completion itself (a
        perf_counter pair around the XLA call) and deposits the result
        here, parented under the enclosing live span so kernels appear
        as children in the txn tree.  No sampling check: callers gate
        on :meth:`current`, which already encodes the decision."""
        self._add(Span(
            next(_SPAN_IDS), parent_id, name, cat, txid, int(start_us),
            int(dur_us), threading.get_ident(), args))

    def _add(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    # -------------------------------------------------------------- queries

    def spans(self, txid=None, name: Optional[str] = None,
              cat: Optional[str] = None) -> List[Span]:
        """Finished spans, oldest first, filtered by any of
        txid/name/cat (the in-process query surface tests assert on)."""
        with self._lock:
            out = list(self._spans)
        if txid is not None:
            out = [s for s in out if s.txid == txid]
        if name is not None:
            out = [s for s in out if s.name == name]
        if cat is not None:
            out = [s for s in out if s.cat == cat]
        return out

    def tree(self, txid) -> List[dict]:
        """The txn's span tree: ``[{"span": Span, "children": [...]}]``
        roots in start order.  Parent links only bind within a thread's
        nesting; cross-thread/plane spans of the txn surface as
        additional roots — the txid is the correlator."""
        spans = self.spans(txid=txid)
        nodes = {s.span_id: {"span": s, "children": []} for s in spans}
        roots = []
        for s in spans:
            parent = nodes.get(s.parent_id)
            if parent is not None:
                parent["children"].append(nodes[s.span_id])
            else:
                roots.append(nodes[s.span_id])
        return roots

    def planes(self, txid) -> set:
        """Categories the txn's spans cover — the smoke test's
        "crossed coordinator → log → device → interdc" assertion."""
        return {s.cat for s in self.spans(txid=txid)}

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # --------------------------------------------------------------- export

    def export_chrome(self, txid=None) -> Dict[str, Any]:
        """Chrome trace_event object (``{"traceEvents": [...]}``) for
        the whole ring or one txn — load in Perfetto / chrome://tracing
        next to a JAX profiler capture of the same window."""
        return {
            "traceEvents": [s.to_trace_event()
                            for s in self.spans(txid=txid)],
            "displayTimeUnit": "ms",
        }

    def export_chrome_json(self, txid=None) -> str:
        return json.dumps(self.export_chrome(txid=txid))

    def save(self, path: str, txid=None) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write(self.export_chrome_json(txid=txid))
        return path


#: process-wide tracer (all DCs share it, like stats.registry)
tracer = Tracer()


def traced(name: str, cat: str):
    """Decorator spanning a coordinator-shaped method (``self, tx,
    ...``) with the transaction's txid — the instrumentation idiom
    tools/trace_lint.py enforces on public txn entry points."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, tx, *args, **kwargs):
            with tracer.span(name, cat, txid=tx.txid):
                return fn(self, tx, *args, **kwargs)
        return wrapper
    return deco

"""Fleet health plane: pull-based federation over every node of a
cluster (ISSUE 17).

Every observability surface before this module is per-process: the
stats registry exposes ONE process at ``/metrics``, the pipeline
registry snapshots the DCs of ONE interpreter, the span ring holds
ONE tracer's events.  A cluster verdict ("is visibility lag within
SLO *anywhere*?") needs all of them merged, so this module federates:

- :func:`parse_prometheus_text` — the exposition-format parser; the
  samples dict it returns is the lingua franca ``obs/slo.py`` judges.
- :func:`scrape_endpoint` — one remote node's ``/metrics`` +
  ``/debug/pipeline`` (and optionally ``/debug/spans``) over HTTP.
- :func:`fleet_snapshot` / :func:`merged_metrics` — every source
  (remote endpoints plus, optionally, the local in-process registry
  and pipeline plane) merged into one snapshot; merged samples carry
  a grafted ``src`` label so SLO worst-offender attribution crosses
  node boundaries.
- :class:`FleetScraper` — caller-elected scrape per the mat/serve.py
  no-background-thread discipline; the ``Config.fleet_scrape_s`` knob
  elects the optional loop in the ``obs_causal_probe_s`` mold
  (interdc/dc.py start_bg_processes is the only spawn site).

Dependency-free by design (urllib + re), like stats.py — the fleet
plane must scrape a wedged node from a bare interpreter.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

log = logging.getLogger(__name__)

#: parsed exposition: sample name -> [(labels, value), ...].  Histogram
#: series keep their exposition suffixes (``*_bucket`` with its ``le``
#: label, ``*_sum``, ``*_count``) — obs/slo.py's quantile math consumes
#: the cumulative buckets directly.
Samples = Dict[str, List[Tuple[Dict[str, str], float]]]

_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"   # sample name
    r"(?:\{(.*)\})?"                 # optional label body
    r"\s+(\S+)"                      # value
    r"(?:\s+-?[0-9]+)?$")            # optional timestamp (ignored)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    out: List[str] = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_prometheus_text(text: str) -> Samples:
    """Exposition text -> samples dict.  Lines that do not parse are
    skipped, not fatal: a half-garbled scrape of a sick node must
    still contribute the samples it did carry."""
    out: Samples = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if m is None:
            continue
        name, labelbody, raw = m.group(1), m.group(2), m.group(3)
        try:
            value = float(raw)  # accepts +Inf/NaN per the format
        except ValueError:
            continue
        labels = ({k: _unescape(v)
                   for k, v in _LABEL_RE.findall(labelbody)}
                  if labelbody else {})
        out.setdefault(name, []).append((labels, value))
    return out


def local_samples() -> Samples:
    """The in-process registry, round-tripped through the exposition
    text so local and remote sources are judged by identical rules."""
    from antidote_tpu import stats

    return parse_prometheus_text(stats.registry.exposition())


def _http_get(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def scrape_endpoint(url: str, timeout: float = 5.0,
                    spans: bool = False) -> Dict[str, object]:
    """One node's surfaces: ``/metrics`` (mandatory — failure raises),
    ``/debug/pipeline`` (best-effort: a metrics-only endpoint still
    federates), ``/debug/spans`` when ``spans`` is set."""
    base = url.rstrip("/")
    src: Dict[str, object] = {
        "metrics": parse_prometheus_text(
            _http_get(base + "/metrics", timeout).decode(
                "utf-8", "replace"))}
    try:
        src["pipeline"] = json.loads(
            _http_get(base + "/debug/pipeline", timeout).decode(
                "utf-8", "replace"))
    except Exception as e:  # noqa: BLE001 — partial sources are sources
        src["pipeline"] = {"error": repr(e)}
    if spans:
        src["spans"] = json.loads(
            _http_get(base + "/debug/spans", timeout).decode(
                "utf-8", "replace"))
    return src


def fleet_snapshot(urls: Iterable[str] = (),
                   include_local: bool = False,
                   timeout: float = 5.0,
                   spans: bool = False) -> dict:
    """Merge every reachable source into one snapshot.  Unreachable
    endpoints land in ``errors`` (and bump the scrape-error counter)
    instead of failing the fleet — a down node is exactly when the
    health verdict matters."""
    from antidote_tpu import stats

    snap: dict = {"at_us": time.time_ns() // 1000,
                  "sources": {}, "errors": {}}
    if include_local:
        from antidote_tpu.obs import pipeline

        snap["sources"]["local"] = {"metrics": local_samples(),
                                    "pipeline": pipeline.snapshot()}
    for url in urls:
        try:
            snap["sources"][url] = scrape_endpoint(
                url, timeout=timeout, spans=spans)
        except Exception as e:  # noqa: BLE001 — per-source isolation
            snap["errors"][url] = repr(e)
            stats.registry.fleet_scrape_errors.inc(source=str(url))
    return snap


def merged_metrics(snapshot: dict) -> Samples:
    """Union of every source's samples with a ``src`` label grafted
    on, so a per-objective worst offender names the node it lives
    on.  Counter-kind objectives sum across sources; histogram-kind
    objectives keep per-source groups (the ``src`` label joins the
    group key like any other label)."""
    merged: Samples = {}
    for src_name, src in snapshot.get("sources", {}).items():
        for name, series in (src.get("metrics") or {}).items():
            rows = merged.setdefault(name, [])
            for labels, value in series:
                labeled = dict(labels)
                labeled["src"] = str(src_name)
                rows.append((labeled, value))
    return merged


class FleetScraper:
    """Caller-elected fleet scrape.  ``scrape_once()`` is the whole
    API — merge the sources, refresh the FLEET_* gauges, judge the
    merged samples against the default SLOs and refresh the SLO_*
    gauges.  No thread exists unless ``start()`` is called, and the
    only production ``start()`` site is the ``Config.fleet_scrape_s``
    knob gate in interdc/dc.py (the ``obs_causal_probe_s`` mold)."""

    def __init__(self, endpoints: Iterable[str] = (),
                 period_s: float = 0.0, include_local: bool = True,
                 timeout: float = 5.0, name: str = "fleet"):
        self.endpoints = list(endpoints)
        self.period_s = float(period_s)
        self.include_local = bool(include_local)
        self.timeout = float(timeout)
        self.name = str(name)
        self.rounds = 0
        self.last_snapshot: Optional[dict] = None
        self.last_verdict: Optional[dict] = None
        self._prev_scrape_s: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def scrape_once(self) -> dict:
        from antidote_tpu import stats
        from antidote_tpu.obs import slo

        snap = fleet_snapshot(self.endpoints,
                              include_local=self.include_local,
                              timeout=self.timeout)
        now = time.monotonic()
        # the realized inter-scrape gap IS the staleness a reader of
        # the merged snapshot pays; a wedged loop freezes the gauge
        # and shows up as Prometheus staleness/absence
        stats.registry.fleet_scrape_age.set(
            0.0 if self._prev_scrape_s is None
            else now - self._prev_scrape_s)
        self._prev_scrape_s = now
        stats.registry.fleet_sources.set(float(len(snap["sources"])))
        verdict = slo.evaluate(merged_metrics(snap))
        slo.refresh_gauges(verdict)
        snap["verdict"] = verdict
        self.last_snapshot = snap
        self.last_verdict = verdict
        self.rounds += 1
        return snap

    # ---- knob-gated loop (obs_causal_probe_s mold) ----------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-scrape-{self.name}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("fleet scrape round failed")

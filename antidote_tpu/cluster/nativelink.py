"""Native-transport node fabric: the NodeLink protocol over the C++
endpoint (antidote_tpu/native/nodelink.cpp).

Why a second transport exists: the pure-Python NodeLink needs the GIL
of a BUSY peer just to read a frame off the socket, which puts a
scheduler-latency floor of ~1-4 ms under every intra-DC RPC (measured;
the reference's BEAM schedulers service vnode commands with no such
global lock, reference include/antidote.hrl:28).  Here all framing and
socket IO runs on a C++ event thread; Python worker threads block
inside ``nl_recv`` / ``nl_wait`` with the GIL RELEASED (ctypes drops it
for the duration of the call), so the interpreter is only entered to
actually execute a handler or consume a completed reply.

The client side is pipelined: ``start_request`` returns immediately
with a correlation handle and any number of requests share one
connection — ``request_many`` fans a 2PC prepare round out to N peers
from a single thread with zero thread spawns (the reference's
broadcast-and-collect, src/clocksi_interactive_coord.erl:514-577).

Everything protocol-level is IDENTICAL to cluster/link.py and shared
with it: termcodec payloads ``(origin, rid, kind, payload)``, typed
error replies, and the server-side AtMostOnceCache keyed by (origin,
rid) — a retry after a transport error re-sends the SAME rid so
non-idempotent RPCs stay exactly-once.

ISSUE 12 adds the NATIVE ANSWER PLANE: after a worker answers a
read-only RPC the ``answer_policy`` marks cacheable (deterministic at
the served state — an explicit-clock snapshot read, a gap-repair
range fully below the commit watermark, a handoff byte-read), the
reply bytes are PUBLISHED to the C++ endpoint keyed by the request's
(origin, kind, payload) bytes; an identical repeat — a retry, a
repair storm, a puller's re-fetch — is then answered by the event
thread with the GIL never taken.  Answers are byte-identical to the
Python handler's by construction (the published bytes ARE its reply),
and ``invalidate_answers`` clears the table wholesale whenever served
state moves under it (log truncation, ring/ownership changes — wired
by cluster/node.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.interdc import termcodec
from antidote_tpu.interdc.transport import LinkDown
from antidote_tpu.cluster.link import (
    AtMostOnceCache,
    _err_kind,
    _raise_remote,
)
from antidote_tpu.obs import nativeobs

log = logging.getLogger(__name__)

#: events per telemetry drain call (ring capacity: one call empties it)
_TEL_DRAIN_MAX = nativeobs.RING_CAPACITY

_lib = None
_lib_lock = threading.Lock()


def _load() -> Optional["_Lib"]:
    """Build + load the endpoint library once per process; None when no
    compiler is available (callers fall back to the Python NodeLink)."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from antidote_tpu.native.build import ensure_built

        path = ensure_built("nodelink")
        if path is None:
            return None
        lib = _Lib(path)
        _lib = lib
        return lib


class _Lib:
    """Two ctypes bindings of ONE shared library, split by GIL policy:

    - BLOCKING entry points (condition waits: nl_wait, nl_collect,
      nl_recv*, plus nl_shutdown's thread join) bind via ``CDLL`` —
      the GIL is released for the call's duration, which is the whole
      point of the native IO plane.
    - QUICK entry points (enqueue/bookkeeping: nl_send, nl_reply*,
      nl_cancel, ...) bind via ``PyDLL`` — the GIL stays HELD.  A CDLL
      call must RE-ACQUIRE the GIL on return, and against busy threads
      that costs up to a scheduler timeslice (~ms) — measured at
      4.4 ms per start_request in the cluster client, dwarfing the
      actual C work (µs).  Safe because these never block: the C side
      takes only the endpoint mutex, whose holders never need the GIL
      (no syscalls run under it — see nodelink.cpp's event loop).
    """

    def __init__(self, path: str):
        quick = ctypes.PyDLL(path)
        slow = ctypes.CDLL(path)
        self.nl_create = slow.nl_create  # binds a socket: rare, safe
        self.nl_create.restype = ctypes.c_void_p
        self.nl_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        self.nl_port = quick.nl_port
        self.nl_port.restype = ctypes.c_int
        self.nl_port.argtypes = [ctypes.c_void_p]
        self.nl_set_peer = quick.nl_set_peer
        self.nl_set_peer.restype = None
        self.nl_set_peer.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_char_p, ctypes.c_int]
        self.nl_send = quick.nl_send
        self.nl_send.restype = ctypes.c_longlong
        self.nl_send.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                 ctypes.c_char_p, ctypes.c_long]
        self.nl_wait = slow.nl_wait
        self.nl_wait.restype = ctypes.c_long
        self.nl_wait.argtypes = [ctypes.c_void_p, ctypes.c_ulonglong,
                                 ctypes.c_void_p, ctypes.c_long,
                                 ctypes.c_int]
        self.nl_cancel = quick.nl_cancel
        self.nl_cancel.restype = None
        self.nl_cancel.argtypes = [ctypes.c_void_p, ctypes.c_ulonglong]
        self.nl_drop_peer = quick.nl_drop_peer
        self.nl_drop_peer.restype = None
        self.nl_drop_peer.argtypes = [ctypes.c_void_p, ctypes.c_int]
        self.nl_reply = quick.nl_reply
        self.nl_reply.restype = ctypes.c_int
        self.nl_reply.argtypes = [ctypes.c_void_p, ctypes.c_ulonglong,
                                  ctypes.c_ulonglong, ctypes.c_char_p,
                                  ctypes.c_long]
        self.nl_recv_batch = slow.nl_recv_batch
        self.nl_recv_batch.restype = ctypes.c_long
        self.nl_recv_batch.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                       ctypes.c_long, ctypes.c_int,
                                       ctypes.c_int]
        self.nl_collect = slow.nl_collect
        self.nl_collect.restype = ctypes.c_long
        self.nl_collect.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_ulonglong),
                                    ctypes.c_int, ctypes.c_void_p,
                                    ctypes.c_long, ctypes.c_int]
        # zero-timeout PROBE bindings of the two waits: with the GIL
        # held they return instantly whether or not results are ready —
        # a pipelined reply that already arrived is consumed without
        # ever giving up the interpreter
        self.nl_wait_probe = quick.nl_wait
        self.nl_wait_probe.restype = ctypes.c_long
        self.nl_wait_probe.argtypes = self.nl_wait.argtypes
        self.nl_collect_probe = quick.nl_collect
        self.nl_collect_probe.restype = ctypes.c_long
        self.nl_collect_probe.argtypes = self.nl_collect.argtypes
        self.nl_shutdown = slow.nl_shutdown
        self.nl_shutdown.restype = None
        self.nl_shutdown.argtypes = [ctypes.c_void_p]
        self.nl_free = quick.nl_free
        self.nl_free.restype = None
        self.nl_free.argtypes = [ctypes.c_void_p]
        # the published-answer plane (ISSUE 12): all bookkeeping-only
        # (map insert / clear / counter reads under the endpoint
        # mutex, whose holders never block) — quick class
        self.nl_publish = quick.nl_publish
        self.nl_publish.restype = None
        self.nl_publish.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_long, ctypes.c_char_p,
                                    ctypes.c_long, ctypes.c_ulonglong,
                                    ctypes.c_int]
        self.nl_publish_clear = quick.nl_publish_clear
        self.nl_publish_clear.restype = None
        self.nl_publish_clear.argtypes = [ctypes.c_void_p]
        self.nl_pub_gen = quick.nl_pub_gen
        self.nl_pub_gen.restype = ctypes.c_ulonglong
        self.nl_pub_gen.argtypes = [ctypes.c_void_p]
        self.nl_counters = quick.nl_counters
        self.nl_counters.restype = ctypes.c_int
        self.nl_counters.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.c_int]
        # the telemetry plane (ISSUE 16): the cursor/enable pair is
        # atomics-only (no mutex, no syscall) — quick class; the drain
        # is a bulk memcpy of up to 128 KiB — CDLL class, GIL released,
        # never called inside a lock region
        self.nl_tel_cursor = quick.nl_tel_cursor
        self.nl_tel_cursor.restype = ctypes.c_int
        self.nl_tel_cursor.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.c_int]
        self.nl_tel_enable = quick.nl_tel_enable
        self.nl_tel_enable.restype = None
        self.nl_tel_enable.argtypes = [ctypes.c_void_p, ctypes.c_int]
        self.nl_tel_drain = slow.nl_tel_drain
        self.nl_tel_drain.restype = ctypes.c_long
        self.nl_tel_drain.argtypes = [
            ctypes.c_void_p, ctypes.c_ulonglong, ctypes.c_void_p,
            ctypes.c_long, ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.POINTER(ctypes.c_ulonglong)]


def native_available() -> bool:
    return _load() is not None


class _Handle:
    """One in-flight request: everything needed to retry it once with
    the same rid after a transport failure."""

    __slots__ = ("peer_id", "idx", "data", "corr", "attempt")

    def __init__(self, peer_id, idx: int, data: bytes, corr: int):
        self.peer_id = peer_id
        self.idx = idx
        self.data = data
        self.corr = corr
        self.attempt = 0


class NativeNodeLink:
    """Drop-in NodeLink with the native IO plane (plus async calls)."""

    def __init__(self, node_id, host: str = "127.0.0.1", port: int = 0,
                 connect_timeout: float = 5.0,
                 request_timeout: float = 30.0, workers: int = 4,
                 batch_max: int = 32):
        lib = _load()
        if lib is None:
            raise RuntimeError("native node fabric unavailable "
                               "(no compiler); use NodeLink")
        self.node_id = node_id
        self.host = host
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self._lib = lib
        self._h = lib.nl_create(host.encode(), port)
        if not self._h:
            raise OSError(f"cannot bind node fabric on {host}:{port}")
        self._n_workers = workers
        #: max requests serviced per GIL timeslice; bounds how long a
        #: blocking request (a clock wait) can stall batch-mates
        self._batch_max = batch_max
        self._workers: List[threading.Thread] = []
        self._handler: Optional[Callable[[Any, str, Any], Any]] = None
        #: native answer plane (ISSUE 12): ``answer_policy(kind,
        #: payload) -> bool`` marks a successfully-answered read-only
        #: RPC publishable — its reply bytes install in the C++
        #: endpoint's table and identical repeats are answered on the
        #: event thread without the GIL.  None = nothing publishes
        #: (the plane stays cold; every request takes the worker path)
        self.answer_policy: Optional[Callable[[str, Any], bool]] = None
        self._amo = AtMostOnceCache(request_timeout=request_timeout)
        self._lock = threading.Lock()
        self._peer_idx: Dict[Any, int] = {}
        self._peer_addr: Dict[Any, Tuple[str, int]] = {}
        self._next_idx = 0
        #: client request ids (boot_token, n) — unique across process
        #: incarnations so a restarted node never collides with its
        #: predecessor's entries in peers' at-most-once caches
        self._boot = int.from_bytes(os.urandom(8), "big")
        self._rid = 0
        self._closed = False
        #: client calls currently inside a native entry point — close()
        #: must not nl_free the handle under them (use-after-free); the
        #: shut-down endpoint fails their waits promptly, so the count
        #: drains in microseconds once nl_shutdown ran
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        # telemetry plane (ISSUE 16): the drain cursor + cumulative
        # overwrite losses live HERE (the C side only knows head); the
        # buffer is reused across drains so the 50 ms cadence never
        # allocates.  The watchdog probe is registered per endpoint —
        # a process hosting several DCs watches each one's ring.
        self._tel_tail = 0
        self._tel_dropped = 0
        self._tel_buf = ctypes.create_string_buffer(
            nativeobs.EVENT_SIZE * _TEL_DRAIN_MAX)
        self._tel_enabled = True
        self._tel_name = f"nodelink:{node_id}"
        nativeobs.watchdog.register(self._tel_name, self._tel_probe)

    # ------------------------------------------------------------- server

    def serve(self, handler: Callable[[Any, str, Any], Any]
              ) -> Tuple[str, int]:
        self._handler = handler
        for i in range(self._n_workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"antidote-nl-worker-{i}")
            t.start()
            self._workers.append(t)
        return self.local_addr()

    def local_addr(self) -> Tuple[str, int]:
        return (self.host, int(self._lib.nl_port(self._h)))

    def _worker(self) -> None:
        """Drain inbound requests in batches: the busy interpreter
        grants this thread one timeslice; servicing every queued request
        inside it collapses N GIL acquisitions into one (the pure-Python
        NodeLink gets the same effect implicitly by looping on a socket
        with buffered data — here it is explicit and cross-connection)."""
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        while True:
            n = self._lib.nl_recv_batch(self._h, buf, cap, 200,
                                        self._batch_max)
            if n == -1:
                return
            if n == 0:
                continue
            if n < -1:
                cap = -n
                buf = ctypes.create_string_buffer(cap)
                continue
            raw = ctypes.string_at(buf, n)
            pos = 0
            while pos < n:
                conn_token = int.from_bytes(raw[pos:pos + 8], "big")
                corr = int.from_bytes(raw[pos + 8:pos + 16], "big")
                rid_s = int.from_bytes(raw[pos + 16:pos + 20], "big")
                rid_e = int.from_bytes(raw[pos + 20:pos + 24], "big")
                plen = int.from_bytes(raw[pos + 24:pos + 28], "big")
                frame = raw[pos + 28:pos + 28 + plen]
                kind = "?"
                ok = False
                publishable = False
                gen = 0
                policy = self.answer_policy
                try:
                    origin, rid, kind, payload = termcodec.decode(frame)
                    if policy is not None and rid_s > 0:
                        # publishability decided BEFORE the handler
                        # (conservative: the watermark checks only
                        # grow) — and the invalidation generation
                        # captured with it, so a clear racing the
                        # handler makes nl_publish drop this answer
                        # instead of resurrecting it into the fresh
                        # table
                        gen = self._lib.nl_pub_gen(self._h)
                        try:
                            publishable = bool(policy(kind, payload))
                        except Exception:  # noqa: BLE001 — the policy
                            # must never fail a request
                            log.exception("answer policy failed (%s)",
                                          kind)
                    reply = self._amo.answer(origin, rid, kind, payload,
                                             self._handler)
                    ok = True
                    if publishable:
                        # the GIL-entry counter per served read: a
                        # request the native table COULD have answered
                        # but that entered the interpreter instead
                        # (native/py is the answer plane's true hit
                        # ratio); counted only on a SERVED answer — a
                        # handler that raised answered nothing
                        stats.registry.fabric_py_answers.inc(kind=kind)
                except Exception as e:  # noqa: BLE001 — must answer
                    if _err_kind(e) == "generic":
                        log.exception("node RPC handler failed (%s)",
                                      kind)
                    reply = termcodec.encode(
                        ("error", _err_kind(e), str(e)))
                # replied IMMEDIATELY, not at batch end: a blocking
                # batch-mate (clock wait, parked duplicate) must not
                # hold finished replies hostage.  The GIL economy is in
                # the batched RECV (one wake per batch); nl_reply is a
                # microsecond C call that costs this timeslice nothing.
                self._lib.nl_reply(self._h, conn_token, corr, reply,
                                   len(reply))
                if ok and publishable:
                    # the request key is the frame with the rid
                    # spliced out (the C++ lookup splices
                    # identically); the published bytes ARE this
                    # reply — a native answer is byte-identical to
                    # the Python handler's
                    key = frame[:rid_s] + frame[rid_e:]
                    # the interned kind id rides along so the event
                    # thread's TEL_EV_ANSWER reports WHICH rpc it
                    # served (interning is a dict hit on the worker
                    # path — never the native answer path)
                    self._lib.nl_publish(
                        self._h, key, len(key), reply, len(reply), gen,
                        nativeobs.kind_interner.id_of(kind))
                pos += 28 + plen

    # ----------------------------------------------------- answer plane

    def invalidate_answers(self) -> None:
        """Drop every published answer — the wholesale invalidation
        for any state change that could make one stale (log
        truncation, ring/ownership moves).  Coarse on purpose: these
        events are rare, re-publication is one Python round per key,
        and a finer-grained map would have to prove which keys a
        truncation touched.  A no-op on a closed endpoint (truncation
        hooks can fire during teardown)."""
        try:
            self._track()
        except LinkDown:
            return
        try:
            self._lib.nl_publish_clear(self._h)
        finally:
            self._untrack()

    def fabric_counters(self) -> dict:
        """{native_answered, published, inq_depth} from the endpoint —
        the native-answer economy's observable face (stats.py FABRIC_*
        gauges and /debug/pipeline pull from here)."""
        out = (ctypes.c_ulonglong * 3)()
        try:
            self._track()
        except LinkDown:
            return {}
        try:
            n = self._lib.nl_counters(self._h, out, 3)
        finally:
            self._untrack()
        keys = ("native_answered", "published", "inq_depth")
        return {k: int(out[i]) for i, k in enumerate(keys[:n])}

    # ------------------------------------------------------ telemetry

    def set_telemetry(self, on: bool) -> None:
        """Flip event recording (Config.native_telemetry; heartbeats
        keep beating either way, so the watchdog still works)."""
        try:
            self._track()
        except LinkDown:
            return
        try:
            self._lib.nl_tel_enable(self._h, 1 if on else 0)
            self._tel_enabled = bool(on)
        finally:
            self._untrack()

    def _tel_probe(self) -> int:
        """Watchdog probe: the ring's last-heartbeat wall-ns (0 =
        endpoint gone).  PyDLL cursor read — atomics only."""
        out = (ctypes.c_ulonglong * 3)()
        try:
            self._track()
        except LinkDown:
            return 0
        try:
            self._lib.nl_tel_cursor(self._h, out, 3)
        finally:
            self._untrack()
        return int(out[2])

    def telemetry_drain(self, max_events: int = _TEL_DRAIN_MAX) -> int:
        """Drain the endpoint's flight-recorder ring into the NATIVE_*
        families; returns the events folded.  Rides the gossip tick /
        /debug/pipeline pulls — never a hot path, and never inside a
        lock region (nl_tel_drain is CDLL class)."""
        try:
            self._track()
        except LinkDown:
            return 0
        try:
            cur = (ctypes.c_ulonglong * 3)()
            self._lib.nl_tel_cursor(self._h, cur, 3)
            head, hb_wall = int(cur[0]), int(cur[2])
            n = 0
            if head != self._tel_tail:
                new_tail = ctypes.c_ulonglong()
                dropped = ctypes.c_ulonglong()
                n = int(self._lib.nl_tel_drain(
                    self._h, self._tel_tail, self._tel_buf,
                    min(max_events, _TEL_DRAIN_MAX),
                    ctypes.byref(new_tail), ctypes.byref(dropped)))
                self._tel_tail = int(new_tail.value)
                self._tel_dropped += int(dropped.value)
                if n > 0:
                    nativeobs.fold_events(
                        nativeobs.decode_events(self._tel_buf, n))
            nativeobs.publish_ring_gauges(
                "nodelink", hb_wall, self._tel_dropped, head,
                self._tel_tail)
            return n
        finally:
            self._untrack()

    def telemetry_info(self) -> dict:
        """The ring's /debug/pipeline face: occupancy, losses,
        heartbeat age (nativeobs-shaped; obs/pipeline.py embeds it)."""
        out = (ctypes.c_ulonglong * 3)()
        try:
            self._track()
        except LinkDown:
            return {}
        try:
            self._lib.nl_tel_cursor(self._h, out, 3)
        finally:
            self._untrack()
        head = int(out[0])
        return {
            "head": head,
            "tail": self._tel_tail,
            "occupancy": min(head - self._tel_tail,
                             nativeobs.RING_CAPACITY),
            "dropped_events": self._tel_dropped,
            "heartbeat_count": int(out[1]),
            "heartbeat_age_s": nativeobs.heartbeat_age_s(int(out[2])),
            "enabled": self._tel_enabled,
        }

    # ------------------------------------------------------------- client

    def connect(self, peer_id, addr: Tuple[str, int]) -> None:
        """Remember a peer's address (the dial is lazy; a dead peer
        surfaces as LinkDown on the first request)."""
        addr = (str(addr[0]), int(addr[1]))
        with self._lock:
            idx = self._peer_idx.get(peer_id)
            if idx is None:
                idx = self._next_idx
                self._next_idx += 1
                self._peer_idx[peer_id] = idx
            if self._peer_addr.get(peer_id) != addr:
                self._peer_addr[peer_id] = addr
                self._lib.nl_set_peer(self._h, idx, addr[0].encode(),
                                      addr[1])

    def peers(self):
        with self._lock:
            return list(self._peer_idx)

    def _next_rid(self) -> Tuple[int, int]:
        with self._lock:
            self._rid += 1
            return (self._boot, self._rid)

    def _track(self):
        with self._inflight_cv:
            if self._closed:
                raise LinkDown("node fabric closed")
            self._inflight += 1

    def _untrack(self):
        with self._inflight_cv:
            self._inflight -= 1
            self._inflight_cv.notify_all()

    def start_request(self, peer_id, kind: str, payload) -> _Handle:
        """Queue a request and return immediately; any number may be in
        flight on one connection (pipelining).  Finish with
        finish_request — every started request MUST be finished or the
        native layer keeps its completion slot until close."""
        with self._lock:
            idx = self._peer_idx.get(peer_id)
        if idx is None:
            raise LinkDown(f"unknown node {peer_id!r}")
        rid = self._next_rid()
        data = termcodec.encode((self.node_id, rid, kind, payload))
        self._track()
        try:
            corr = self._lib.nl_send(self._h, idx, data, len(data))
        finally:
            self._untrack()
        return _Handle(peer_id, idx, data, corr)

    def finish_request(self, h: _Handle, timeout: Optional[float] = None
                       ) -> Any:
        """Collect one started request; transparently retries ONCE with
        the same rid after a transport failure (the peer's at-most-once
        cache answers a duplicate without re-executing)."""
        self._track()
        try:
            return self._finish_request(h, timeout)
        finally:
            self._untrack()

    def _finish_request(self, h: _Handle,
                        timeout: Optional[float] = None) -> Any:
        deadline_ms = int((timeout or self.request_timeout) * 1000)
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        while True:
            if h.corr < 0:  # send refused (unknown peer / closed)
                err = OSError(f"send failed ({h.corr})")
            else:
                # GIL-held probe first: a reply that already landed is
                # consumed without paying the CDLL GIL round trip
                n = self._lib.nl_wait_probe(self._h, h.corr, buf, cap,
                                            0)
                if n == 0:
                    n = self._lib.nl_wait(self._h, h.corr, buf, cap,
                                          deadline_ms)
                if n < -1:
                    cap = -n
                    buf = ctypes.create_string_buffer(cap)
                    continue
                if n > 0:
                    reply = termcodec.decode(ctypes.string_at(buf, n))
                    if reply[0] == "error":
                        _, ekind, msg = reply
                        _raise_remote(ekind, f"{h.peer_id!r}: {msg}")
                    return reply[1]
                if n == 0:
                    # protocol timeout: the link may be stuck — tear it
                    # down so the retry dials fresh
                    self._lib.nl_cancel(self._h, h.corr)
                    self._lib.nl_drop_peer(self._h, h.idx)
                    err = TimeoutError("request timed out")
                else:
                    err = OSError("link failed")
            if h.attempt >= 1:
                raise LinkDown(
                    f"node {h.peer_id!r} unreachable: {err}") from err
            h.attempt += 1
            # re-send the SAME encoded request (same rid): a lost reply
            # is answered from the peer's at-most-once cache
            h.corr = self._lib.nl_send(self._h, h.idx, h.data,
                                       len(h.data))

    def request(self, peer_id, kind: str, payload) -> Any:
        """Synchronous RPC; LinkDown when the peer is unreachable,
        remote exceptions re-raised with their kind preserved."""
        return self.finish_request(self.start_request(peer_id, kind,
                                                      payload))

    def request_many(self, calls: List[Tuple[Any, str, Any]]
                     ) -> List[Tuple[bool, Any]]:
        """Fan out several RPCs concurrently from this one thread and
        collect them in order.  Returns ``(True, value)`` or
        ``(False, exception)`` per call — the caller decides which
        failures abort what (a 2PC prepare round must collect EVERY
        reply before acting, coordinator._fan_out's contract)."""
        handles = [self.start_request(p, k, pl) for p, k, pl in calls]
        return self.finish_many(handles)

    def finish_many(self, handles: List[_Handle]
                    ) -> List[Tuple[bool, Any]]:
        """Collect a fan-out round in ONE native wait: nl_collect blocks
        (GIL-free) until every reply is terminal and returns them all in
        a single buffer — one GIL re-acquisition for the whole round."""
        self._track()
        try:
            return self._finish_many(handles)
        finally:
            self._untrack()

    def _finish_many(self, handles: List[_Handle]
                     ) -> List[Tuple[bool, Any]]:
        out_map: Dict[int, Tuple[bool, Any]] = {}
        pending = [h for h in handles if h.corr > 0]
        if pending:
            # GIL-held probe first: pipelined replies usually ALL
            # arrived while the caller ran its local participants — the
            # whole round then resolves without one CDLL GIL round trip
            pending = self._collect_into(pending, 0, out_map)
        if pending:
            deadline_ms = int(self.request_timeout * 1000)
            pending = self._collect_into(pending, deadline_ms, out_map)
            for h in pending:
                # still pending at the deadline: abandon + tear the
                # link down so the retry below dials fresh
                self._lib.nl_cancel(self._h, h.corr)
                self._lib.nl_drop_peer(self._h, h.idx)
        out: List[Tuple[bool, Any]] = []
        for h in handles:
            got = out_map.get(id(h))
            if got is None:
                # failed / timed out / send refused: the one-retry
                # path (same rid — the peer's at-most-once cache
                # answers a duplicate without re-executing)
                try:
                    got = (True, self._finish_request(h))
                except Exception as e:  # noqa: BLE001 — collected
                    got = (False, e)
            out.append(got)
        return out

    def _collect_into(self, live: List[_Handle], timeout_ms: int,
                      out_map: Dict[int, Tuple[bool, Any]]
                      ) -> List[_Handle]:
        """One nl_collect pass over ``live``: resolved replies land in
        out_map (failures stay absent — the caller's retry path owns
        them); returns the handles still pending.  timeout_ms == 0 uses
        the GIL-held probe binding."""
        n = len(live)
        corrs = (ctypes.c_ulonglong * n)(*[h.corr for h in live])
        fn = (self._lib.nl_collect_probe if timeout_ms == 0
              else self._lib.nl_collect)
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        while True:
            w = fn(self._h, corrs, n, buf, cap, timeout_ms)
            if w < -1:
                cap = -w
                buf = ctypes.create_string_buffer(cap)
                continue
            break
        if w <= 0:
            return list(live)
        raw = ctypes.string_at(buf, w)
        pos = 0
        still = []
        for h in live:
            if pos >= len(raw):
                still.append(h)
                continue
            status = raw[pos]
            plen = int.from_bytes(raw[pos + 1:pos + 5], "big")
            body = raw[pos + 5:pos + 5 + plen]
            pos += 5 + plen
            if status == 0:
                try:
                    reply = termcodec.decode(body)
                    if reply[0] == "error":
                        _, ekind, msg = reply
                        _raise_remote(ekind, f"{h.peer_id!r}: {msg}")
                    out_map[id(h)] = (True, reply[1])
                except Exception as e:  # noqa: BLE001 — collected
                    out_map[id(h)] = (False, e)
            elif status == 2:
                still.append(h)
        return still

    def abandon(self, handles: List[_Handle]) -> None:
        """Forget started requests without collecting them (an error
        elsewhere aborted the round): frees their native completion
        slots; late replies for cancelled ids are dropped by the event
        loop."""
        self._track()
        try:
            for h in handles:
                if h.corr > 0:
                    self._lib.nl_cancel(self._h, h.corr)
        finally:
            self._untrack()

    # ----------------------------------------------------------- shutdown

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        nativeobs.watchdog.unregister(self._tel_name)
        self._lib.nl_shutdown(self._h)
        for t in self._workers:
            t.join(timeout=5.0)
        with self._inflight_cv:
            # client threads parked in waits were failed by nl_shutdown
            # and drain in microseconds; wait them out before freeing
            self._inflight_cv.wait_for(lambda: self._inflight == 0,
                                       timeout=5.0)
            drained = self._inflight == 0
        if not drained or any(t.is_alive() for t in self._workers):
            # a thread is wedged inside a handler or native call;
            # freeing the handle under it would be use-after-free —
            # leak it instead (the shut-down endpoint answers all
            # calls with "closed")
            log.warning("node fabric still in use at close; endpoint "
                        "handle leaked")
        else:
            self._lib.nl_free(self._h)
            self._h = None

"""Node fabric: framed request/response RPC between the OS processes of
ONE data center.

The reference's intra-DC transport is distributed Erlang — synchronous
gen_server calls for vnode commands and metadata broadcast (reference
src/meta_data_sender.erl:241-243, src/stable_meta_data_server.erl:103-135).
Here each node process binds one TCP listener; peers hold a persistent
connection per target, re-dialed once on failure, with typed errors
carried back so a remote certification failure aborts the coordinator's
transaction exactly like a local one.

Framing and codec are shared with the inter-DC fabric
(antidote_tpu/interdc/tcp.py, termcodec.py): 4-byte big-endian length
frames of safe tagged terms — never pickle, even inside one DC (a
compromised node must not get arbitrary code execution on its peers).
"""

from __future__ import annotations

import logging
import os
import socket
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from antidote_tpu.interdc import termcodec
from antidote_tpu.interdc.tcp import _recv_frame, _send_frame
from antidote_tpu.interdc.transport import LinkDown

log = logging.getLogger(__name__)


def _err_kind(exc: Exception) -> str:
    from antidote_tpu.cluster.remote import HandoffParked, WrongOwner
    from antidote_tpu.txn.manager import CertificationError

    if isinstance(exc, CertificationError):
        return "certification"
    if isinstance(exc, WrongOwner):
        return "wrong_owner"
    if isinstance(exc, HandoffParked):
        return "parked"
    if isinstance(exc, TimeoutError):
        return "timeout"
    return "generic"


def _raise_remote(kind: str, msg: str):
    from antidote_tpu.txn.manager import CertificationError

    if kind == "certification":
        raise CertificationError(msg)
    if kind == "timeout":
        raise TimeoutError(msg)
    from antidote_tpu.cluster.remote import (
        HandoffParked,
        RemoteCallError,
        WrongOwner,
    )

    if kind == "wrong_owner":
        raise WrongOwner(msg)
    if kind == "parked":
        raise HandoffParked(msg)
    raise RemoteCallError(msg)


#: replies remembered per origin for at-most-once retries (a retry
#: follows its first attempt immediately, so a small window suffices)
_DEDUP_CAP = 256


class AtMostOnceCache:
    """Server-side at-most-once request cache, shared by the Python
    NodeLink and the native-transport link (cluster/nativelink.py): the
    execute-once / remember-reply semantics are protocol, not transport,
    so both fabrics answer retries identically."""

    def __init__(self, request_timeout: float = 30.0):
        self.request_timeout = request_timeout
        self._lock = threading.RLock()
        #: origin -> {rid: reply bytes | in-flight Event}
        self._seen: Dict[Any, "dict"] = {}

    def answer(self, origin, rid, kind: str, payload,
               handler: Callable[[Any, str, Any], Any]) -> bytes:
        """Run the handler at most once per (origin, rid): a client that
        lost the reply re-sends the same rid on a fresh connection and
        gets the remembered answer, not a re-execution.  A retry that
        lands while the FIRST execution is still running (connection
        dropped mid-handler) parks on its in-flight marker instead of
        re-executing concurrently."""
        with self._lock:
            cache = self._seen.setdefault(origin, {})
            entry = cache.get(rid)
            if isinstance(entry, bytes):
                return entry
            owner = entry is None
            if owner:
                entry = threading.Event()
                cache[rid] = entry
        if not owner:
            # a duplicate while the first execution is still running:
            # park on its marker, then serve the owner's reply
            entry.wait(timeout=self.request_timeout)
            with self._lock:
                got = cache.get(rid)
            if isinstance(got, bytes):
                return got
            from antidote_tpu.cluster.remote import RemoteCallError

            raise RemoteCallError(
                "duplicate request: first execution failed or timed out")
        try:
            result = handler(origin, kind, payload)
            reply = termcodec.encode(("ok", result))
        except Exception:
            with self._lock:
                cache.pop(rid, None)  # errors are not cached (typed
                # protocol errors are deterministic; infra errors should
                # retry fresh)
            entry.set()
            raise
        with self._lock:
            # evict oldest COMPLETED replies only — popping another
            # request's in-flight marker would orphan its waiters
            if len(cache) >= _DEDUP_CAP:
                stale = [k for k, v in cache.items()
                         if isinstance(v, bytes)]
                for k in stale[:len(cache) - _DEDUP_CAP + 1]:
                    cache.pop(k)
            # re-insert at the dict tail: overwriting the in-flight
            # marker in place would leave a SLOW request's reply at its
            # request-START position — first in line for eviction,
            # exactly for the requests most likely to be retried
            cache.pop(rid, None)
            cache[rid] = reply
        entry.set()
        return reply


class NodeLink:
    """One node's endpoint of the DC's node fabric."""

    def __init__(self, node_id, host: str = "127.0.0.1", port: int = 0,
                 connect_timeout: float = 5.0,
                 request_timeout: float = 30.0):
        self.node_id = node_id
        self.host = host
        self._port = port
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self._handler: Optional[Callable[[Any, str, Any], Any]] = None
        self._srv: Optional[socket.socket] = None
        #: peer node_id -> {"addr", "sock", "lock"}
        self._peers: Dict[Any, Dict[str, Any]] = {}
        #: accepted server-side connections (closed on shutdown so a
        #: restarted process can rebind the advertised port)
        self._accepted: List[socket.socket] = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        #: client-side request ids: (boot_token, n).  The token makes
        #: ids unique ACROSS process incarnations — a restarted node
        #: must not collide with its predecessor's entries in peers'
        #: at-most-once caches and be served stale cached replies.
        self._boot = int.from_bytes(os.urandom(8), "big")
        self._rid = 0
        #: server-side at-most-once cache — a reconnecting client
        #: re-sends its last request with the SAME rid; answering from
        #: the cache instead of re-executing keeps non-idempotent RPCs
        #: (stage_update, commit) exactly-once across a lost reply
        self._amo = AtMostOnceCache(request_timeout=request_timeout)

    # ------------------------------------------------------------- server

    def serve(self, handler: Callable[[Any, str, Any], Any]
              ) -> Tuple[str, int]:
        """Bind the listener and answer requests with
        ``handler(origin_node, kind, payload)``; returns the bound
        address for the node's descriptor."""
        self._handler = handler
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self._port))
        srv.listen(64)
        self._srv = srv
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="antidote-nl-accept").start()
        return srv.getsockname()[:2]

    def local_addr(self) -> Tuple[str, int]:
        if self._srv is None:
            raise RuntimeError("serve() first")
        return self._srv.getsockname()[:2]

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            with self._lock:
                self._accepted.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="antidote-nl-serve").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    frame = _recv_frame(conn)
                except ValueError:
                    return
                if frame is None:
                    return
                kind = "?"
                try:
                    origin, rid, kind, payload = termcodec.decode(frame)
                    reply = self._answer(origin, rid, kind, payload)
                except Exception as e:  # noqa: BLE001 — must answer
                    if _err_kind(e) == "generic":
                        log.exception("node RPC handler failed (%s)",
                                      kind)
                    reply = termcodec.encode(
                        ("error", _err_kind(e), str(e)))
                try:
                    _send_frame(conn, reply)
                except OSError:
                    return

    def _answer(self, origin, rid, kind: str, payload) -> bytes:
        return self._amo.answer(origin, rid, kind, payload,
                                self._handler)

    # ------------------------------------------------------------- client

    def connect(self, peer_id, addr: Tuple[str, int]) -> None:
        """Remember a peer's address (the dial is lazy; a dead peer
        surfaces as LinkDown on the first request)."""
        with self._lock:
            peer = self._peers.get(peer_id)
            if peer is None:
                self._peers[peer_id] = {
                    "addr": tuple(addr), "sock": None,
                    "lock": threading.Lock()}
            else:
                peer["addr"] = tuple(addr)

    def peers(self):
        with self._lock:
            return list(self._peers)

    def request(self, peer_id, kind: str, payload) -> Any:
        """Synchronous RPC; LinkDown when the peer is unreachable,
        remote exceptions re-raised with their kind preserved.  The
        retry after a transport error re-sends the SAME request id, so
        the server's at-most-once cache answers without re-executing a
        request whose reply was lost (non-idempotent RPCs stay
        exactly-once)."""
        with self._lock:
            peer = self._peers.get(peer_id)
            self._rid += 1
            rid = (self._boot, self._rid)
        if peer is None:
            raise LinkDown(f"unknown node {peer_id!r}")
        with peer["lock"]:
            for attempt in (0, 1):
                sock = peer["sock"]
                reply = None
                try:
                    if sock is None:
                        sock = socket.create_connection(
                            peer["addr"], timeout=self.connect_timeout)
                        sock.settimeout(self.request_timeout)
                        peer["sock"] = sock
                    _send_frame(sock, termcodec.encode(
                        (self.node_id, rid, kind, payload)))
                    frame = _recv_frame(sock)
                    if frame is None:
                        raise OSError("connection closed mid-request")
                    reply = termcodec.decode(frame)
                except (OSError, ValueError) as e:
                    if peer["sock"] is not None:
                        peer["sock"].close()
                        peer["sock"] = None
                    if attempt == 1:
                        raise LinkDown(
                            f"node {peer_id!r} unreachable: {e}") from e
                    continue
                # raised OUTSIDE the try: TimeoutError subclasses
                # OSError, and a remote protocol timeout must reach the
                # caller typed, not tear the socket down as "unreachable"
                if reply[0] == "error":
                    _, ekind, msg = reply
                    _raise_remote(ekind, f"{peer_id!r}: {msg}")
                return reply[1]

    # ----------------------------------------------------------- shutdown

    def close(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                # wake the thread blocked in accept(): close() alone
                # leaves the kernel file (and the LISTEN entry) alive
                # until the in-syscall accept returns, so a restarted
                # process could never rebind the advertised port
                self._srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._srv.close()
            except OSError:
                pass
        with self._lock:
            for conn in self._accepted:
                try:
                    conn.close()
                except OSError:
                    pass
            self._accepted.clear()
            for peer in self._peers.values():
                if peer["sock"] is not None:
                    peer["sock"].close()
                    peer["sock"] = None

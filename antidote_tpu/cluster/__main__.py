"""Node-process CLI: ``python -m antidote_tpu.cluster <node_id> ...``.

Runs one NodeServer (one OS process of a multi-node DC) until killed —
the rebuild's `bin/antidote start` for a cluster member (reference
release script + antidote_dc_manager staged join).  A coordinator (the
console, a test harness, or another node) pushes the cluster plan via
the "join" RPC; with ``--expect-plan`` the process just serves until
that happens.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m antidote_tpu.cluster")
    ap.add_argument("node_id")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default="antidote_data")
    ap.add_argument("--n-partitions", type=int, default=8)
    ap.add_argument("--heartbeat-s", type=float, default=1.0)
    ap.add_argument("--sync-log", action="store_true")
    args = ap.parse_args(argv)

    # serving fabric RPCs next to local work: the default 5 ms GIL
    # switch interval adds multi-ms scheduling stalls per cross-node
    # round trip
    sys.setswitchinterval(0.0005)

    from antidote_tpu.cluster import NodeServer
    from antidote_tpu.config import Config

    srv = NodeServer(
        args.node_id, host=args.host, port=args.port,
        data_dir=args.data_dir,
        config=Config(n_partitions=args.n_partitions,
                      heartbeat_s=args.heartbeat_s,
                      sync_log=args.sync_log))
    print(f"node {args.node_id} serving on {srv.addr[0]}:{srv.addr[1]}"
          f" (assembled={srv.node is not None})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())
    signal.signal(signal.SIGINT, lambda *_a: stop.set())
    stop.wait()
    srv.close()


if __name__ == "__main__":
    sys.exit(main())

"""Remote partition proxy: the coordinator-facing surface of a
PartitionManager that lives in another OS process of the same DC.

The reference's coordinator reaches any partition through riak_core
vnode dispatch — `riak_core_vnode_master:sync_command` routes to the
owning BEAM node transparently (reference
src/clocksi_vnode.erl:99-209 call sites).  Here the routing is the
ring map (ClusterNode.ring); a partition owned elsewhere is this proxy,
which forwards the exact PartitionManager method over the node fabric.
Typed errors (certification, timeout) survive the hop so 2PC aborts
behave identically local and remote.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from antidote_tpu.clocks import VC


class RemoteCallError(RuntimeError):
    """A remote partition call failed for a non-protocol reason."""


class WrongOwner(RuntimeError):
    """The partition moved to another node (cross-node handoff): the
    caller refreshes its routing and retries — riak_core's forwarding
    window after an ownership transfer."""


class HandoffParked(RuntimeError):
    """The partition is draining for a cutover and new mutating work is
    momentarily refused.  Retryable: the CALLER backs off and re-sends
    (this proxy does so transparently) — refusing instead of parking
    the request server-side keeps the fabric's worker threads free to
    serve the commit/abort traffic the drain is waiting on (advisor
    r04: a blocked-worker park could starve the drain under load)."""


#: PartitionManager methods a peer may invoke — the vnode command set
#: (reads, 2PC, staging, stable-time probes).  A whitelist, not
#: getattr-anything: the fabric is intra-DC but still a network surface.
PARTITION_METHODS = frozenset({
    "read", "read_many", "read_with_writeset", "stage_update",
    "stage_prepare", "stage_single_commit",
    "prepare", "commit", "abort", "single_commit", "min_prepared",
    "value_snapshot",
})


class RemotePartition:
    """Duck-typed stand-in for PartitionManager on non-owned ring slots."""

    #: the coordinator buffers this partition's writeset locally and
    #: ships it WITH prepare / single-commit (one fabric round trip per
    #: remote participant instead of one per update — the reference's
    #: async-append shape, src/clocksi_interactive_coord.erl:514-577)
    deferred_stage = True

    def __init__(self, link, owner_node, partition: int):
        self.link = link
        self.owner = owner_node
        self.partition = partition

    #: client-side backoff while the owner drains for a cutover; the
    #: window is normally a few ms, the deadline mirrors the server's
    #: old 30 s park bound
    _PARK_RETRY_S = 0.005
    _PARK_DEADLINE_S = 30.0

    def _call(self, method: str, *args, **kwargs):
        payload = (self.partition, method, tuple(args), dict(kwargs))
        deadline = None
        redirected = False
        while True:
            try:
                return self.link.request(self.owner, "part", payload)
            except WrongOwner:
                if redirected:
                    raise  # one refresh per call: a ping-pong ring is a bug
                # the partition moved (cross-node handoff): learn the
                # new ring from the node that redirected us, re-aim,
                # retry — riak_core's forwarding after a transfer
                self.refresh_owner()
                redirected = True
            except HandoffParked:
                # drain window: back off client-side and re-send (the
                # server refuses rather than parking a worker thread)
                now = time.monotonic()
                if deadline is None:
                    deadline = now + self._PARK_DEADLINE_S
                elif now > deadline:
                    raise
                time.sleep(self._PARK_RETRY_S)

    def refresh_owner(self) -> None:
        """Re-resolve this slot's owner from the redirecting node's
        current ring and make sure the fabric can dial it."""
        ring_pairs, member_pairs = self.link.request(
            self.owner, "ring", None)
        ring = {int(p): nid for p, nid in ring_pairs}
        members = {nid: tuple(addr) for nid, addr in member_pairs}
        new_owner = ring.get(self.partition)
        if new_owner is None or new_owner == self.owner:
            raise RemoteCallError(
                f"partition {self.partition} has no (new) owner in the "
                f"redirecting node's ring")
        if new_owner in members:
            self.link.connect(new_owner, members[new_owner])
        self.owner = new_owner

    # -- reads ------------------------------------------------------------

    def read(self, key, type_name: str, snapshot_vc: Optional[VC],
             txid=None, exact_state: bool = False) -> Any:
        return self._call("read", key, type_name, snapshot_vc, txid,
                          exact_state=exact_state)

    def read_with_writeset(self, key, type_name: str, snapshot_vc,
                           txid, own_effects: List[Any],
                           exact_state: bool = False) -> Any:
        return self._call("read_with_writeset", key, type_name,
                          snapshot_vc, txid, list(own_effects),
                          exact_state=exact_state)

    def read_many(self, items: List[Tuple[Any, str]], snapshot_vc,
                  txid=None) -> Dict[Tuple[Any, str], Any]:
        return self._call("read_many", [tuple(i) for i in items],
                          snapshot_vc, txid)

    def value_snapshot(self, key, type_name: str,
                       clock: Optional[VC] = None) -> Any:
        return self._call("value_snapshot", key, type_name, clock)

    # -- write path / 2PC -------------------------------------------------

    def stage_update(self, txid, key, type_name: str, effect) -> None:
        self._call("stage_update", txid, key, type_name, effect)

    def stage_prepare(self, txid, ops, snapshot_vc: VC,
                      certify: bool = True) -> int:
        return self._call("stage_prepare", txid,
                          [tuple(o) for o in ops], snapshot_vc, certify)

    def stage_single_commit(self, txid, ops, snapshot_vc: VC,
                            certify: bool = True) -> int:
        return self._call("stage_single_commit", txid,
                          [tuple(o) for o in ops], snapshot_vc, certify)

    def prepare(self, txid, snapshot_vc: VC, certify: bool = True) -> int:
        return self._call("prepare", txid, snapshot_vc, certify)

    def commit(self, txid, commit_time: int, snapshot_vc: VC,
               certified: bool = True) -> None:
        self._call("commit", txid, commit_time, snapshot_vc, certified)

    def single_commit(self, txid, snapshot_vc: VC,
                      certify: bool = True) -> int:
        return self._call("single_commit", txid, snapshot_vc, certify)

    def abort(self, txid) -> None:
        self._call("abort", txid)

    # -- stable plane -----------------------------------------------------

    def min_prepared(self) -> int:
        return self._call("min_prepared")

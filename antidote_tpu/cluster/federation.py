"""Inter-DC replication for a MULTI-NODE DC: each node process runs the
six inter-DC vnode duties for its own ring slice, exactly as the
reference registers the inter_dc vnode types on every BEAM node
(reference src/antidote_app.erl:42-59) and subscribes each node only to
the partitions it owns (src/inter_dc_sub.erl:138-141).

Topology: a federated descriptor carries ONE publisher + log-reader
address per member node and the ring (partition -> member index), so

- each local node subscribes to EVERY remote node's txn stream but
  keeps sub-buffers / dependency gates only for its OWN partitions
  (frames for other slices drop — their owners have their own
  subscriptions), and
- gap-repair queries route to the remote node that owns the partition
  (the reference's per-(DC, partition) REQ socket map,
  src/inter_dc_query.erl:95-130).

Stable time composes two planes: the dep-gate watermarks + min-prepared
of the node's local partitions feed its ClusterStablePlane tracker, the
intra-DC node gossip min-folds the members, and the published snapshot
covers every federated DC's entries — the reference's
partitions x nodes x DCs min cascade (SURVEY §3.4)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from antidote_tpu.api import AntidoteTPU
from antidote_tpu.clocks import VC
from antidote_tpu.interdc import query as idc_query
from antidote_tpu.interdc.dep import DependencyGate, gate_from_config
from antidote_tpu.interdc.interest import interest_from_config
from antidote_tpu.interdc.sender import InterDcLogSender
from antidote_tpu.interdc.sub_buf import SubBuf
from antidote_tpu.interdc.transport import InboxWorker, LinkDown, Transport
from antidote_tpu.interdc.wire import (
    DcDescriptor,
    InterDcBatch,
    InterDcTxn,
    frame_from_bin,
)
from antidote_tpu.obs import pipeline as obs_pipeline
from antidote_tpu.obs.spans import tracer

log = logging.getLogger(__name__)


class FederatedDescriptor:
    """The multi-node DC's membership card: per-member transport
    addresses + the ring, exchanged between DCs (reference
    get_descriptor returns every node's addresses,
    src/inter_dc_manager.erl:49-61)."""

    def __init__(self, dc_id, n_partitions: int,
                 pub_addrs: Tuple, logreader_addrs: Tuple,
                 ring: Tuple):
        self.dc_id = dc_id
        self.n_partitions = n_partitions
        self.pub_addrs = tuple(pub_addrs)            # one per member
        self.logreader_addrs = tuple(logreader_addrs)
        self.ring = tuple(ring)                      # partition -> member

    def member_desc(self, i: int) -> DcDescriptor:
        """Transport-level descriptor for ONE remote member: peers are
        keyed (dc_id, member) so every local node holds a subscription
        and a query channel per remote node."""
        return DcDescriptor(
            dc_id=(self.dc_id, i), n_partitions=self.n_partitions,
            pub_addrs=(self.pub_addrs[i],),
            logreader_addrs=(self.logreader_addrs[i],))

    @property
    def n_members(self) -> int:
        return len(self.pub_addrs)

    def to_wire(self):
        return (self.dc_id, self.n_partitions, self.pub_addrs,
                self.logreader_addrs, self.ring)

    @classmethod
    def from_wire(cls, t):
        return cls(*t)


class NodeInterDc:
    """One node's endpoint of the inter-DC fabric (composes with
    NodeServer after the cluster plan is installed)."""

    def __init__(self, srv, bus: Transport):
        node = srv.node
        if node is None:
            raise RuntimeError("install the cluster plan first")
        self.srv = srv
        self.bus = bus
        self.node = node
        #: client API over this member's node — answers remote
        #: snapshot reads (idc_query.SNAPSHOT_READ) with full ring
        #: routing, locally-owned slices on the read serve plane
        self._api = AntidoteTPU(node=node)
        self.dc_id = node.dc_id
        #: this DC's interest spec (ISSUE 18) — None = full stream.
        #: Every member advertises the SAME spec (it is config-routed),
        #: so a remote DC's per-member subscriptions slice identically.
        self.interest = interest_from_config(node.config)
        self.member_index = sorted(srv.plane.members,
                                   key=repr).index(srv.node_id)
        self.local = set(node.local_partition_indices())
        #: senders tap this node's local appends (one per owned slice)
        self.senders: Dict[int, InterDcLogSender] = {}
        for p in sorted(self.local):
            pm = node.partitions[p]
            # config routes the ship knobs through (the gate_from_config
            # lesson: federated senders must honor interdc_ship too)
            sender = InterDcLogSender(self.dc_id, p, bus, enabled=False,
                                      config=node.config)
            sender.seed_watermark(pm.log.op_counters.get(self.dc_id, 0))
            pm.log.on_append = (
                lambda rec, _s=sender: _s.on_append(rec))
            self.senders[p] = sender
            # checkpoint-truncation retention floor (ISSUE 10): same
            # wiring as DataCenter's — ship watermark with peers, else
            # unconstrained
            pm.log.retention_opid_source = (
                lambda _s=sender: _s.last_sent_opid if self.remote
                else None)
        #: dependency gates for owned slices; their watermarks feed the
        #: node's stable tracker
        self.gates: Dict[int, DependencyGate] = {}
        for p in sorted(self.local):
            g = gate_from_config(node.partitions[p], self.dc_id,
                                 node.clock.now_us, node.config)
            g.seed_clock(node.partitions[p].log.max_commit_vc)
            self.gates[p] = g
        #: (origin dc, partition) -> SubBuf, owned slices only
        self.sub_bufs: Dict[Tuple[Any, int], SubBuf] = {}
        #: remote dc -> FederatedDescriptor
        self.remote: Dict[Any, FederatedDescriptor] = {}
        self._rx_lock = threading.Lock()
        self._inbox = bus.register(self._self_desc(), self._handle_query)
        if self.interest is not None:
            # advertised per member key — remote senders cut this
            # node's slice; a transport without the hook would silently
            # ship full streams, so a spec'd member demands it loudly
            bus.set_local_interest((self.dc_id, self.member_index),
                                   self.interest)
        self._worker = InboxWorker(self._inbox, self._deliver)
        self._hb = None
        # stable sources: gate watermarks + own min-prepared per slice.
        # Installed as the NodeServer's source FACTORY (not a one-shot
        # sources list): a cross-node handoff rebuilds the stable plane,
        # and the rebuild must keep pulling the dep-gate watermarks or
        # the DC snapshot could pass un-applied remote transactions.
        srv.source_factory = self._source_for
        srv.plane.local.sources = [
            self._source_for(p) for p in sorted(self.local)]
        srv.on_ring_change = self.refresh_ring
        node.wait_hook = self._wait_hook
        # restart re-join: re-observe the federations this node knew
        # (reference check_node_restart reconnects its DCs,
        # src/inter_dc_manager.erl:156-201)
        for t in (srv.meta.get("federated_descriptors") or []):
            try:
                self.observe_dc(FederatedDescriptor.from_wire(t))
            except Exception:  # noqa: BLE001 — a dead peer at boot
                log.warning("restart re-observe of %r failed", t[0])
        # the pipeline snapshot plane sees federated members too (one
        # entry per member, keyed "dcid[member]" — obs/pipeline.py)
        obs_pipeline.register(self)

    def _source_for(self, p: int):
        def pull():
            g = self.gates.get(p)
            pm = self.node.partitions[p]
            if g is None:
                # a just-adopted slice whose gate is still being wired
                # (refresh_ring runs right after the plane rebuild):
                # the log's per-DC commit maxima are its conservative
                # applied watermarks
                return VC(pm.log.max_commit_vc).set_dc(
                    self.dc_id, pm.min_prepared())
            return VC(g.applied_vc).set_dc(
                self.dc_id, pm.min_prepared())
        return pull

    def refresh_ring(self) -> None:
        """Adopt a re-planned ring (cross-node handoff): wire senders,
        dependency gates, and sub-buffers for newly-owned slices,
        retire those of de-owned slices.  Stream continuity holds
        because the transferred log carries the per-origin opid
        counters — the new owner's sender resumes the SAME opid stream
        remote sub-buffers are watching, and its sub-buffers resume at
        the watermarks the old owner had applied."""
        node = self.node
        with self._rx_lock:
            new_local = set(node.local_partition_indices())
            for p in sorted(new_local - self.local):
                pm = node.partitions[p]
                sender = InterDcLogSender(self.dc_id, p, self.bus,
                                          enabled=bool(self.remote),
                                          config=node.config)
                sender.seed_watermark(
                    pm.log.op_counters.get(self.dc_id, 0))
                pm.log.on_append = (
                    lambda rec, _s=sender: _s.on_append(rec))
                self.senders[p] = sender
                pm.log.retention_opid_source = (
                    lambda _s=sender: _s.last_sent_opid if self.remote
                    else None)
                g = gate_from_config(pm, self.dc_id,
                                     node.clock.now_us, node.config)
                g.seed_clock(pm.log.max_commit_vc)
                self.gates[p] = g
                for dc_id in self.remote:
                    if self.interest is not None:
                        g.note_subscription(dc_id,
                                            len(self.interest.ranges))
                    self.sub_bufs[(dc_id, p)] = SubBuf(
                        dc_id, p,
                        deliver=self._make_gate_deliver(p),
                        deliver_batch=self._make_gate_deliver_batch(p),
                        fetch_range=self._fetch_range,
                        bootstrap=self._bootstrap_from_ckpt,
                        last_opid=pm.log.op_counters.get(dc_id, 0),
                        filtered=self.interest is not None)
            for p in sorted(self.local - new_local):
                gone = self.senders.pop(p, None)
                if gone is not None:
                    gone.close()
                self.gates.pop(p, None)
                for dc_id in list(self.remote):
                    self.sub_bufs.pop((dc_id, p), None)
            self.local = new_local
        # the plane was just rebuilt by the NodeServer with this
        # object's source factory, so the gate watermarks are already
        # wired for the new slice set — nothing further here

    # ---------------------------------------------------------- membership

    def _self_desc(self) -> DcDescriptor:
        """This NODE's transport registration (keyed (dc, member))."""
        return DcDescriptor(
            dc_id=(self.dc_id, self.member_index),
            n_partitions=self.node.config.n_partitions)

    def local_addrs(self) -> Tuple:
        """(pub, logreader) addresses of this node's bus endpoint."""
        addrs = self.bus.local_addrs()
        if addrs is None:
            key = (self.dc_id, self.member_index)
            return (key, key)
        return (addrs[0][0], addrs[1][0])

    def observe_dc(self, desc: FederatedDescriptor) -> None:
        """Subscribe this node to EVERY member of the remote DC
        (reference observe_dc connects each local node to all remote
        nodes, src/inter_dc_manager.erl:87-109)."""
        if desc.dc_id == self.dc_id:
            return
        if desc.dc_id in self.remote:
            # already subscribed (e.g. restart re-observe + a manual
            # call): refresh the descriptor, keep the live buffers
            self.remote[desc.dc_id] = desc
            return
        if desc.n_partitions != self.node.config.n_partitions:
            raise ValueError(
                f"{desc.dc_id!r} has {desc.n_partitions} partitions, "
                f"local DC has {self.node.config.n_partitions}")
        my_key = (self.dc_id, self.member_index)
        for i in range(desc.n_members):
            self.bus.connect(my_key, desc.member_desc(i))
        for p in sorted(self.local):
            if self.interest is not None:
                # the dep gate's stable-time qualifier (ISSUE 18):
                # this origin's stream is a partial subscription
                self.gates[p].note_subscription(
                    desc.dc_id, len(self.interest.ranges))
            self.sub_bufs[(desc.dc_id, p)] = SubBuf(
                desc.dc_id, p,
                deliver=self._make_gate_deliver(p),
                deliver_batch=self._make_gate_deliver_batch(p),
                fetch_range=self._fetch_range,
                bootstrap=self._bootstrap_from_ckpt,
                last_opid=self.node.partitions[p].log.op_counters.get(
                    desc.dc_id, 0),
                filtered=self.interest is not None)
        self.remote[desc.dc_id] = desc
        for s in self.senders.values():
            s.enabled = True
        # persist for restart re-observe
        kept = [t for t in
                (self.srv.meta.get("federated_descriptors") or [])
                if t[0] != desc.dc_id]
        self.srv.meta.put("federated_descriptors",
                          kept + [desc.to_wire()])

    # --------------------------------------------------------- background

    def start(self) -> None:
        """Delivery worker + heartbeat ticker.  Heartbeats must tick
        continuously: a partition that receives no real txns only
        advances its remote clock entries through pings, and the stable
        snapshot is the min over ALL partitions (reference
        start_bg_processes, src/inter_dc_manager.erl:112-145)."""
        self._worker.start()
        if self._hb is None:
            from antidote_tpu.interdc.dc import _Ticker

            self._hb = _Ticker(self.node.config.heartbeat_s,
                               self.tick_heartbeats)
            self._hb.start()

    def tick_heartbeats(self) -> None:
        """Per-slice min-prepared pings (reference 1 s ping,
        src/inter_dc_log_sender_vnode.erl:133-143)."""
        for p, sender in self.senders.items():
            sender.ping(self.node.partitions[p].min_prepared())

    def pump(self) -> int:
        return self._worker.pump()

    def _wait_hook(self) -> None:
        self.pump()
        time.sleep(0.002)

    # ------------------------------------------------------------ inbound

    def _deliver(self, data: bytes) -> None:
        try:
            frame = frame_from_bin(data)
        except ValueError:
            log.warning("dropping malformed inter-DC frame (%d bytes)",
                        len(data))
            return
        with self._rx_lock:
            if frame.partition not in self.local:
                return  # another member's slice: its owner handles it
            buf = self.sub_bufs.get((frame.dc_id, frame.partition))
            if buf is None:
                return
            if isinstance(frame, InterDcBatch):
                tracer.adopt_from_wire(frame.trace_hdr, frame.txns())
                for txn in frame.txns():
                    tracer.instant(
                        "interdc_rx", "interdc",
                        txid=getattr(txn.records[-1], "txid", None),
                        origin=str(frame.dc_id),
                        partition=frame.partition)
                buf.process_batch(frame.delivery_txns())
                return
            if not frame.is_ping():
                if frame.trace_ctx is not None:
                    tracer.adopt_from_wire((frame.trace_ctx[1], 0),
                                           [frame])
                tracer.instant(
                    "interdc_rx", "interdc",
                    txid=getattr(frame.records[-1], "txid", None),
                    origin=str(frame.dc_id), partition=frame.partition)
            buf.process(frame)

    def _make_gate_deliver(self, p: int):
        def deliver(txn: InterDcTxn) -> None:
            self.gates[p].enqueue(txn)
        return deliver

    def _make_gate_deliver_batch(self, p: int):
        def deliver_batch(txns: List[InterDcTxn]) -> None:
            self.gates[p].enqueue_batch(txns)
        return deliver_batch

    def _fetch_range(self, origin_dc, partition: int, first: int,
                     last: int) -> Optional[List[InterDcTxn]]:
        """Gap repair routed to the remote NODE owning the partition
        (the descriptor's ring)."""
        desc = self.remote.get(origin_dc)
        if desc is None:
            return None
        target = (origin_dc, desc.ring[partition])
        my_key = (self.dc_id, self.member_index)
        payload = ((partition, first, last) if self.interest is None
                   else (partition, first, last, self.interest.ranges))
        try:
            # the transport returns decoded InterDcTxn objects (termcodec
            # on TCP, live objects in-process) — same contract as
            # idc_query.fetch_log_range
            return self.bus.request(my_key, target, idc_query.LOG_READ,
                                    payload)
        except LinkDown:
            return None

    def _bootstrap_from_ckpt(self, origin_dc, partition: int
                             ) -> Optional[int]:
        """BELOW_FLOOR escalation (ISSUE 10), federated form: the
        CKPT_READ routes to the remote MEMBER owning the partition
        (the descriptor's ring) and the seeds install into this
        member's local slice — mirrors DataCenter._bootstrap_from_ckpt."""
        desc = self.remote.get(origin_dc)
        if desc is None or partition not in self.local:
            return None
        target = (origin_dc, desc.ring[partition])
        my_key = (self.dc_id, self.member_index)
        payload = ((partition,) if self.interest is None
                   else (partition, self.interest.ranges))
        try:
            ans = self.bus.request(my_key, target, idc_query.CKPT_READ,
                                   payload)
        except LinkDown:
            return None
        if ans is None:
            return None
        return idc_query.install_ckpt_bootstrap(
            self.node.partitions[partition], self.gates[partition],
            origin_dc, partition, ans)

    # ------------------------------------------------------------ queries

    def _handle_query(self, from_dc, kind: str, payload) -> Any:
        if kind == idc_query.LOG_READ:
            if len(payload) == 4:
                # the ranged form (ISSUE 18): a filtered subscriber's
                # backfill — the 3-tuple stays the pre-upgrade shape
                partition, first, last, ranges = payload
            else:
                partition, first, last = payload
                ranges = None
            if partition not in self.local:
                owner = self.node.ring.get(partition)
                if owner is not None and owner != self.srv.node_id:
                    # the slice moved (cross-node handoff) after the
                    # remote DC cached our descriptor: forward over the
                    # node fabric to the current owner and relay its
                    # answer — repair keeps routing across re-plans,
                    # and the ranged form forwards verbatim
                    bins = self.srv.link.request(
                        owner, "idc_log_read",
                        (partition, first, last) if ranges is None
                        else (partition, first, last, ranges))
                    if idc_query.is_below_floor(bins):
                        # the owner reclaimed the range: relay the
                        # explicit marker so the requester escalates
                        # to the checkpoint bootstrap instead of
                        # reading a decode crash as a dead peer
                        tracer.instant("interdc_repair_relay",
                                       "interdc", partition=partition,
                                       first=first, last=last,
                                       below_floor=True)
                        return bins
                    tracer.instant("interdc_repair_relay", "interdc",
                                   partition=partition, first=first,
                                   last=last, frames=len(bins))
                    return [InterDcTxn.from_bin(b) for b in bins]
                raise ValueError(
                    f"partition {partition} not owned by member "
                    f"{self.member_index} of {self.dc_id!r}")
            pm = self.node.partitions[partition]
            return pm.scan_log(
                lambda lg: idc_query.answer_log_read(
                    lg, self.dc_id, partition, first, last,
                    ranges=ranges))
        if kind == idc_query.SNAPSHOT_READ:
            objects, clock = payload
            # the federated remote-read leg (ISSUE 8): any member can
            # answer — partitions this node does not own route over
            # the node fabric (RemotePartition) inside the read, and
            # locally-owned slices serve through the read serve plane
            tracer.instant("interdc_snapshot_read", "interdc",
                           origin=str(from_dc), keys=len(objects))
            return idc_query.answer_snapshot_read(
                self._api, objects, clock)
        if kind == idc_query.CKPT_READ:
            if len(payload) == 2:
                partition, ranges = payload  # ranged form (ISSUE 18)
            else:
                (partition,) = payload
                ranges = None
            if partition not in self.local:
                raise ValueError(
                    f"partition {partition} not owned by member "
                    f"{self.member_index} of {self.dc_id!r}")
            tracer.instant("interdc_ckpt_read", "interdc",
                           origin=str(from_dc), partition=partition)
            return idc_query.answer_ckpt_read(
                self.node.partitions[partition], self.dc_id, partition,
                ranges=ranges)
        if kind == idc_query.CHECK_UP:
            return True
        raise ValueError(f"unknown inter-DC query kind {kind!r}")

    def close(self) -> None:
        obs_pipeline.unregister(self)
        if self._hb is not None:
            self._hb.stop()
            self._hb = None
        for s in self.senders.values():
            s.close()
        self._worker.stop()
        self.bus.unregister((self.dc_id, self.member_index))


def dc_descriptor(members: List[NodeInterDc]) -> FederatedDescriptor:
    """Assemble one DC's federated descriptor from its members'
    endpoints + the shared ring."""
    members = sorted(members, key=lambda n: n.member_index)
    node = members[0].node
    order = sorted(members[0].srv.plane.members, key=repr)
    ring = tuple(order.index(node.ring[p])
                 for p in range(node.config.n_partitions))
    addrs = [m.local_addrs() for m in members]
    return FederatedDescriptor(
        node.dc_id, node.config.n_partitions,
        tuple(a[0] for a in addrs), tuple(a[1] for a in addrs), ring)


def connect_federation(dcs: List[List[NodeInterDc]], sync: bool = True,
                       timeout: float = 30.0) -> None:
    """Full-mesh federation of multi-node DCs: every node of every DC
    observes every other DC's full membership, then (sync) waits until
    each node's stable snapshot covers every federated DC — the
    connect_cluster + observe_dcs_sync flow at multi-node scale
    (reference src/inter_dc_manager.erl:209-230)."""
    descs = [dc_descriptor(members) for members in dcs]
    for members in dcs:
        for nid in members:
            for desc in descs:
                nid.observe_dc(desc)  # skips its own DC
            nid.start()
    if not sync:
        return
    want = {d.dc_id for d in descs}
    deadline = time.monotonic() + timeout
    while True:
        for members in dcs:
            for nid in members:
                nid.tick_heartbeats()
                nid.pump()
                nid.srv.gossip_tick()
        done = all(
            all(nid.srv.plane.get_stable_snapshot().get_dc(dc) > 0
                for dc in want - {nid.dc_id})
            for members in dcs for nid in members)
        if done:
            return
        if time.monotonic() > deadline:
            raise TimeoutError("federation never stabilized")
        time.sleep(0.001)

"""ClusterNode + NodeServer: one DC spanning several OS processes.

Roles, mapped from the reference:

- **ClusterNode** — the riak_core placement duty: a ring maps every
  partition index to an owning node; this process instantiates real
  PartitionManagers for its slice and RemotePartition proxies for the
  rest, so the unchanged Coordinator transparently spans nodes exactly
  as `riak_core_vnode_master` routes vnode commands across BEAM nodes
  (reference src/clocksi_vnode.erl:99-209 call sites).
- **ClusterStablePlane** — the cross-node half of the stable-time
  protocol: each node min-folds its own partitions (meta_data_sender's
  per-node merge, reference src/meta_data_sender.erl:224-255), gossips
  the summary to every peer, stores peer summaries
  (meta_data_manager's remote-node table, src/meta_data_manager.erl:
  64-94), and publishes the min-of-mins monotonically; a member that
  has never reported pins the snapshot to zero (reference
  src/stable_time_functions.erl:78-85).
- **NodeServer** — the per-process assembly + antidote_dc_manager's
  staged join (reference src/antidote_dc_manager.erl:53-81): nodes
  boot empty, a coordinator pushes the cluster plan (ring + member
  addresses) to each, every node persists it and assembles; a
  restarted process reloads the plan, recovers its partitions from
  their logs, and re-joins the gossip.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from antidote_tpu.clocks import VC, vc_min
from antidote_tpu.cluster.link import NodeLink
from antidote_tpu.cluster.remote import (
    PARTITION_METHODS,
    RemoteCallError,
    RemotePartition,
)
from antidote_tpu.config import Config
from antidote_tpu.meta.gossip import StableTimeTracker
from antidote_tpu.meta.sender import MetaDataSender
from antidote_tpu.meta.stable_store import StableMetaData
from antidote_tpu.oplog.log import _fsync_dir
from antidote_tpu.txn.manager import PartitionManager, PartitionRetired
from antidote_tpu.txn.node import Node

log = logging.getLogger(__name__)

#: partition methods parked during a handoff drain: NEW mutating work.
#: Reads and the commit/abort calls resolving already-prepared
#: transactions keep flowing — the drain needs them to finish.
_HANDOFF_PARKED = frozenset({
    "stage_update", "stage_prepare", "stage_single_commit",
    "prepare", "single_commit",
})


def build_link(node_id, host: str = "127.0.0.1", port: int = 0,
               config: Optional[Config] = None):
    """The DC's node-fabric endpoint, routed by ``Config.fabric_native``
    (the ONE construction path — the gate_from_config discipline):
    "auto" picks the native IO plane when built (C++ event loop,
    GIL-free waits, pipelined requests, the published-answer plane —
    cluster/nativelink.py) and falls back to the pure-Python NodeLink;
    True requires native and fails loudly without a compiler; False
    keeps the exact legacy NodeLink path.  Both speak the same
    termcodec payloads over different wire framings, so every member
    of one cluster must pick the same plane — which they do, by
    sharing the Config default and the same build environment."""
    cfg = config or Config()
    if cfg.fabric_native not in ("auto", True, False):
        # fail loudly: treating an unknown value as "auto" would route
        # e.g. fabric_native="python" (a plausible guess at the legacy
        # knob) to the NATIVE plane — the opposite of the request
        raise ValueError(
            f"Config.fabric_native must be 'auto', True, or False "
            f"(got {cfg.fabric_native!r})")
    if cfg.fabric_native is not False:
        from antidote_tpu.cluster import nativelink

        if nativelink.native_available():
            link = nativelink.NativeNodeLink(
                node_id, host=host, port=port,
                workers=cfg.fabric_workers)
            if not cfg.native_telemetry:
                # heartbeats keep beating with recording off, so the
                # stall watchdog still covers this endpoint
                link.set_telemetry(False)
            return link
        if cfg.fabric_native is True:
            raise RuntimeError(
                "Config.fabric_native=True but the native node fabric "
                "is unavailable (no C++ toolchain); install g++ or "
                "set fabric_native to 'auto'/False")
        log.warning("native node fabric unavailable; falling back to "
                    "the Python NodeLink")
    return NodeLink(node_id, host=host, port=port)


def plan_ring(n_partitions: int, node_ids: List[Any]) -> Dict[int, Any]:
    """Round-robin partition placement — the cluster plan the reference
    computes via riak_core claim (reference antidote_dc_manager's
    plan/commit staged join).  Every member must own at least one
    partition: a slotless member would contribute an eternally-bottom
    stable summary, pinning the DC's snapshot at zero."""
    if n_partitions < len(node_ids):
        raise ValueError(
            f"{len(node_ids)} members need >= {len(node_ids)} "
            f"partitions (got {n_partitions}): a member owning no "
            "partition pins the cluster stable snapshot at zero")
    ids = sorted(node_ids, key=repr)
    return {p: ids[p % len(ids)] for p in range(n_partitions)}


class ClusterNode(Node):
    """A Node owning only its ring slice; other slots are RPC proxies."""

    def __init__(self, node_id, ring: Dict[int, Any], link: NodeLink,
                 dc_id="dc1", config: Optional[Config] = None,
                 data_dir: Optional[str] = None, on_log_append=None):
        if sorted(ring) != list(range(len(ring))):
            raise ValueError("ring must map every partition 0..N-1")
        self.node_id = node_id
        self.ring = dict(ring)
        self.link = link
        cfg = config or Config()
        cfg.n_partitions = len(ring)
        super().__init__(dc_id=dc_id, config=cfg, data_dir=data_dir,
                         on_log_append=on_log_append)

    def _build_partition(self, p: int):
        if self.ring[p] == self.node_id:
            return super()._build_partition(p)
        return RemotePartition(self.link, self.ring[p], p)

    def _local_partitions(self) -> List[PartitionManager]:
        return [pm for pm in self.partitions
                if isinstance(pm, PartitionManager)]

    def local_partition_indices(self) -> List[int]:
        return [p for p, owner in sorted(self.ring.items())
                if owner == self.node_id]

    def mint_dot(self) -> Tuple[Any, int]:
        """Dots are NODE-scoped in a multi-node DC: the device plane's
        per-actor-column max-seq collapse needs same-column dots minted
        under ONE monotone clock in observation order, which only this
        process's clock guarantees (Node.mint_dot documents the single-
        node argument).  Cross-node same-key commits still serialize at
        the key's owner partition, so per-column collapse stays sound
        per column; cross-column concurrency is what ORSWOT handles
        anyway."""
        return ((self.dc_id, self.node_id), self.clock.now_us())

    def repartition(self, new_n: int) -> None:
        raise RuntimeError(
            "repartition of a multi-node DC is a cluster-level plan "
            "(every member folds its slice against the new ring); "
            "use NodeServer.resize_cluster, or resize single-node DCs "
            "directly")

    def build_resize_fold(self, new_n: int, own_slot=None):
        """LiveFold over THIS member's ring slice only.  ``own_slot``
        is not accepted here — the slice IS the filter, and silently
        substituting it for a caller's would stage the wrong slots.
        Restricted to
        integer growth factors: with new_n = m * old_n the key routing
        satisfies q ≡ p (mod old_n) for every key of old partition p
        (k % new_n ≡ k % old_n mod old_n, crc32 alike), so each
        partition splits IN PLACE into m children on its current owner
        and no data crosses nodes during the resize — ownership moves
        afterwards with the ordinary rebalance/handoff (the riak_core
        plan/claim separation, reference
        src/antidote_dc_manager.erl:53-81)."""
        if own_slot is not None:
            raise ValueError(
                "a ClusterNode's fold slice is its ring slice; "
                "own_slot cannot be overridden")
        old_n = self.config.n_partitions
        if new_n % old_n:
            raise ValueError(
                f"multi-node resize must grow by an integer factor "
                f"({old_n} -> {new_n}); children of a partition must "
                f"stay on its owner")
        return super().build_resize_fold(
            new_n,
            own_slot=lambda q: self.ring[q % old_n] == self.node_id)


class ClusterStablePlane:
    """Two-level stable time: local partition fold + node-summary gossip.

    ``member_ids`` are the DATA members (ring owners) only: the
    min-of-mins is over nodes that actually hold partitions.  A
    coordinator-only member (see NodeServer's client role) neither
    contributes a summary nor pins the snapshot — it just receives
    peer summaries and reads the merged view."""

    def __init__(self, dc_id, node_id, member_ids: List[Any],
                 local: StableTimeTracker):
        self.dc_id = dc_id
        self.node_id = node_id
        self.members = sorted(member_ids, key=repr)
        self._idx = {nid: i for i, nid in enumerate(self.members)}
        self.local = local
        self.sender = MetaDataSender()
        self.sender.register(
            "stable_nodes", len(self.members), initial=lambda: None,
            merge=self._merge_nodes,
            publish=lambda prev, new: new if prev is None
            else prev.join(new))

    def _merge_nodes(self, vals: List[Optional[VC]]) -> VC:
        if any(v is None for v in vals):
            # an unheard-from member pins every column to zero — the
            # published view stays at its previous floor (monotone)
            return VC()
        return vc_min(vals)

    def put_node(self, node_id, vc: VC) -> None:
        """Store one node's summary (gossip receive side); per-source
        entries never regress."""
        i = self._idx.get(node_id)
        if i is None:
            log.warning("gossip from unknown node %r ignored", node_id)
            return
        self.sender.update(
            "stable_nodes", i,
            lambda cur: vc if cur is None else cur.join(vc))

    def local_summary(self) -> VC:
        """This node's contribution: the min-fold over its partitions.
        A coordinator-only member has none — nothing to record."""
        s = self.local.get_stable_snapshot()
        if self.node_id in self._idx:
            self.put_node(self.node_id, s)
        return s

    def get_stable_snapshot(self) -> VC:
        self.local_summary()
        return VC(self.sender.merged("stable_nodes"))

    def seed_floor(self, vc: VC) -> None:
        self.local.seed_floor(vc)


class NodeServer:
    """One OS process of a multi-node DC: fabric endpoint, cluster-plan
    persistence, gossip ticker, and the client API once assembled."""

    def __init__(self, node_id, host: str = "127.0.0.1", port: int = 0,
                 data_dir: str = ".", config: Optional[Config] = None):
        self.node_id = node_id
        self.config = config or Config()
        if self.config.tune_process:
            # this process serves a node: GC + GIL knobs.  Embedders
            # opt out with Config(tune_process=False) — the tuning
            # mutates process-global interpreter state.
            from antidote_tpu.runtime import tune_runtime

            tune_runtime()
        os.makedirs(data_dir, exist_ok=True)
        self.data_dir = data_dir
        self.meta = StableMetaData(
            os.path.join(data_dir, f"node_{node_id}_meta.pkl"),
            recover=self.config.recover_meta_data_on_start)
        plan = self.meta.get("cluster_plan")
        if plan is not None and port == 0:
            # a restarted member must come back at its ADVERTISED
            # address: peers' persisted member tables (and federated
            # descriptors) point there, and a fresh random port would
            # leave their gossip/RPC dialing a dead socket forever
            planned = dict(plan[2]).get(node_id)
            if planned is not None:
                host, port = planned
        self.link = build_link(node_id, host=host, port=port,
                               config=self.config)
        # native-plane flight recorder (ISSUE 16): the stall threshold
        # is process-global like stats.registry — every node in one
        # process shares the Config default anyway
        from antidote_tpu.obs import nativeobs

        nativeobs.watchdog.threshold_s = self.config.native_watchdog_s
        # every attribute _handle touches must exist BEFORE serve():
        # a restarting member's peers dial the advertised address the
        # moment it binds, and a gossip arriving mid-__init__ used to
        # AttributeError (which also put the SENDER on its 2 s
        # backoff, delaying the restarted member's stable view)
        self.node: Optional[ClusterNode] = None
        self.api = None
        self.plane: Optional[ClusterStablePlane] = None
        self._gossip: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._assembled = threading.Event()
        #: peer -> monotonic time before which gossip skips it
        self._peer_backoff: Dict[Any, float] = {}
        #: member id -> advertised address (the committed plan's view)
        self._members: Dict[Any, Tuple[str, int]] = {}
        #: cross-node handoff state per partition (outbound side):
        #: {"state": "drain" | "retired" | "in_doubt", "new_owner"}
        self._handoff: Dict[int, dict] = {}
        #: inbound install state per partition: serializes
        #: handoff_install vs. handoff_probe and carries the probe's
        #: cancel fence (see _handoff_in_entry)
        self._handoff_in: Dict[int, dict] = {}
        self._handoff_in_lock = threading.Lock()
        #: partitions handed off but not yet re-planned globally: their
        #: stable contribution stays PINNED at the transfer's commit
        #: watermark VC (own entry: max own-DC commit; remote entries:
        #: the applied-replication watermarks) so the DC snapshot can
        #: pass neither a commit the new owner is still preparing nor
        #: a remote txn it has not applied (see handoff_cutover)
        self._stable_pins: Dict[int, VC] = {}
        #: stable-source builder per local partition; the federation
        #: layer (cluster/federation.py) swaps in gate-aware sources so
        #: a plane rebuild never drops the dep-gate watermarks
        self.source_factory: Optional[Callable[[int], Callable]] = None
        #: called after any ring/ownership change (handoff install,
        #: cutover, re-plan) — the federation layer re-wires its
        #: per-partition senders/gates/sub-buffers here
        self.on_ring_change: Optional[Callable[[], None]] = None
        #: cluster-resize state: the LiveFold built by resize_prepare
        #: (consumed by resize_commit) and the parking flag that
        #: refuses part RPCs while this member's width is mid-change
        self._resize_fold = None
        self._resize_ring = None
        # PARKED BEFORE THE FABRIC BINDS when restarting mid-resize:
        # a peer still routing at the old partition width must not
        # land a key on a wrong-width partition in the window between
        # serve() and the marker check below (the gate freeze itself
        # needs the assembled node and follows)
        self._resize_parking = (
            self.meta.get("cluster_resize") is not None)
        self.addr = self.link.serve(self._handle)
        plan = self.meta.get("cluster_plan")
        if plan is not None:
            # restart: a node-level resize journal means this member
            # was killed between its fold swap and the plan persist —
            # the journaled width wins; expand the plan's ring to it
            # (children inherit their parent's owner) before assembly
            plan = self._reconcile_resized_plan(plan)
            # reload the committed plan and re-join (reference
            # check_node_restart, src/inter_dc_manager.erl:156-201)
            self._assemble(*plan)
            self._resume_handoff_out()
            if self._resize_parking:
                # killed mid-cluster-resize: come back FROZEN (part
                # RPCs were already parked before the fabric bound) —
                # serving at this member's width while peers may hold
                # another would split key routing; the driver's
                # resize_cluster re-run finishes and unfreezes
                self.node.txn_gate.freeze()
                log.warning(
                    "%r restarted mid-cluster-resize: parked until the "
                    "resize is re-driven to completion", node_id)

    def _reconcile_resized_plan(self, plan):
        from antidote_tpu.txn.node import (
            read_resize_journal,
            resize_journal_path,
        )

        dc_id, ring, members = plan
        parsed = read_resize_journal(
            resize_journal_path(self.data_dir, dc_id))
        if parsed is None:
            return plan
        old_n, new_n = parsed
        if len(ring) == old_n:
            ring = {q: ring[q % old_n] for q in range(new_n)}
            plan = (dc_id, ring, members)
            self.meta.put("cluster_plan", plan)
        return plan

    # ------------------------------------------------------------ lifecycle

    def descriptor(self) -> Tuple[Any, Tuple[str, int]]:
        return (self.node_id, self.addr)

    def fabric_kind(self) -> str:
        """Which wire framing this node's fabric speaks ("native" =
        corr-id frames via nodelink.cpp, "python" = plain NodeLink
        frames).  The two do not interoperate: a plan must never mix
        them — one member silently falling back (no compiler) would
        strand every RPC to it in decode errors."""
        return "native" if hasattr(self.link, "finish_many") else \
            "python"

    def install_cluster(self, dc_id, ring: Dict[int, Any],
                        members: Dict[Any, Tuple[str, int]],
                        fabric: Optional[str] = None,
                        clients: Optional[List[Any]] = None) -> None:
        """Commit the cluster plan on this node (the staged-join
        plan/commit step).  Persisted first: a crash between commit and
        assembly re-runs assembly at the next boot.

        ``fabric`` is the plan author's fabric kind: a mismatch with
        this node's refuses the join LOUDLY instead of assembling a
        member nobody can talk to.  ``clients`` lists the members that
        are INTENDED to be coordinator-only (client role): they hold
        RemotePartition proxies for the whole ring and run transactions
        without owning data — the riak_core pattern of coordinating
        from any node while vnodes live on the ring (reference
        src/antidote_dc_manager.erl nodes vs ring claim).  The list is
        explicit so a member that was MEANT to own data but got no ring
        slot (an operator sizing mistake) still fails loudly."""
        if self.node is not None:
            raise RuntimeError("node already belongs to a cluster")
        if self.node_id not in members:
            raise ValueError(f"plan does not include {self.node_id!r}")
        if fabric is not None and fabric != self.fabric_kind():
            raise RuntimeError(
                f"fabric mismatch: plan requires {fabric!r} but "
                f"{self.node_id!r} runs {self.fabric_kind()!r} (native "
                "fabric unavailable here? fix the build or set "
                "Config.fabric_native=False cluster-wide)")
        owners = set(ring.values())
        if not owners <= set(members):
            raise ValueError(
                f"every ring owner must be a member (owners {owners!r} "
                f"vs members {set(members)!r})")
        slotless = set(members) - owners
        declared = set(clients or ())
        if slotless != declared:
            raise ValueError(
                f"members without ring slots {sorted(slotless, key=repr)!r} "
                f"must exactly match the declared client members "
                f"{sorted(declared, key=repr)!r} — a data member left "
                "without a slot is a plan error, not a silent demotion")
        self.meta.put("cluster_plan", (dc_id, dict(ring), dict(members)))
        self._assemble(dc_id, dict(ring), dict(members))

    def _assemble(self, dc_id, ring, members) -> None:
        from antidote_tpu.api import AntidoteTPU

        self._members = {nid: tuple(addr)
                         for nid, addr in members.items()}
        for nid, addr in self._members.items():
            if nid != self.node_id:
                self.link.connect(nid, addr)
        node = ClusterNode(self.node_id, ring, self.link, dc_id=dc_id,
                           config=self.config, data_dir=self.data_dir)
        self.node = node
        last = self.meta.get("last_stable_vc")
        self._install_stable_plane(
            prev_stable=VC(last) if last else None)
        node.wait_hook = self._wait_hook
        self.api = AntidoteTPU(node=node)
        self._refresh_fabric_plane()
        self._gossip = threading.Thread(target=self._gossip_loop,
                                        daemon=True,
                                        name="antidote-nl-gossip")
        self._gossip.start()
        self._assembled.set()
        self.meta.mark_started()

    # ------------------------------------------------ native answer plane

    def _refresh_fabric_plane(self) -> None:
        """(Re)arm the native answer plane (ISSUE 12) over the CURRENT
        ring slice: drop every published answer (ownership or log
        layout may have moved under them) and re-wire the truncation
        hooks so a checkpoint truncation — the one event that can
        change bytes a published idc_log_read / handoff_fetch answer
        was cut from — clears the table.  A no-op on the Python
        NodeLink (no native endpoint to publish into)."""
        link = self.link
        if not hasattr(link, "invalidate_answers"):
            return
        link.invalidate_answers()
        link.answer_policy = self._fabric_answer_policy
        if self.node is not None:
            for pm in self.node._local_partitions():
                pm.log.on_truncate = self._invalidate_fabric_answers

    def _invalidate_fabric_answers(self) -> None:
        if hasattr(self.link, "invalidate_answers"):
            self.link.invalidate_answers()

    def _fabric_answer_policy(self, kind: str, payload) -> bool:
        """Which successfully-answered node RPCs may be published for
        GIL-free native repeats.  The bar is DETERMINISM AT THE SERVED
        STATE: the answer must stay byte-valid until an invalidation
        event (_refresh_fabric_plane / the truncation hook) clears it.

        - ``snap_read`` at an EXPLICIT clock: Clock-SI fixes the value
          set at a covered clock forever (later commits stamp higher);
          a clockless read serves the moving stable snapshot — never
          published.
        - ``idc_log_read`` whose range is fully below this DC's commit
          watermark: the log is append-only and new commits mint
          HIGHER opids, so a fully-past range's answer is immutable —
          until truncation reclaims it, which clears the table.
        - ``handoff_fetch``: log bytes at an offset are immutable
          modulo truncation (cleared); a stale ``end`` only makes the
          puller stage less before the cutover's tail push — safe.
        - ``ring`` / ``check_up``: constant between ring changes,
          which re-arm the plane.
        """
        try:
            if kind == "check_up":
                return True
            if kind == "ring":
                return self.node is not None
            if kind == "snap_read":
                return payload[1] is not None
            if self.node is None:
                return False
            if kind == "idc_log_read":
                # the ranged 4-tuple (ISSUE 18) is publishable too: the
                # ranges are part of the payload key and a fully-past
                # filtered answer is just as immutable
                p, _first, last = payload[:3]
                pm = self.node.partitions[int(p)]
                return (isinstance(pm, PartitionManager)
                        and pm.log.enabled
                        and int(last) <= pm.log.op_counters.get(
                            self.node.dc_id, 0))
            if kind == "handoff_fetch":
                pm = self.node.partitions[int(payload[0])]
                return isinstance(pm, PartitionManager)
        except (TypeError, ValueError, IndexError, KeyError):
            return False
        return False

    def _install_stable_plane(self, prev_stable: Optional[VC] = None
                              ) -> None:
        """(Re)build the two-level stable plane from the CURRENT ring:
        sources for the locally-owned partitions, plus pinned entries
        for partitions handed off but not yet globally re-planned.
        ``prev_stable`` seeds both the local floor and every data
        member's summary entry — the previous published snapshot is the
        min over all members, so it is a sound (conservative) starting
        summary for each, and it keeps the published view monotone
        across the rebuild."""
        node = self.node
        dc_id = node.dc_id
        local_idx = node.local_partition_indices()
        # under ring placement the local fold itself is a device
        # collective: each local row sits on its partition's GLOBAL
        # ring chip (meta/device_stable.py); pinned rows ride the same
        # mapping.  Cross-node stays gossip — on a multi-host pod the
        # mesh spans the hosts and the collective spans the DC.
        from antidote_tpu.meta.device_stable import make_stable_tracker

        placement = None
        if node.config.device_placement == "ring":
            import jax

            n_devs = len(jax.devices())
            if n_devs > 1:
                placement = [p % n_devs for p in local_idx] + [
                    p % n_devs for p in sorted(self._stable_pins)]
        tracker = make_stable_tracker(
            node.config, dc_id,
            len(local_idx) + len(self._stable_pins),
            placement=placement)

        def _default_source(p):
            pm = node.partitions[p]
            return lambda: VC({dc_id: pm.min_prepared()})

        mk = self.source_factory or _default_source
        sources = [mk(p) for p in local_idx]
        for p in sorted(self._stable_pins):
            sources.append(lambda _v=self._stable_pins[p]: _v)
        tracker.sources = sources
        data_members = sorted(set(node.ring.values()), key=repr)
        plane = ClusterStablePlane(dc_id, self.node_id,
                                   data_members, tracker)
        if prev_stable:
            plane.seed_floor(prev_stable)
            for m in data_members:
                plane.put_node(m, prev_stable)
        node.stable_vc_provider = plane.get_stable_snapshot
        self.plane = plane

    def _wait_hook(self) -> None:
        # a causal wait is released by PEER summaries arriving at their
        # gossip cadence — nothing to push from here, and dialing peers
        # synchronously would stall the 2ms spin behind connect
        # timeouts when one is down
        self._stop.wait(0.002)

    # -------------------------------------------------------------- gossip

    def _gossip_loop(self) -> None:
        period = self.config.cluster_gossip_s
        if period is None:
            period = self.config.heartbeat_s
        while not self._stop.wait(period):
            try:
                self.gossip_tick()
            except Exception:  # noqa: BLE001 — the ticker must not die
                log.exception("gossip tick failed")

    def gossip_tick(self) -> None:
        """Broadcast this node's summary to every peer (reference
        meta_data_sender loop, src/meta_data_sender.erl:224-255); an
        unreachable peer is skipped — its entry simply stops advancing,
        holding the published snapshot, until it returns.  A peer that
        just failed is backed off for a few seconds so one dead member's
        connect timeouts don't delay the live members' gossip."""
        if self.plane is None:
            return
        if self.node_id not in self.plane._idx:
            # coordinator-only member: nothing to contribute — its
            # stable view fills from the data members' broadcasts
            return
        summary = self.plane.local_summary()
        now = time.monotonic()
        peers = [p for p in self.link.peers()
                 if self._peer_backoff.get(p, 0) <= now]
        if hasattr(self.link, "request_many"):
            # pipelined broadcast (ISSUE 12): every peer's gossip
            # frame rides the native endpoint concurrently and the
            # round collects in ONE GIL-free wait — a slow peer costs
            # its own timeout, not a serial convoy ahead of the rest
            try:
                results = self.link.request_many(
                    [(p, "gossip", (self.node_id, summary))
                     for p in peers])
            except Exception:  # noqa: BLE001 — closing endpoint
                return
            for peer, (ok, _val) in zip(peers, results):
                if ok:
                    self._peer_backoff.pop(peer, None)
                else:
                    self._peer_backoff[peer] = now + 2.0
        else:
            for peer in peers:
                try:
                    self.link.request(peer, "gossip",
                                      (self.node_id, summary))
                    self._peer_backoff.pop(peer, None)
                except Exception:  # noqa: BLE001 — down peer
                    self._peer_backoff[peer] = now + 2.0
        self._refresh_fabric_gauges()
        self._native_telemetry_tick()

    def _refresh_fabric_gauges(self, counters=None) -> None:
        """Pull the C++ endpoint's answer-plane counters into the
        FABRIC_* gauges (native answers never enter Python, so nothing
        Python-side can increment a Counter for them); rides the
        gossip cadence, plus every /debug/pipeline read — which passes
        its already-pulled dict so one snapshot feeds both the section
        and the gauges (one ctypes crossing, no disagreement)."""
        if counters is None:
            pull = getattr(self.link, "fabric_counters", None)
            if pull is None:
                return
            counters = pull()
        from antidote_tpu import stats

        c = counters
        if "native_answered" in c:
            stats.registry.fabric_native_answered.set(
                c["native_answered"])
        if "published" in c:
            stats.registry.fabric_published.set(c["published"])

    def _native_telemetry_tick(self) -> None:
        """Drain the node link's flight-recorder ring into the NATIVE_*
        families and run the stall watchdog (ISSUE 16).  Rides the
        gossip cadence like the fabric gauges — never a hot path; the
        fabric hub's ring drains on the transport's own 50 ms gauge
        cadence instead."""
        from antidote_tpu.obs import nativeobs

        drain = getattr(self.link, "telemetry_drain", None)
        if drain is not None:
            try:
                drain()
            except Exception:  # noqa: BLE001 — closing endpoint
                pass
        nativeobs.watchdog.check()

    # ----------------------------------------------------------- RPC server

    def _handle(self, origin, kind: str, payload) -> Any:
        if kind == "check_up":
            return True
        if kind == "join":
            dc_id, ring_pairs, member_pairs = payload[:3]
            fabric = payload[3] if len(payload) > 3 else None
            clients = list(payload[4]) if len(payload) > 4 else None
            self.install_cluster(
                dc_id, {int(p): nid for p, nid in ring_pairs},
                {nid: tuple(addr) for nid, addr in member_pairs},
                fabric=fabric, clients=clients)
            return True
        if kind == "gossip":
            nid, vc = payload
            if self.plane is not None:
                self.plane.put_node(nid, vc)
            return None
        if kind == "part":
            self._require_serving()
            p, method, args, kwargs = payload
            return self._part_call(origin, int(p), method, args,
                                   kwargs)
        if kind == "part_batch":
            # per-owner batched 2PC round: one frame carries a whole
            # member's share of the fan-out (prepare/commit/abort...)
            # with ELEMENT-WISE results — a certification conflict on
            # one partition must not mask the others' replies
            self._require_serving()
            from antidote_tpu.cluster.link import _err_kind

            (calls,) = payload
            out = []
            for p, method, args, kwargs in calls:
                try:
                    out.append((True, self._part_call(
                        origin, int(p), method, args, kwargs)))
                except Exception as e:  # noqa: BLE001 — element-wise
                    ek = _err_kind(e)
                    if ek == "generic":
                        # a lone "part" failure logs its traceback in
                        # the fabric worker; a batched element must
                        # stay as diagnosable
                        log.exception(
                            "part_batch element failed "
                            "(p=%s %s from %r)", p, method, origin)
                    out.append((False, (ek, str(e))))
            return out
        if kind == "part_multi":
            # per-owner batched read: ONE fabric round trip carries a
            # whole member's share of a multi-partition read, answered
            # by the fused per-chip fold (txn/manager.read_many_fused)
            # — the remote mirror of the coordinator's local fusion
            self._require_serving()
            groups_payload, snapshot_vc, txid = payload
            groups = []
            for p, items in groups_payload:
                p = int(p)
                st = self._handoff.get(p)
                if st is not None and st["state"] != "drain":
                    # reads flow during a drain (matching "part");
                    # retired/in_doubt refuse for the WHOLE batch —
                    # the caller heals partition by partition
                    self._handoff_refusal(p, st)
                pm = self.node.partitions[p]
                if not isinstance(pm, PartitionManager):
                    raise RemoteCallError(
                        f"partition {p} not owned by "
                        f"{self.node_id!r} (stale ring at {origin!r}?)")
                groups.append((pm, [tuple(i) for i in items]))
            from antidote_tpu.mat.serve import read_groups

            try:
                # the owner-side serve plane (mat/serve.py): a peer's
                # batched read coalesces with this member's own local
                # readers; read_serve=False keeps the fused per-chip
                # fold (txn/manager.read_many_fused) exactly
                return read_groups(groups, snapshot_vc, txid)
            except PartitionRetired:
                # raced a cutover mid-batch: refuse; the caller's
                # per-partition fallback self-heals each slot
                from antidote_tpu.cluster.remote import HandoffParked

                raise HandoffParked(
                    "partition draining for handoff") from None
        if kind == "ring":
            if self.node is None:
                raise RemoteCallError("node not assembled yet")
            return (list(self.node.ring.items()),
                    list(self._members.items()))
        if kind == "idc_log_read":
            # intra-DC forward of a federated gap-repair query: a
            # remote DC with a pre-handoff descriptor asked the wrong
            # member; the partition's CURRENT owner answers from its
            # log (see federation._handle_query).  Fully-past ranges
            # are publishable for native repeats (the answer plane's
            # gap-repair leg — O(range) preads off the PR-8 index,
            # repeats served without the GIL).
            from antidote_tpu.interdc import query as idc_query

            p, first, last = payload[:3]
            ranges = payload[3] if len(payload) == 4 else None
            pm = self.node.partitions[int(p)]
            if not isinstance(pm, PartitionManager):
                raise RemoteCallError(f"partition {p} not local")
            ans = pm.scan_log(
                lambda lg: idc_query.answer_log_read(
                    lg, self.node.dc_id, int(p), first, last,
                    ranges=ranges))
            if idc_query.is_below_floor(ans):
                # the explicit reclaimed-range marker must survive the
                # relay verbatim — a crash here would turn a loud
                # BELOW_FLOOR into a generic repair failure and hide
                # the checkpoint-bootstrap escalation from the peer
                return ans
            return [t.to_bin() for t in ans]
        if kind == "snap_read":
            # one-shot causal read at a clock over the node fabric —
            # the intra-cluster SNAPSHOT_READ (interdc/query.py) leg:
            # any member answers (non-owned slices route over the
            # fabric inside the read), and explicit-clock answers are
            # publishable — a repeat (probe rounds, a retried client)
            # is served by the C++ event thread with the GIL never
            # taken
            self._require_serving()
            from antidote_tpu.interdc import query as idc_query

            objects, clock = payload
            return idc_query.answer_snapshot_read(
                self.api, objects, clock)
        if kind == "handoff_fetch":
            p, offset, max_bytes = payload
            pm = self.node.partitions[p]
            if not isinstance(pm, PartitionManager):
                raise RemoteCallError(f"partition {p} not local")
            # the truncation base rides along (ISSUE 10): byte cursors
            # are PHYSICAL file offsets now, and a checkpoint
            # truncation rewrites the file — the puller must detect a
            # mid-copy rewrite and restart, or its concatenation
            # carries a silent CRC seam recovery would truncate at.
            # The base is sampled BEFORE and AFTER the read: a
            # truncation during the read would otherwise label old-
            # layout bytes with the new base and defeat the check.
            for _ in range(5):
                b0 = self._log_trunc_base(pm)
                data, end = pm.log.read_bytes(int(offset),
                                              int(max_bytes))
                if self._log_trunc_base(pm) == b0:
                    return data, end, b0
            raise RemoteCallError(
                f"partition {p}: log kept truncating under the fetch")
        if kind == "handoff_ckpt":
            # the checkpoint as part of the transfer unit (ISSUE 13):
            # manifest + immutable segments ship as one bundle, so a
            # truncated donor's receiver recovers FULL state instead
            # of suffix-only.  Segments never mutate, so no
            # truncation-epoch dance — the puller pairs the bundle
            # with a base-epoch re-check instead.
            (p,) = payload
            pm = self.node.partitions[int(p)]
            if not isinstance(pm, PartitionManager):
                raise RemoteCallError(f"partition {p} not local")
            if not pm.log.enabled or pm.log.ckpt is None:
                return None
            return pm.log.ckpt.ship_bundle()
        if kind == "ckpt_manifest":
            # streamed transfer, first message (ISSUE 19): manifest
            # bytes + the ordered segment list the receiver's cursor
            # walks.  None when the slot has no (valid) checkpoint.
            (p,) = payload
            pm = self.node.partitions[int(p)]
            if not isinstance(pm, PartitionManager):
                raise RemoteCallError(f"partition {p} not local")
            if not pm.log.enabled or pm.log.ckpt is None:
                return None
            return pm.log.ckpt.bundle_manifest()
        if kind == "ckpt_segs":
            # streamed transfer, segment batch: raw bytes per name
            # (None for a segment compacted away since the manifest —
            # the receiver re-pulls the fresh manifest and resumes).
            # The batch size is the RECEIVER's window; the donor just
            # answers what it is asked.
            p, names = payload
            pm = self.node.partitions[int(p)]
            if not isinstance(pm, PartitionManager):
                raise RemoteCallError(f"partition {p} not local")
            if not pm.log.enabled or pm.log.ckpt is None:
                return [None for _ in names]
            return [pm.log.ckpt.read_segment_raw(n) for n in names]
        if kind == "handoff_begin":
            p, from_owner = payload
            return self._handoff_begin(int(p), from_owner)
        if kind == "handoff_install":
            p, base_offset, tail = payload
            return self._handoff_install(int(p), int(base_offset), tail)
        if kind == "handoff_probe":
            (p,) = payload
            return self._handoff_probe(int(p))
        if kind == "handoff_settle":
            p, new_owner = payload
            return self._handoff_settle(int(p), new_owner)
        if kind == "handoff_cutover":
            p, new_owner, b_cursor = payload[0], payload[1], payload[2]
            b_base = int(payload[3]) if len(payload) > 3 else None
            return self._handoff_cutover(int(p), new_owner,
                                         int(b_cursor), b_base)
        if kind == "ring_update":
            ring_pairs, member_pairs, clients = payload
            self._apply_ring_update(
                {int(p): nid for p, nid in ring_pairs},
                {nid: tuple(addr) for nid, addr in member_pairs},
                list(clients))
            return True
        if kind == "resize_prepare":
            new_n, max_passes, delta_threshold = payload
            return self._resize_prepare(int(new_n), int(max_passes),
                                        int(delta_threshold))
        if kind == "resize_freeze":
            (new_n,) = payload
            return self._resize_freeze(int(new_n))
        if kind == "resize_drain":
            self.node.txn_gate.wait_idle(timeout=60.0)
            return True
        if kind == "resize_commit":
            (new_n,) = payload
            return self._resize_commit(int(new_n))
        if kind == "resize_finish":
            return self._resize_finish()
        if kind == "resize_abort":
            return self._resize_abort()
        if kind == "status":
            return {
                "node_id": self.node_id,
                "assembled": self.node is not None,
                "local_partitions":
                    self.node.local_partition_indices()
                    if self.node else [],
                "ring": sorted(self.node.ring.items())
                    if self.node else [],
                "stable": dict(self.plane.get_stable_snapshot())
                    if self.plane else {},
            }
        raise RemoteCallError(f"unknown node RPC kind {kind!r}")

    # ----------------------------------------------------- cross-node handoff

    def _require_serving(self) -> None:
        """Shared partition-RPC admission guard (part / part_multi /
        part_batch): a member must be assembled, and while its
        partition WIDTH is mid-change a peer still routing at the old
        width would land keys on the wrong partition — refuse
        retryably until the resize finishes cluster-wide."""
        if self.node is None:
            raise RemoteCallError("node not assembled yet")
        if self._resize_parking:
            from antidote_tpu.cluster.remote import HandoffParked

            raise HandoffParked(
                f"cluster resize in progress at {self.node_id!r}")

    def _part_call(self, origin, p: int, method: str, args, kwargs):
        """One partition-method dispatch with the full handoff-state
        discipline — shared by the "part" RPC and each element of a
        "part_batch" frame."""
        if method not in PARTITION_METHODS:
            raise RemoteCallError(f"method {method!r} not allowed")
        st = self._handoff.get(p)
        if st is not None and (st["state"] != "drain"
                               or method in _HANDOFF_PARKED):
            # mutating work during a drain is refused with a RETRYABLE
            # error — the proxy backs off and re-sends; refusing
            # instead of parking keeps every fabric worker free for
            # the reads and commit/abort traffic the drain itself is
            # waiting on (advisor r04)
            self._handoff_refusal(p, st)
        pm = self.node.partitions[p]
        if not isinstance(pm, PartitionManager):
            raise RemoteCallError(
                f"partition {p} not owned by {self.node_id!r} "
                f"(stale ring at {origin!r}?)")
        try:
            rs = getattr(pm, "read_server", None)
            if method == "read_many" and rs is not None and rs.enabled:
                # the remote-read leg of the serve plane (ISSUE 8):
                # a peer's per-partition fallback read (coordinator
                # _read_groups_fallback) coalesces with this owner's
                # local readers instead of buying its own fold.  The
                # proxy marshals txid POSITIONALLY (cluster/remote.py
                # read_many) — dropping it would make the waiter's own
                # prepared entry look foreign and lose trace joins
                txid = args[2] if len(args) > 2 else kwargs.get("txid")
                return rs.read_many(args[0], args[1], txid=txid)
            return getattr(pm, method)(*args, **kwargs)
        except PartitionRetired:
            # this call raced the cutover's drain refusal: it passed
            # the state check above before drain was set, then hit
            # the retired flag under pm._lock — map by the CURRENT
            # handoff state instead of silently losing the append
            # (advisor r04 TOCTOU)
            self._handoff_refusal(p, self._handoff.get(p))

    def _handoff_refusal(self, p: int, st: Optional[dict]):
        """Raise the typed refusal for a partition in handoff state
        ``st`` — shared by the pre-dispatch check and the
        PartitionRetired race path.  Retired -> WrongOwner redirect;
        in_doubt -> hard error; draining (or state unknown: the race
        hit between the retire flag and the state update) -> a
        retryable backoff, because the ring still names this node
        until the install completes and a WrongOwner redirect would
        dead-end in refresh_owner."""
        from antidote_tpu.cluster.remote import HandoffParked, WrongOwner

        state = st["state"] if st else None
        if state == "retired":
            raise WrongOwner(
                f"partition {p} moved to {st['new_owner']!r}") from None
        if state == "in_doubt":
            raise RemoteCallError(
                f"partition {p} ownership in doubt (transfer to "
                f"{st['new_owner']!r} unresolved)") from None
        raise HandoffParked(
            f"partition {p} draining for handoff") from None

    def _rpc(self, target, kind: str, payload):
        """Fabric request, or a direct local dispatch when the target
        is this node (the rebalance driver addresses every member
        uniformly)."""
        if target == self.node_id:
            return self._handle(self.node_id, kind, payload)
        return self.link.request(target, kind, payload)

    def _staged_path(self, p: int) -> str:
        return self.node._log_path(p) + ".handoff"

    def _handoff_in_entry(self, p: int) -> dict:
        """Receiver-side per-partition install state: a lock that
        serializes install vs. probe, and the probe's cancel flag."""
        with self._handoff_in_lock:
            return self._handoff_in.setdefault(
                int(p), {"lock": threading.Lock(), "cancelled": False})

    @staticmethod
    def _log_trunc_base(pm) -> int:
        """The partition log's truncation base (0 when logging is off)
        — the handoff byte-stream's layout epoch: a change means the
        file was rewritten under the physical cursors."""
        return pm.log.log.truncated_base if pm.log.enabled else 0

    def _pull_bundle_streamed(self, p: int, from_owner):
        """Segment-granular checkpoint pull (ISSUE 19): manifest
        first, then segments in batches bounded by
        Config.ckpt_stream_window_bytes — the receiver never holds
        more than one window of un-staged bytes in flight
        (backpressure), every validated segment is durably staged and
        acked in a :class:`BundleCursor`, and a torn fetch or a donor
        kill resumes at the first un-acked segment instead of
        refetching the whole bundle.  A donor compacting mid-stream
        answers a segment fetch with None: the fresh manifest is
        re-adopted (``begin`` counts the discarded progress) and the
        walk continues.  Returns the fully-acked cursor — committed
        by _handoff_install AFTER the log promotion — or None when
        the donor has no checkpoint.  Raises RemoteCallError when the
        donor predates the streamed kinds (caller falls back to the
        one-shot bundle) or when the pull cannot converge."""
        from antidote_tpu import stats
        from antidote_tpu.oplog.checkpoint import (
            BundleCursor,
            retry_bounded,
        )

        window = int(getattr(self.node.config,
                             "ckpt_stream_window_bytes", 4 << 20))
        cur = BundleCursor(self.node._log_path(p) + ".ckpt")

        def pull_manifest():
            stats.registry.stream_manifest_fetches.inc()
            return self._rpc(from_owner, "ckpt_manifest", (p,))

        # the FIRST manifest pull runs unretried so a pre-upgrade
        # donor's unknown-kind error reaches the caller immediately
        man = pull_manifest()
        if man is None:
            return None
        cur.begin(man["manifest"])
        strikes = 0
        while True:
            todo = cur.pending()
            if not todo:
                return cur
            batch, acc = [], 0
            for name, _k, nb in todo:
                if batch and acc + int(nb) > window:
                    break
                batch.append(name)
                acc += int(nb)
            raws = retry_bounded(
                lambda names=tuple(batch): self._rpc(
                    from_owner, "ckpt_segs", (p, list(names))),
                attempts=5,
                what=(f"partition {p}: segment batch pull "
                      f"from {from_owner!r}"),
                counter=stats.registry.ckpt_seg_pull_retries,
                base_delay_s=0.002, exceptions=(RemoteCallError,))
            stale = False
            before = cur.acked_segments()
            try:
                for name, raw in zip(batch, raws):
                    if raw is None:
                        stale = True  # compacted away mid-stream
                        break
                    cur.offer(name, raw)
            except ValueError as e:
                # torn fetch: offer refused it un-acked (counted in
                # STREAM_TORN_FETCHES) — re-pull the same batch
                log.warning("partition %d: %s", p, e)
                stats.registry.ckpt_seg_pull_retries.inc()
            if stale:
                man = retry_bounded(
                    pull_manifest, attempts=5,
                    what=(f"partition {p}: manifest re-pull "
                          f"from {from_owner!r}"),
                    counter=stats.registry.ckpt_seg_pull_retries,
                    base_delay_s=0.002, exceptions=(RemoteCallError,))
                if man is None:
                    cur.discard()
                    return None  # donor dropped its checkpoint
                cur.begin(man["manifest"])
            # only NON-progress rounds (torn fetch, donor compaction)
            # count toward the abort bound — a large bundle legally
            # takes hundreds of clean windows
            strikes = 0 if cur.acked_segments() > before else strikes + 1
            if strikes > 8:
                cur.discard()
                raise RemoteCallError(
                    f"partition {p}: streamed checkpoint pull from "
                    f"{from_owner!r} kept losing to torn fetches or "
                    "donor compaction; retry the handoff")

    def _handoff_begin(self, p: int, from_owner):
        """Receiving side, serving phase: pull the partition's log in
        chunks from the current owner into a staged file, re-pulling
        until the remaining delta is small (the riak_core handoff fold
        while the vnode keeps serving, reference
        src/logging_vnode.erl:781-812).  Returns (staged cursor,
        truncation base the copy is consistent with); the final tail
        arrives pushed by the owner's cutover, which re-verifies the
        base — a checkpoint truncation rewrites the log file, and a
        cursor from the old layout concatenated with new-layout bytes
        would hand recovery a silent CRC seam (everything after it
        silently truncated at the receiver)."""
        if self.meta.get("cluster_resize") is not None:
            raise RemoteCallError(
                "cluster resize in progress; no handoff may start")
        ent = self._handoff_in_entry(p)
        with ent["lock"]:
            # a fresh staging round supersedes any cancel a previous
            # attempt's settlement probe left behind
            ent["cancelled"] = False
        staged = self._staged_path(p)
        for _attempt in range(5):
            cursor = 0
            base = None
            restart = False
            with open(staged, "wb") as f:
                while True:
                    ans = self._rpc(from_owner, "handoff_fetch",
                                    (p, cursor, 4 << 20))
                    # a pre-truncation owner answers (data, end) with
                    # no base — its log is never rewritten, so base 0
                    # is exact (same mixed-version tolerance as the
                    # cutover's len(payload) > 3 check)
                    data, end, b = ans if len(ans) == 3 else (*ans, 0)
                    if base is None:
                        base = int(b)
                    elif int(b) != base:
                        restart = True  # rewritten mid-copy: rebuild
                        break
                    if data:
                        f.write(data)
                        cursor += len(data)
                    if end - cursor <= 65536:
                        break
                f.flush()
                os.fsync(f.fileno())
            if restart:
                continue
            # checkpoint-shipping (ISSUE 13): pull the donor's
            # manifest + segments AFTER the byte copy, then re-check
            # the layout epoch — unchanged base means no truncation
            # landed since the copy, so the bundle's cut is >= the
            # staged base, and the cutover's own b_base check extends
            # that guarantee to the pushed tail (the final file always
            # contains the cut).  With Config.ckpt_stream (ISSUE 19)
            # the pull is segment-granular and cursor-resumable; a
            # donor predating the streamed kinds falls back to the
            # one-shot bundle below.  A pre-ISSUE-13 owner answers
            # THAT with an unknown-kind error too: proceed without a
            # bundle — the transferred log recovers by full scan
            # exactly as before (suffix-only, loudly, if truncated).
            bundle = None
            ckpt_cursor = None
            one_shot = not getattr(self.node.config, "ckpt_stream",
                                   True)
            if not one_shot:
                try:
                    ckpt_cursor = self._pull_bundle_streamed(
                        p, from_owner)
                except RemoteCallError as e:
                    if "unknown node RPC kind" in str(e):
                        one_shot = True  # pre-ISSUE-19 donor
                    else:
                        log.warning(
                            "partition %d: streamed checkpoint pull "
                            "from %r failed (%s); proceeding without "
                            "a bundle — a truncated donor's below-cut "
                            "history will NOT transfer",
                            p, from_owner, e)
                except ValueError as e:  # torn manifest: same stance
                    log.warning(
                        "partition %d: streamed checkpoint pull from "
                        "%r refused (%s); proceeding without a bundle",
                        p, from_owner, e)
            if one_shot:
                for pull in range(3):
                    try:
                        bundle = self._rpc(from_owner, "handoff_ckpt",
                                           (p,))
                        break
                    except RemoteCallError as e:
                        if "unknown node RPC kind" in str(e):
                            # pre-upgrade donor: it cannot ship
                            log.info(
                                "partition %d: donor %r predates "
                                "checkpoint shipping; receiver will "
                                "recover by full scan", p, from_owner)
                            break
                        if pull == 2:
                            # a TRANSIENT failure must not silently
                            # ship no bundle — that re-opens the
                            # truncated-donor suffix-only hole this
                            # transfer unit exists to close; loud, and
                            # the epoch re-check below still gates
                            # consistency
                            log.warning(
                                "partition %d: checkpoint-bundle pull "
                                "from %r failed 3x (%s); proceeding "
                                "without it — a truncated donor's "
                                "below-cut history will NOT transfer",
                                p, from_owner, e)
            ans = self._rpc(from_owner, "handoff_fetch",
                            (p, cursor, 0))
            b_now = int(ans[2]) if len(ans) == 3 else 0
            if b_now != int(base or 0):
                if ckpt_cursor is not None:
                    ckpt_cursor.discard()
                continue  # truncated since the copy: re-stage
            ent["ckpt_bundle"] = bundle
            ent["ckpt_cursor"] = ckpt_cursor
            return cursor, int(base or 0)
        raise RemoteCallError(
            f"partition {p}: log kept truncating under the handoff "
            "pre-copy; pause checkpoint truncation and re-drive")

    def _handoff_install(self, p: int, base_offset: int,
                         tail: bytes) -> bool:
        """Receiving side, cutover: append the owner-pushed tail to the
        staged log, promote it to the live log path, and adopt the
        partition (build + recover + serve).  The local plan persists
        immediately: if this node restarts before the global re-plan,
        it must come back serving the partition it accepted.

        Runs under the per-partition install lock shared with
        handoff_probe: the owner's settlement probe either observes
        this install COMPLETE (and reports adoption) or cancels it
        before it starts — "probe answered not-adopted, then the
        install applied anyway" cannot happen (the double-owner race
        the round-4 advisor flagged)."""
        if self.meta.get("cluster_resize") is not None:
            # freeze order is per-member: this receiver may be frozen
            # while the pushing owner is not yet — adopting an
            # old-width partition here would dodge the resize barrier
            # (the owner's cutover settles via the probe and resumes)
            raise RemoteCallError(
                "cluster resize in progress; no install may land")
        ent = self._handoff_in_entry(p)
        with ent["lock"]:
            if ent["cancelled"]:
                raise RemoteCallError(
                    f"handoff install of {p} cancelled by the owner's "
                    f"settlement probe; re-run handoff_begin to retry")
            staged = self._staged_path(p)
            have = os.path.getsize(staged) if os.path.exists(staged) \
                else 0
            if have != base_offset:
                raise RemoteCallError(
                    f"handoff install mismatch: staged {have} bytes, "
                    f"owner pushed tail from {base_offset}")
            with open(staged, "ab") as f:
                f.write(tail)
                f.flush()
                os.fsync(f.fileno())
            os.replace(staged, self.node._log_path(p))
            # pin the promotion rename before adopting: the bundle
            # install below also dir-fsyncs, but only when the donor
            # shipped one — the log publish must not depend on that
            _fsync_dir(os.path.dirname(self.node._log_path(p)),
                       instant="handoff_install_fsync")
            # a stale LOCAL checkpoint (from a previous ownership of
            # this slot) describes a different log's layout — retire
            # it (segments included) and install the donor's shipped
            # bundle in its place (ISSUE 13): adoption then recovers
            # checkpoint-seeded, FULL state even when the donor's
            # below-cut bytes were truncated (pre-fix: suffix-only,
            # loudly)
            from antidote_tpu.oplog.checkpoint import (
                install_shipped_bundle,
            )

            ckpt_cursor = ent.pop("ckpt_cursor", None)
            if ckpt_cursor is not None:
                # streamed pull (ISSUE 19): every segment is already
                # validated + durably staged; commit retires the stale
                # local checkpoint and publishes via the same
                # segments-then-manifest rename discipline
                ckpt_cursor.commit()
                ent.pop("ckpt_bundle", None)
            else:
                install_shipped_bundle(
                    self.node._log_path(p) + ".ckpt",
                    ent.pop("ckpt_bundle", None))
            self.node.ring[p] = self.node_id
            self.node.adopt_partition(p)
            prev = self.plane.get_stable_snapshot() if self.plane \
                else None
            self._install_stable_plane(prev_stable=prev)
            self._refresh_fabric_plane()
            if self.on_ring_change is not None:
                self.on_ring_change()
            self.meta.put("cluster_plan",
                          (self.node.dc_id, dict(self.node.ring),
                           dict(self._members)))
            return True

    def _handoff_probe(self, p: int) -> bool:
        """Receiving side: adoption oracle for the owner's settlement
        (cutover failure / restart resolution).  Under the install
        lock: reports whether this node adopted the partition, and if
        not, CANCELS any staged-but-unapplied install so the answer
        stays true afterwards — the fence that makes 'resume
        ownership' safe for the asking side."""
        ent = self._handoff_in_entry(p)
        with ent["lock"]:
            adopted = (
                self.node is not None
                and self.node.ring.get(p) == self.node_id
                and isinstance(self.node.partitions[p],
                               PartitionManager))
            if not adopted:
                ent["cancelled"] = True
            return adopted

    def _handoff_settle(self, p: int, new_owner) -> bool:
        """Driver-requested settlement of an interrupted transfer (a
        re-driven rebalance saw the receiver adopted while this node
        may still hold a parked in-doubt copy): probe + retire /
        resume, exactly the cutover failure path.  True when the local
        copy no longer serves (retired or already proxied)."""
        if self.node is None:
            raise RemoteCallError("node not assembled yet")
        pm = self.node.partitions[p]
        if not isinstance(pm, PartitionManager):
            return True
        self._settle_inflight_handoff(p, new_owner, pm)
        return not isinstance(self.node.partitions[p],
                              PartitionManager)

    def _handoff_cutover(self, p: int, new_owner, b_cursor: int,
                         b_base: int | None = None) -> bool:
        """Owning side, cutover: drain the partition (park new mutating
        work, let prepared transactions resolve, drain local
        transactions via the TxnGate), push the final log tail to the
        new owner, then retire the partition behind a typed
        wrong-owner redirect.  The stable contribution stays pinned at
        the transferred commit watermark until the global re-plan, so
        the DC snapshot cannot pass a commit the new owner is still
        preparing (their clock advances past the watermark at adopt).

        ``b_base``: the truncation base the receiver's pre-copy was
        consistent with (None = caller predates the check) — a
        checkpoint truncation since then rewrote the file, so the
        byte cursor no longer addresses the layout the staged copy
        was cut from; the cutover refuses (clean failure: the
        partition un-retires and the driver re-drives, re-staging
        from the new layout) instead of pushing a tail that would
        seam the receiver's file and silently truncate at recovery."""
        pm = self.node.partitions[p]
        if not isinstance(pm, PartitionManager):
            raise RemoteCallError(
                f"partition {p} not owned by {self.node_id!r}")
        if new_owner not in self._members:
            raise RemoteCallError(f"unknown member {new_owner!r}")
        if self.meta.get("cluster_resize") is not None:
            # a resize is mid-flight: its fold captured THIS ring; an
            # ownership move under it would desync the fold's slices
            raise RemoteCallError(
                "cluster resize in progress; no cutover may start")
        #: a journal entry from a PREVIOUS attempt means that attempt's
        #: install may have been applied at the receiver — then even a
        #: pre-install failure of THIS attempt must settle by probe,
        #: never clean-resume (the clean path deletes the journal)
        prior_intent = p in (self.meta.get("handoff_out") or {})
        #: an existing entry (a retry of an in_doubt transfer) must be
        #: RESTORED — not deleted — if this attempt backs out before
        #: doing anything, or the parked-in-doubt safety state is lost
        prior_entry = self._handoff.get(p)
        self._handoff[p] = {"state": "drain", "new_owner": new_owner}
        # flag-then-check against a racing resize_freeze (which sets
        # its marker, then looks for drain entries): with both sides
        # re-checking after setting their own flag, one of the two
        # admin operations always sees the other and backs out
        if self.meta.get("cluster_resize") is not None:
            if prior_entry is None:
                self._handoff.pop(p, None)
            else:
                self._handoff[p] = prior_entry
            raise RemoteCallError(
                "cluster resize in progress; no cutover may start")
        install_sent = False
        try:
            with self.node.txn_gate.exclusive():
                deadline = time.monotonic() + 30.0
                while True:
                    # the prepared check, the retire flag, and the tail
                    # snapshot form ONE pm._lock critical section:
                    # every append also runs under pm._lock and checks
                    # pm.retired first, so no mutating RPC that raced
                    # the drain park can land a record after the tail
                    # is read — it raises PartitionRetired instead
                    # (advisor r04: cutover TOCTOU)
                    with pm._lock:
                        if not pm.prepared:
                            if b_base is not None and \
                                    self._log_trunc_base(pm) != b_base:
                                raise RemoteCallError(
                                    f"partition {p}: log truncated "
                                    "during the handoff pre-copy "
                                    "(layout epoch moved); re-drive "
                                    "to re-stage")
                            pm.retired = True
                            tail, end = pm.log.read_bytes(
                                b_cursor, 1 << 62)
                            break
                    if time.monotonic() > deadline:
                        raise RemoteCallError(
                            f"partition {p} drain timed out")
                    time.sleep(0.005)
                # journal the in-doubt transfer BEFORE the push: a
                # crash from here on resolves ownership by asking the
                # new owner at restart (_resume_handoff_out)
                out = dict(self.meta.get("handoff_out") or {})
                out[p] = new_owner
                self.meta.put("handoff_out", out)
                install_sent = True
                self._rpc(new_owner, "handoff_install",
                          (p, b_cursor, tail))
                self._retire_local_copy(p, new_owner, pm)
        except BaseException:
            if not install_sent and not prior_intent:
                # clean failure before anything ever left this node:
                # un-drain, forget the intent, keep serving
                with pm._lock:
                    pm.retired = False
                self._handoff.pop(p, None)
                out = dict(self.meta.get("handoff_out") or {})
                if out.pop(p, None) is not None:
                    self.meta.put("handoff_out", out)
                raise
            # an install push (this attempt's or a journaled earlier
            # one) may have been applied at the receiver even though we
            # saw an error (reply lost, link dropped).  Resuming
            # ownership here would create two live owners with the
            # in-doubt journal deleted (advisor r04) — resolve by
            # probing the intended new owner instead, exactly like a
            # restart does.
            self._settle_inflight_handoff(p, new_owner, pm)
            raise
        return True

    def _retire_local_copy(self, p: int, new_owner,
                           pm: Optional[PartitionManager]) -> None:
        """Ownership-transfer epilogue, shared by the cutover success
        path, the settlement's adopted branch, and restart resolution:
        pin the stable contribution at the transferred commit
        watermark VC (every future commit on p happens at the new
        owner ABOVE the own-DC entry — their clock advances past it
        at adopt — and their replication gate seeds at the same
        remote watermarks), re-aim ring + proxy, rebuild the stable
        plane, announce the ring change, and retire the log file
        behind the redirect state.  ``pm`` is None when no live local
        copy exists (restart found the slot already proxied)."""
        if pm is not None and pm.log.max_commit_vc:
            # an EMPTY max_commit_vc means this pm was rebuilt over a
            # fresh log after the real history was renamed (restart
            # after a completed cutover): pinning BOTTOM would freeze
            # the DC's stable snapshot at zero until the re-plan.  No
            # pin is needed then — the receiver's clock advanced past
            # the true watermark at adopt
            self._stable_pins[p] = VC(pm.log.max_commit_vc)
        self.node.ring[p] = new_owner
        self.node.partitions[p] = RemotePartition(
            self.link, new_owner, p)
        self._install_stable_plane(
            prev_stable=self.plane.get_stable_snapshot())
        self._refresh_fabric_plane()
        if self.on_ring_change is not None:
            self.on_ring_change()
        if pm is not None:
            with pm._lock:
                # already set on the cutover path; restart resolution
                # reaches here with a freshly rebuilt pm
                pm.retired = True
            pm.log.close()
            if os.path.exists(pm.log.path):
                # dur-ok: retire rename of an already-closed log — no
                # temp bytes to pin (the inode's content is unchanged)
                # and a rename lost to a power cut only re-surfaces
                # the .handedoff copy at the old path, which restart
                # resolution re-retires from the persisted plan
                os.replace(pm.log.path, pm.log.path + ".handedoff")
        self._handoff[p] = {"state": "retired", "new_owner": new_owner}

    def _settle_inflight_handoff(self, p: int, new_owner, pm) -> None:
        """A cutover failed after an install push may have reached the
        receiver.  Probe the intended new owner: the probe runs under
        the receiver's per-partition install lock and CANCELS any
        not-yet-applied install, so its answer is a fence, not a
        snapshot — "not adopted" means no install can land afterwards
        (a still-executing install either finished before the probe,
        and the probe reports adoption, or fails on the cancel flag).
        Adopted -> finish retiring our copy; fenced-not-adopted ->
        resume serving and forget the intent; unreachable -> the
        transfer stays in doubt: journal KEPT, partition parked, and
        restart (or a rebalance retry — handoff_begin re-stages and
        clears the cancel) resolves it."""
        adopted = None
        try:
            adopted = bool(self.link.request(
                new_owner, "handoff_probe", (p,)))
        except Exception:  # noqa: BLE001 — peer down
            pass
        if adopted:
            # adopted there: complete our side of the cutover
            self._retire_local_copy(p, new_owner, pm)
            log.warning(
                "partition %d: install push errored but %r adopted it; "
                "retired local copy", p, new_owner)
        elif adopted is False:
            # fenced: the receiver answered, did not adopt, and can no
            # longer apply a late install — safe to resume
            with pm._lock:
                pm.retired = False
                pm.parked = False
            self._handoff.pop(p, None)
            out = dict(self.meta.get("handoff_out") or {})
            if out.pop(p, None) is not None:
                self.meta.put("handoff_out", out)
        else:
            # unreachable: genuinely in doubt — park WRITES AND READS
            # (the receiver may have adopted and taken writes), keep
            # the journal
            with pm._lock:
                pm.parked = True
            self._handoff[p] = {"state": "in_doubt",
                                "new_owner": new_owner}
            log.warning(
                "partition %d: transfer to %r in doubt (peer "
                "unreachable after install push) — parked until "
                "resolution", p, new_owner)

    def _apply_ring_update(self, ring: Dict[int, Any],
                           members: Dict[Any, Tuple[str, int]],
                           clients: List[Any]) -> None:
        """Adopt the re-planned ring: re-aim proxies, rebuild the
        stable plane over the new data-member set, persist the plan
        (the riak_core ring gossip + claimant commit)."""
        if self.node is None:
            raise RemoteCallError("node not assembled yet")
        if self.meta.get("cluster_resize") is not None:
            # a resize is mid-flight here: adopting a re-plan now would
            # desync this member's ring from the resize fold (and the
            # resize's own freeze check only sees its LOCAL snapshot)
            raise RemoteCallError(
                "cluster resize in progress; ring update refused")
        n = self.node.config.n_partitions
        if sorted(ring) != list(range(n)):
            # a re-plan broadcast that raced a completed resize: its
            # old-width ring applied over this member would leave the
            # widened tail permanently stale
            raise RemoteCallError(
                f"ring update at width {len(ring)} does not match this "
                f"member's {n} partitions; stale re-plan refused")
        prev = self.plane.get_stable_snapshot() if self.plane else None
        self._members = dict(members)
        for nid, addr in self._members.items():
            if nid != self.node_id:
                self.link.connect(nid, addr)
        node = self.node
        for p, owner in ring.items():
            node.ring[p] = owner
            cur = node.partitions[p]
            if owner == self.node_id:
                if not isinstance(cur, PartitionManager):
                    raise RemoteCallError(
                        f"re-plan says {self.node_id!r} owns partition "
                        f"{p} but it was never handed off here")
            elif isinstance(cur, RemotePartition):
                cur.owner = owner
            else:
                raise RemoteCallError(
                    f"re-plan moves partition {p} away from "
                    f"{self.node_id!r} without a handoff")
        # pins for partitions the plan now assigns elsewhere are done:
        # the new owner reports them from here on
        self._stable_pins = {
            p: t for p, t in self._stable_pins.items()
            if ring.get(p) == self.node_id}
        out = dict(self.meta.get("handoff_out") or {})
        done = [p for p, owner in out.items() if ring.get(p) == owner]
        if done:
            for p in done:
                out.pop(p)
            self.meta.put("handoff_out", out)
        self._install_stable_plane(prev_stable=prev)
        self._refresh_fabric_plane()
        if self.on_ring_change is not None:
            self.on_ring_change()
        self.meta.put("cluster_plan",
                      (node.dc_id, dict(ring), dict(self._members)))

    def _resume_handoff_out(self) -> None:
        """Restart with an in-doubt outbound handoff journaled: probe
        the intended new owner (the probe fences late installs — see
        _handoff_probe).  Adopted -> retire our copy behind a
        redirect; fenced-not-adopted -> resume ownership; unreachable
        -> the transfer stays in doubt — the journal only exists once
        the install push was attempted, so our surviving log does NOT
        prove non-adoption (install applied + crash before the rename
        leaves it intact).  Park the partition rather than risk two
        live owners; the next restart (or the peer returning before a
        rebalance retry) resolves it."""
        out = dict(self.meta.get("handoff_out") or {})
        if not out or self.node is None:
            return
        for p, new_owner in list(out.items()):
            p = int(p)
            adopted = None
            try:
                adopted = bool(self.link.request(
                    new_owner, "handoff_probe", (p,)))
            except Exception:  # noqa: BLE001 — peer down
                log.warning("handoff resolution: %r unreachable", new_owner)
            if adopted:
                # adopted there: stay retired behind a redirect (and
                # close + rename any surviving local log — the crash
                # may have landed before the cutover's rename)
                pm = self.node.partitions[p]
                self._retire_local_copy(
                    p, new_owner,
                    pm if isinstance(pm, PartitionManager) else None)
            elif adopted is False:
                # fenced: no install can land there — resume ownership
                out.pop(p)
                self.meta.put("handoff_out", out)
            else:
                # unreachable: park in doubt, keep the journal.  Reads
                # park too (pm.parked): the partition object here was
                # rebuilt over whatever log survived — possibly a
                # brand-new EMPTY one if the crash landed after the
                # cutover's rename — so a local read could serve
                # bottom values for committed keys
                pm = self.node.partitions[p] \
                    if p < len(self.node.partitions) else None
                if isinstance(pm, PartitionManager):
                    with pm._lock:
                        pm.retired = True
                        pm.parked = True
                self._handoff[p] = {"state": "in_doubt",
                                    "new_owner": new_owner}
                log.warning(
                    "partition %d: transfer to %r in doubt (peer "
                    "unreachable at restart) — parked until "
                    "resolution", p, new_owner)

    def add_member(self, node_id, addr: Tuple[str, int]) -> None:
        """Admit a running, empty NodeServer into this live cluster as
        a coordinator-only member (the staged-join 'plan' half); hand
        it data afterwards with rebalance() (the 'commit' half) — the
        reference's join_new_nodes + claim transition,
        src/antidote_dc_manager.erl:53-81."""
        if self.node is None:
            raise RuntimeError("node not assembled yet")
        if node_id in self._members:
            raise ValueError(f"{node_id!r} is already a member")
        self._members[node_id] = tuple(addr)
        self.link.connect(node_id, tuple(addr))
        ring = dict(self.node.ring)
        clients = sorted(set(self._members) - set(ring.values()),
                         key=repr)
        self.link.request(
            node_id, "join",
            (self.node.dc_id, list(ring.items()),
             list(self._members.items()), self.fabric_kind(), clients))
        payload = (list(ring.items()), list(self._members.items()),
                   clients)
        for nid in self._members:
            if nid not in (self.node_id, node_id):
                self.link.request(nid, "ring_update", payload)
        self._apply_ring_update(ring, dict(self._members), clients)

    def rebalance(self, new_ring: Dict[int, Any]) -> Dict[int, Any]:
        """Re-plan a LIVE cluster's ring from this node: stream each
        moving partition to its new owner while serving, cut over
        under the owner's TxnGate, then push + persist the new plan on
        every member (the reference's riak_core claimant transition,
        antidote_dc_manager's plan/commit staged change,
        src/antidote_dc_manager.erl:53-81)."""
        if self.node is None:
            raise RuntimeError("node not assembled yet")
        old_ring = dict(self.node.ring)
        if sorted(new_ring) != sorted(old_ring):
            raise ValueError("re-plan must cover the same partitions")
        owners = set(new_ring.values())
        unknown = owners - set(self._members)
        if unknown:
            raise ValueError(f"new owners {unknown!r} are not members")
        moves = [(p, old_ring[p], new_ring[p])
                 for p in sorted(new_ring) if old_ring[p] != new_ring[p]]
        for p, old, new in moves:
            # a RE-DRIVEN rebalance (an earlier attempt's broadcast was
            # refused mid-way, e.g. by a mid-flight resize): the probe
            # fences + reports adoption, so a move whose data already
            # transferred is skipped instead of re-fetched from an
            # owner that no longer holds it.  The OLD owner may still
            # hold a parked in-doubt copy from the interrupted attempt
            # — settle it (probe + retire) or its ring_update below
            # would refuse 'moved without a handoff' on every re-drive
            if bool(self._rpc(new, "handoff_probe", (p,))):
                if not bool(self._rpc(old, "handoff_settle", (p, new))):
                    raise RemoteCallError(
                        f"partition {p}: receiver {new!r} adopted but "
                        f"old owner {old!r} could not settle its copy; "
                        f"resolve connectivity and re-drive")
                continue
            cursor, base = self._rpc(new, "handoff_begin", (p, old))
            self._rpc(old, "handoff_cutover", (p, new, cursor, base))
        clients = sorted(set(self._members) - owners, key=repr)
        payload = (list(new_ring.items()),
                   list(self._members.items()), clients)
        refused = []
        for nid in self._members:
            if nid != self.node_id:
                try:
                    self.link.request(nid, "ring_update", payload)
                except Exception as e:  # noqa: BLE001 — keep going
                    refused.append((nid, e))
        # apply locally even when part of the broadcast was refused:
        # the DRIVER's ring must reflect the moves that already
        # happened or a re-drive would recompute them as fresh moves.
        # The divergence window this leaves (some members on the old
        # ring) is closed against a racing resize by resize_cluster's
        # pre-flight ring-equality check across all members.
        self._apply_ring_update(dict(new_ring), dict(self._members),
                                clients)
        if refused:
            raise RemoteCallError(
                f"re-plan applied on {len(self._members) - len(refused)}"
                f"/{len(self._members)} members; refused by "
                f"{sorted(nid for nid, _ in refused)!r} "
                f"({refused[0][1]}) — re-drive rebalance(new_ring) "
                f"once the refusing operation resolves")
        return dict(new_ring)

    # ------------------------------------- cluster partition-count resize

    def resize_cluster(self, new_n: int, max_passes: int = 6,
                       delta_threshold: int = 256) -> Dict[int, Any]:
        """Grow a LIVE multi-node DC's partition count (the riak_core
        ring-resize the reference's fixed ring cannot do, generalized
        from the single-node Node.repartition_live).  ``new_n`` must
        be an integer multiple of the current count: each partition
        splits IN PLACE into new_n/old_n children on its current owner
        (no data crosses nodes — see ClusterNode.build_resize_fold);
        ownership then moves with the ordinary rebalance().

        Protocol (driver = this member):
        1. prepare  — every data member incrementally folds its slice
           into staged child logs WHILE SERVING (LiveFold passes).
        2. freeze   — every member closes its gate to NEW transactions
           and journals the resize marker (a member restarting
           mid-resize comes back parked, never serving a width its
           peers may not share).
        3. drain    — wait until every member's in-flight transactions
           completed (their remote 2PC legs still serve: no member has
           changed width yet, routing stays consistent).
        4. commit   — each member folds its final delta, swaps logs
           under the node-level crash journal, adopts the expanded
           ring at the new width, persists the new plan, and PARKS
           part RPCs (peers still at the old width must not land
           wrong-partition records).
        5. finish   — clear markers, unpark, unfreeze everywhere.

        Crash-resumable and idempotent: a member killed at any point
        restarts parked (marker) with its journaled width reconciled
        (_reconcile_resized_plan + Node._resume_interrupted_resize);
        re-running resize_cluster no-ops the already-resized members
        and completes the stragglers.  A driver failure leaves the
        cluster frozen-but-consistent; re-drive to finish.  Refused
        while federated (partition counts are part of the inter-DC
        contract — same rule as DataCenter.repartition) or while a
        handoff is in flight."""
        if self.node is None:
            raise RuntimeError("node not assembled yet")
        if self.source_factory is not None:
            raise RuntimeError(
                "resize requires a disconnected DC: drop the "
                "federation first; every DC resizes separately "
                "(partition counts are part of the inter-DC contract)")
        old_n = self.node.config.n_partitions
        if new_n != old_n and (new_n <= 0 or new_n % old_n):
            raise ValueError(
                f"multi-node resize must grow by an integer factor "
                f"({old_n} -> {new_n})")
        members = sorted(self._members, key=repr)
        # pre-flight: members must agree on the ring.  An interrupted
        # rebalance broadcast (refused on one member, the old owner's
        # handoff journal already drained by its own ring_update)
        # leaves silent same-width divergence none of the per-member
        # checks can see — resize_commit expands each member's OWN
        # ring, so committing over divergent rings splits routing
        # permanently.  A partial-commit RECOVERY legitimately mixes
        # two widths; that state is allowed only when it is exactly
        # this resize's split (children on the parent's owner).
        rings_by_width: Dict[int, dict] = {}
        for m in members:
            st = self._rpc(m, "status", None)
            r = {int(p): o for p, o in (st.get("ring") or [])}
            if not r:
                raise RuntimeError(
                    f"member {m!r} is not assembled (empty ring); "
                    f"restore or remove it before resizing")
            rings_by_width.setdefault(len(r), {})[m] = r
        for w, group in rings_by_width.items():
            if len({tuple(sorted(r.items()))
                    for r in group.values()}) > 1:
                raise RuntimeError(
                    f"members at width {w} disagree on the ring "
                    f"(an interrupted rebalance?): {group!r}; "
                    f"re-drive the rebalance to convergence before "
                    f"resizing")
        widths = sorted(rings_by_width)
        if len(widths) == 2:
            w0, w1 = widths
            small = next(iter(rings_by_width[w0].values()))
            big = next(iter(rings_by_width[w1].values()))
            if w1 != new_n or w1 % w0 or \
                    any(big[q] != small[q % w0] for q in big):
                raise RuntimeError(
                    f"mixed ring widths {widths} are not a "
                    f"partial commit of this resize (to {new_n}); "
                    f"resolve before resizing")
        elif len(widths) > 2:
            raise RuntimeError(
                f"members at {len(widths)} different ring widths "
                f"{widths}; resolve before resizing")

        def unwind():
            # abort-before-start: every member discards its prepare
            # staging, clears its marker, and reopens its gate.  Sent
            # to ALL members (not just those whose RPC returned — a
            # freeze whose reply was lost may still have applied) so
            # nobody stays gated or keeps staged child logs until an
            # operator re-drives.  Post-freeze phases deliberately do
            # NOT unwind — a commit must be re-driven to completion,
            # never rolled back.
            for m in members:
                try:
                    self._rpc(m, "resize_abort", None)
                except Exception:  # noqa: BLE001 — best-effort unwind
                    log.warning(
                        "resize unwind: could not reach %r", m)

        try:
            for m in members:
                self._rpc(m, "resize_prepare",
                          (new_n, max_passes, delta_threshold))
            for m in members:
                self._rpc(m, "resize_freeze", (new_n,))
        except BaseException:
            unwind()
            raise
        for m in members:
            self._rpc(m, "resize_drain", None)
        for m in members:
            self._rpc(m, "resize_commit", (new_n,))
        for m in members:
            self._rpc(m, "resize_finish", None)
        return dict(self.node.ring)

    def _resize_prepare(self, new_n: int, max_passes: int,
                        delta_threshold: int) -> str:
        node = self.node
        if node is None:
            raise RemoteCallError("node not assembled yet")
        if node.config.n_partitions == new_n:
            return "done"  # idempotent re-drive after a crash
        self._refuse_if_handoff_busy()
        if not node.config.enable_logging:
            raise RemoteCallError(
                "resize folds the durable logs; enable_logging=False "
                "leaves nothing to redistribute")
        if self.source_factory is not None:
            raise RemoteCallError(
                "member is federated; disconnect before resizing")
        #: the ring the fold slices were built against — commit/freeze
        #: refuse if ownership moved afterwards (the folds would stage
        #: the wrong slots)
        self._resize_ring = dict(node.ring)
        if self.node_id not in set(node.ring.values()):
            self._resize_fold = None  # coordinator-only member
            return "client"
        self._resize_fold = node.build_resize_fold(new_n)
        self._resize_fold.serve_passes(max_passes, delta_threshold)
        return "prepared"

    def _refuse_if_handoff_busy(self) -> None:
        """An IN-FLIGHT ownership transfer (draining or in doubt)
        excludes a resize; COMPLETED transfers (retired redirect
        entries, which persist for stale callers) do not."""
        busy = [p for p, st in self._handoff.items()
                if st["state"] in ("drain", "in_doubt")]
        if busy:
            raise RemoteCallError(
                f"handoff in flight on partitions {sorted(busy)}; "
                f"resolve it before resizing")
        if self.meta.get("handoff_out"):
            # a journaled transfer not yet globally re-planned: its
            # OLD-width partition indices would be misread after the
            # resize (restart resolution probes by index)
            raise RemoteCallError(
                "journaled handoff awaiting re-plan; commit the "
                "rebalance before resizing")

    def _resize_freeze(self, new_n: int) -> bool:
        # flag-then-check, mirrored by the cutover (which sets its
        # drain entry, then re-checks this marker): whichever admin
        # operation loses the race sees the other's flag and backs
        # out — neither can slip through the check-then-act window
        self.meta.put("cluster_resize", int(new_n))
        self.node.txn_gate.freeze()
        try:
            self._refuse_if_handoff_busy()
            if self._resize_ring is not None and \
                    self._resize_ring != dict(self.node.ring):
                # a rebalance COMPLETED between prepare and freeze:
                # the folds staged at prepare no longer match
                # ownership — the driver must re-prepare
                raise RemoteCallError(
                    "ring changed since resize_prepare; re-drive "
                    "the resize")
        except BaseException:
            self.meta.delete("cluster_resize")
            self.node.txn_gate.unfreeze()
            raise
        return True

    def _resize_commit(self, new_n: int) -> str:
        node = self.node
        old_n = node.config.n_partitions
        if old_n == new_n:
            return "done"
        if self._resize_ring is not None and \
                self._resize_ring != dict(node.ring):
            raise RemoteCallError(
                "ring changed since resize_prepare; re-drive the "
                "resize")
        self._resize_parking = True
        data_member = self.node_id in set(node.ring.values())
        new_ring = {q: node.ring[q % old_n] for q in range(new_n)}
        if data_member:
            fold = self._resize_fold
            if fold is None:
                raise RemoteCallError(
                    "resize_commit without resize_prepare")
            fold.final_pass()
            for pm in node._local_partitions():
                pm.log.close()
            journal = node._resize_journal_path()
            tmp = journal + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{old_n} {new_n}\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, journal)
            # pin the journal rename before acting on it (ISSUE 15 —
            # the single-node resize paths carry the same discipline)
            _fsync_dir(node.data_dir, instant="resize_journal_fsync")
            # the new plan persists BEFORE the swap clears the
            # journal: at every crash point either the journal or the
            # persisted plan carries the new width (restart reconciles
            # from whichever survives)
            self.meta.put("cluster_plan",
                          (node.dc_id, dict(new_ring),
                           dict(self._members)))
            node._complete_resize_swap(old_n, new_n)
        else:
            self.meta.put("cluster_plan",
                          (node.dc_id, dict(new_ring),
                           dict(self._members)))
        node.config.n_partitions = new_n
        node.ring = dict(new_ring)
        # completed-handoff redirect entries and stable pins are keyed
        # by OLD-width partition indices: left in place they would
        # shadow (WrongOwner) or mis-pin the NEW partitions that reuse
        # those indices.  The freshly persisted plan supersedes them —
        # every remaining entry is "retired" (drain/in_doubt refused
        # at prepare AND freeze).
        self._handoff.clear()
        self._stable_pins.clear()
        node.partitions = [node._build_partition(q)
                           for q in range(new_n)]
        if data_member:
            # UNCONDITIONAL, like the single-node resize paths:
            # recover_from_log only governs boot — a mid-session
            # resize that skipped the replay would serve bottom for
            # every committed key
            node._recover_stores()
        self._resize_fold = None
        self._resize_ring = None
        self._install_stable_plane(
            prev_stable=self.plane.get_stable_snapshot()
            if self.plane else None)
        self._refresh_fabric_plane()
        if self.on_ring_change is not None:
            self.on_ring_change()
        return "committed"

    def _resize_finish(self) -> bool:
        self.meta.delete("cluster_resize")
        self._resize_parking = False
        self.node.txn_gate.unfreeze()
        return True

    def _resize_abort(self) -> str:
        """Abort-before-commit: discard the prepare staging (folds AND
        their staged child log files), clear the marker, reopen the
        gate.  On a member that already COMMITTED the new width (a
        re-driven resize unwinding after a partial-commit crash) this
        is a NO-OP: committed members must stay parked at the new
        width until a successful re-drive finishes — unparking one
        would let it serve a width its peers may not share."""
        marker = self.meta.get("cluster_resize")
        if marker is not None and self._resize_parking \
                and self.node is not None \
                and self.node.config.n_partitions == int(marker):
            # _resize_parking discriminates a REAL pending commit from
            # an idempotent same-width re-drive that merely re-froze
            # this member (width equality alone would classify the
            # whole already-finished cluster as committed and leave
            # every member gated after an unwind)
            return "committed"
        if self._resize_fold is not None:
            self._resize_fold.discard()
            self._resize_fold = None
        if self.node is not None:
            # also sweep staged files from a PREVIOUS attempt that
            # died before this process held a fold object (restart
            # after a prepare-crash): left behind, a later resize's
            # swap would promote them over the live logs
            self.node.sweep_staged_resize()
        self._resize_ring = None
        self.meta.delete("cluster_resize")
        self._resize_parking = False
        if self.node is not None:
            self.node.txn_gate.unfreeze()
        return "aborted"

    # ------------------------------------------------------------ shutdown

    def close(self) -> None:
        self._stop.set()
        if self._gossip is not None:
            self._gossip.join(timeout=2.0)
        if self.plane is not None:
            self.meta.put("last_stable_vc",
                          dict(self.plane.get_stable_snapshot()))
        self.link.close()
        if self.node is not None:
            self.node.close()


def create_dc_cluster(dc_id, n_partitions: int,
                      servers: List[NodeServer],
                      clients: List[NodeServer] = ()) -> Dict[int, Any]:
    """In-process cluster build: plan the ring over the given servers
    and commit it on each (the antidote_dc_manager:create_dc flow,
    reference src/antidote_dc_manager.erl:53-81).  ``clients`` join as
    coordinator-only members: full API, no ring slots.  For
    cross-process builds, push the same plan via the "join" RPC
    instead."""
    members = {s.node_id: s.addr for s in servers}
    members.update({c.node_id: c.addr for c in clients})
    kinds = {s.fabric_kind() for s in list(servers) + list(clients)}
    if len(kinds) > 1:
        raise RuntimeError(
            f"members run different fabrics {sorted(kinds)!r}; the "
        "framings do not interoperate — align Config.fabric_native")
    ring = plan_ring(n_partitions, [s.node_id for s in servers])
    client_ids = [c.node_id for c in clients]
    for s in list(servers) + list(clients):
        s.install_cluster(dc_id, ring, members, clients=client_ids)
    return ring

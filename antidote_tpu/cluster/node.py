"""ClusterNode + NodeServer: one DC spanning several OS processes.

Roles, mapped from the reference:

- **ClusterNode** — the riak_core placement duty: a ring maps every
  partition index to an owning node; this process instantiates real
  PartitionManagers for its slice and RemotePartition proxies for the
  rest, so the unchanged Coordinator transparently spans nodes exactly
  as `riak_core_vnode_master` routes vnode commands across BEAM nodes
  (reference src/clocksi_vnode.erl:99-209 call sites).
- **ClusterStablePlane** — the cross-node half of the stable-time
  protocol: each node min-folds its own partitions (meta_data_sender's
  per-node merge, reference src/meta_data_sender.erl:224-255), gossips
  the summary to every peer, stores peer summaries
  (meta_data_manager's remote-node table, src/meta_data_manager.erl:
  64-94), and publishes the min-of-mins monotonically; a member that
  has never reported pins the snapshot to zero (reference
  src/stable_time_functions.erl:78-85).
- **NodeServer** — the per-process assembly + antidote_dc_manager's
  staged join (reference src/antidote_dc_manager.erl:53-81): nodes
  boot empty, a coordinator pushes the cluster plan (ring + member
  addresses) to each, every node persists it and assembles; a
  restarted process reloads the plan, recovers its partitions from
  their logs, and re-joins the gossip.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from antidote_tpu.clocks import VC, vc_min
from antidote_tpu.cluster.link import NodeLink
from antidote_tpu.cluster.remote import (
    PARTITION_METHODS,
    RemoteCallError,
    RemotePartition,
)
from antidote_tpu.config import Config
from antidote_tpu.meta.gossip import StableTimeTracker
from antidote_tpu.meta.sender import MetaDataSender
from antidote_tpu.meta.stable_store import StableMetaData
from antidote_tpu.txn.manager import PartitionManager
from antidote_tpu.txn.node import Node

log = logging.getLogger(__name__)


def build_link(node_id, host: str = "127.0.0.1", port: int = 0,
               config: Optional[Config] = None):
    """The DC's node-fabric endpoint: the native IO plane when built
    (C++ event loop, GIL-free waits, pipelined requests —
    cluster/nativelink.py), else the pure-Python NodeLink.  Both speak
    the same termcodec payloads over different wire framings, so every
    member of one cluster must pick the same plane — which they do, by
    sharing the Config default and the same build environment."""
    cfg = config or Config()
    if cfg.node_fabric == "native":
        from antidote_tpu.cluster import nativelink

        if nativelink.native_available():
            return nativelink.NativeNodeLink(
                node_id, host=host, port=port,
                workers=cfg.fabric_workers)
        log.warning("native node fabric unavailable; falling back to "
                    "the Python NodeLink")
    return NodeLink(node_id, host=host, port=port)


def plan_ring(n_partitions: int, node_ids: List[Any]) -> Dict[int, Any]:
    """Round-robin partition placement — the cluster plan the reference
    computes via riak_core claim (reference antidote_dc_manager's
    plan/commit staged join).  Every member must own at least one
    partition: a slotless member would contribute an eternally-bottom
    stable summary, pinning the DC's snapshot at zero."""
    if n_partitions < len(node_ids):
        raise ValueError(
            f"{len(node_ids)} members need >= {len(node_ids)} "
            f"partitions (got {n_partitions}): a member owning no "
            "partition pins the cluster stable snapshot at zero")
    ids = sorted(node_ids, key=repr)
    return {p: ids[p % len(ids)] for p in range(n_partitions)}


class ClusterNode(Node):
    """A Node owning only its ring slice; other slots are RPC proxies."""

    def __init__(self, node_id, ring: Dict[int, Any], link: NodeLink,
                 dc_id="dc1", config: Optional[Config] = None,
                 data_dir: Optional[str] = None, on_log_append=None):
        if sorted(ring) != list(range(len(ring))):
            raise ValueError("ring must map every partition 0..N-1")
        self.node_id = node_id
        self.ring = dict(ring)
        self.link = link
        cfg = config or Config()
        cfg.n_partitions = len(ring)
        super().__init__(dc_id=dc_id, config=cfg, data_dir=data_dir,
                         on_log_append=on_log_append)

    def _build_partition(self, p: int):
        if self.ring[p] == self.node_id:
            return super()._build_partition(p)
        return RemotePartition(self.link, self.ring[p], p)

    def _local_partitions(self) -> List[PartitionManager]:
        return [pm for pm in self.partitions
                if isinstance(pm, PartitionManager)]

    def local_partition_indices(self) -> List[int]:
        return [p for p, owner in sorted(self.ring.items())
                if owner == self.node_id]

    def mint_dot(self) -> Tuple[Any, int]:
        """Dots are NODE-scoped in a multi-node DC: the device plane's
        per-actor-column max-seq collapse needs same-column dots minted
        under ONE monotone clock in observation order, which only this
        process's clock guarantees (Node.mint_dot documents the single-
        node argument).  Cross-node same-key commits still serialize at
        the key's owner partition, so per-column collapse stays sound
        per column; cross-column concurrency is what ORSWOT handles
        anyway."""
        return ((self.dc_id, self.node_id), self.clock.now_us())

    def repartition(self, new_n: int) -> None:
        raise RuntimeError(
            "repartition of a multi-node DC is a cluster-level plan "
            "(every member folds its slice against the new ring); "
            "resize single-node DCs or re-plan the cluster instead")


class ClusterStablePlane:
    """Two-level stable time: local partition fold + node-summary gossip.

    ``member_ids`` are the DATA members (ring owners) only: the
    min-of-mins is over nodes that actually hold partitions.  A
    coordinator-only member (see NodeServer's client role) neither
    contributes a summary nor pins the snapshot — it just receives
    peer summaries and reads the merged view."""

    def __init__(self, dc_id, node_id, member_ids: List[Any],
                 local: StableTimeTracker):
        self.dc_id = dc_id
        self.node_id = node_id
        self.members = sorted(member_ids, key=repr)
        self._idx = {nid: i for i, nid in enumerate(self.members)}
        self.local = local
        self.sender = MetaDataSender()
        self.sender.register(
            "stable_nodes", len(self.members), initial=lambda: None,
            merge=self._merge_nodes,
            publish=lambda prev, new: new if prev is None
            else prev.join(new))

    def _merge_nodes(self, vals: List[Optional[VC]]) -> VC:
        if any(v is None for v in vals):
            # an unheard-from member pins every column to zero — the
            # published view stays at its previous floor (monotone)
            return VC()
        return vc_min(vals)

    def put_node(self, node_id, vc: VC) -> None:
        """Store one node's summary (gossip receive side); per-source
        entries never regress."""
        i = self._idx.get(node_id)
        if i is None:
            log.warning("gossip from unknown node %r ignored", node_id)
            return
        self.sender.update(
            "stable_nodes", i,
            lambda cur: vc if cur is None else cur.join(vc))

    def local_summary(self) -> VC:
        """This node's contribution: the min-fold over its partitions.
        A coordinator-only member has none — nothing to record."""
        s = self.local.get_stable_snapshot()
        if self.node_id in self._idx:
            self.put_node(self.node_id, s)
        return s

    def get_stable_snapshot(self) -> VC:
        self.local_summary()
        return VC(self.sender.merged("stable_nodes"))

    def seed_floor(self, vc: VC) -> None:
        self.local.seed_floor(vc)


class NodeServer:
    """One OS process of a multi-node DC: fabric endpoint, cluster-plan
    persistence, gossip ticker, and the client API once assembled."""

    def __init__(self, node_id, host: str = "127.0.0.1", port: int = 0,
                 data_dir: str = ".", config: Optional[Config] = None):
        self.node_id = node_id
        self.config = config or Config()
        os.makedirs(data_dir, exist_ok=True)
        self.data_dir = data_dir
        self.meta = StableMetaData(
            os.path.join(data_dir, f"node_{node_id}_meta.pkl"),
            recover=self.config.recover_meta_data_on_start)
        plan = self.meta.get("cluster_plan")
        if plan is not None and port == 0:
            # a restarted member must come back at its ADVERTISED
            # address: peers' persisted member tables (and federated
            # descriptors) point there, and a fresh random port would
            # leave their gossip/RPC dialing a dead socket forever
            planned = dict(plan[2]).get(node_id)
            if planned is not None:
                host, port = planned
        self.link = build_link(node_id, host=host, port=port,
                               config=self.config)
        self.addr = self.link.serve(self._handle)
        self.node: Optional[ClusterNode] = None
        self.api = None
        self.plane: Optional[ClusterStablePlane] = None
        self._gossip: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._assembled = threading.Event()
        #: peer -> monotonic time before which gossip skips it
        self._peer_backoff: Dict[Any, float] = {}
        plan = self.meta.get("cluster_plan")
        if plan is not None:
            # restart: reload the committed plan and re-join (reference
            # check_node_restart, src/inter_dc_manager.erl:156-201)
            self._assemble(*plan)

    # ------------------------------------------------------------ lifecycle

    def descriptor(self) -> Tuple[Any, Tuple[str, int]]:
        return (self.node_id, self.addr)

    def fabric_kind(self) -> str:
        """Which wire framing this node's fabric speaks ("native" =
        corr-id frames via nodelink.cpp, "python" = plain NodeLink
        frames).  The two do not interoperate: a plan must never mix
        them — one member silently falling back (no compiler) would
        strand every RPC to it in decode errors."""
        return "native" if hasattr(self.link, "finish_many") else \
            "python"

    def install_cluster(self, dc_id, ring: Dict[int, Any],
                        members: Dict[Any, Tuple[str, int]],
                        fabric: Optional[str] = None,
                        clients: Optional[List[Any]] = None) -> None:
        """Commit the cluster plan on this node (the staged-join
        plan/commit step).  Persisted first: a crash between commit and
        assembly re-runs assembly at the next boot.

        ``fabric`` is the plan author's fabric kind: a mismatch with
        this node's refuses the join LOUDLY instead of assembling a
        member nobody can talk to.  ``clients`` lists the members that
        are INTENDED to be coordinator-only (client role): they hold
        RemotePartition proxies for the whole ring and run transactions
        without owning data — the riak_core pattern of coordinating
        from any node while vnodes live on the ring (reference
        src/antidote_dc_manager.erl nodes vs ring claim).  The list is
        explicit so a member that was MEANT to own data but got no ring
        slot (an operator sizing mistake) still fails loudly."""
        if self.node is not None:
            raise RuntimeError("node already belongs to a cluster")
        if self.node_id not in members:
            raise ValueError(f"plan does not include {self.node_id!r}")
        if fabric is not None and fabric != self.fabric_kind():
            raise RuntimeError(
                f"fabric mismatch: plan requires {fabric!r} but "
                f"{self.node_id!r} runs {self.fabric_kind()!r} (native "
                "fabric unavailable here? fix the build or set "
                "Config.node_fabric='python' cluster-wide)")
        owners = set(ring.values())
        if not owners <= set(members):
            raise ValueError(
                f"every ring owner must be a member (owners {owners!r} "
                f"vs members {set(members)!r})")
        slotless = set(members) - owners
        declared = set(clients or ())
        if slotless != declared:
            raise ValueError(
                f"members without ring slots {sorted(slotless, key=repr)!r} "
                f"must exactly match the declared client members "
                f"{sorted(declared, key=repr)!r} — a data member left "
                "without a slot is a plan error, not a silent demotion")
        self.meta.put("cluster_plan", (dc_id, dict(ring), dict(members)))
        self._assemble(dc_id, dict(ring), dict(members))

    def _assemble(self, dc_id, ring, members) -> None:
        from antidote_tpu.api import AntidoteTPU

        for nid, addr in members.items():
            if nid != self.node_id:
                self.link.connect(nid, tuple(addr))
        node = ClusterNode(self.node_id, ring, self.link, dc_id=dc_id,
                           config=self.config, data_dir=self.data_dir)
        local_idx = node.local_partition_indices()
        tracker = StableTimeTracker(dc_id, len(local_idx))

        def _source(pm):
            return lambda: VC({dc_id: pm.min_prepared()})

        tracker.sources = [_source(node.partitions[p]) for p in local_idx]
        data_members = sorted(set(ring.values()), key=repr)
        plane = ClusterStablePlane(dc_id, self.node_id,
                                   data_members, tracker)
        last = self.meta.get("last_stable_vc")
        if last:
            plane.seed_floor(VC(last))
        node.stable_vc_provider = plane.get_stable_snapshot
        node.wait_hook = self._wait_hook
        self.plane = plane
        self.node = node
        self.api = AntidoteTPU(node=node)
        self._gossip = threading.Thread(target=self._gossip_loop,
                                        daemon=True)
        self._gossip.start()
        self._assembled.set()
        self.meta.mark_started()

    def _wait_hook(self) -> None:
        # a causal wait is released by PEER summaries arriving at their
        # gossip cadence — nothing to push from here, and dialing peers
        # synchronously would stall the 2ms spin behind connect
        # timeouts when one is down
        self._stop.wait(0.002)

    # -------------------------------------------------------------- gossip

    def _gossip_loop(self) -> None:
        period = self.config.cluster_gossip_s
        if period is None:
            period = self.config.heartbeat_s
        while not self._stop.wait(period):
            try:
                self.gossip_tick()
            except Exception:  # noqa: BLE001 — the ticker must not die
                log.exception("gossip tick failed")

    def gossip_tick(self) -> None:
        """Broadcast this node's summary to every peer (reference
        meta_data_sender loop, src/meta_data_sender.erl:224-255); an
        unreachable peer is skipped — its entry simply stops advancing,
        holding the published snapshot, until it returns.  A peer that
        just failed is backed off for a few seconds so one dead member's
        connect timeouts don't delay the live members' gossip."""
        if self.plane is None:
            return
        if self.node_id not in self.plane._idx:
            # coordinator-only member: nothing to contribute — its
            # stable view fills from the data members' broadcasts
            return
        summary = self.plane.local_summary()
        now = time.monotonic()
        for peer in self.link.peers():
            if self._peer_backoff.get(peer, 0) > now:
                continue
            try:
                self.link.request(peer, "gossip",
                                  (self.node_id, summary))
                self._peer_backoff.pop(peer, None)
            except Exception:  # noqa: BLE001 — down peer
                self._peer_backoff[peer] = now + 2.0

    # ----------------------------------------------------------- RPC server

    def _handle(self, origin, kind: str, payload) -> Any:
        if kind == "check_up":
            return True
        if kind == "join":
            dc_id, ring_pairs, member_pairs = payload[:3]
            fabric = payload[3] if len(payload) > 3 else None
            clients = list(payload[4]) if len(payload) > 4 else None
            self.install_cluster(
                dc_id, {int(p): nid for p, nid in ring_pairs},
                {nid: tuple(addr) for nid, addr in member_pairs},
                fabric=fabric, clients=clients)
            return True
        if kind == "gossip":
            nid, vc = payload
            if self.plane is not None:
                self.plane.put_node(nid, vc)
            return None
        if kind == "part":
            if self.node is None:
                raise RemoteCallError("node not assembled yet")
            p, method, args, kwargs = payload
            if method not in PARTITION_METHODS:
                raise RemoteCallError(f"method {method!r} not allowed")
            pm = self.node.partitions[p]
            if not isinstance(pm, PartitionManager):
                raise RemoteCallError(
                    f"partition {p} not owned by {self.node_id!r} "
                    f"(stale ring at {origin!r}?)")
            return getattr(pm, method)(*args, **kwargs)
        if kind == "status":
            return {
                "node_id": self.node_id,
                "assembled": self.node is not None,
                "local_partitions":
                    self.node.local_partition_indices()
                    if self.node else [],
                "stable": dict(self.plane.get_stable_snapshot())
                    if self.plane else {},
            }
        raise RemoteCallError(f"unknown node RPC kind {kind!r}")

    # ------------------------------------------------------------ shutdown

    def close(self) -> None:
        self._stop.set()
        if self._gossip is not None:
            self._gossip.join(timeout=2.0)
        if self.plane is not None:
            self.meta.put("last_stable_vc",
                          dict(self.plane.get_stable_snapshot()))
        self.link.close()
        if self.node is not None:
            self.node.close()


def create_dc_cluster(dc_id, n_partitions: int,
                      servers: List[NodeServer],
                      clients: List[NodeServer] = ()) -> Dict[int, Any]:
    """In-process cluster build: plan the ring over the given servers
    and commit it on each (the antidote_dc_manager:create_dc flow,
    reference src/antidote_dc_manager.erl:53-81).  ``clients`` join as
    coordinator-only members: full API, no ring slots.  For
    cross-process builds, push the same plan via the "join" RPC
    instead."""
    members = {s.node_id: s.addr for s in servers}
    members.update({c.node_id: c.addr for c in clients})
    kinds = {s.fabric_kind() for s in list(servers) + list(clients)}
    if len(kinds) > 1:
        raise RuntimeError(
            f"members run different fabrics {sorted(kinds)!r}; the "
        "framings do not interoperate — align Config.node_fabric")
    ring = plan_ring(n_partitions, [s.node_id for s in servers])
    client_ids = [c.node_id for c in clients]
    for s in list(servers) + list(clients):
        s.install_cluster(dc_id, ring, members, clients=client_ids)
    return ring

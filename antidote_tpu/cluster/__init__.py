"""Multi-process DC: one data center spanning several OS processes.

The reference's DC spans many BEAM nodes — riak_core places partitions
across them, distributed Erlang carries vnode calls and metadata gossip
(reference src/antidote_dc_manager.erl:53-81 staged joins,
src/meta_data_sender.erl:224-255 cross-node gossip,
src/meta_data_manager.erl:64-94 receive side).  This package is the
rebuild's node dimension: a :class:`NodeServer` per OS process, a ring
mapping partitions to nodes, cross-node partition RPC over the node
fabric, and a two-level stable-time plane (per-node tracker fold +
cross-node summary gossip).
"""

from antidote_tpu.cluster.link import NodeLink  # noqa: F401
from antidote_tpu.cluster.node import (  # noqa: F401
    ClusterNode,
    ClusterStablePlane,
    NodeServer,
    create_dc_cluster,
    plan_ring,
)
from antidote_tpu.cluster.federation import (  # noqa: F401
    FederatedDescriptor,
    NodeInterDc,
    connect_federation,
    dc_descriptor,
)
from antidote_tpu.cluster.remote import (  # noqa: F401
    RemoteCallError,
    RemotePartition,
)

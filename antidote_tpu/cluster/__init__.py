"""Multi-process DC: one data center spanning several OS processes.

The reference's DC spans many BEAM nodes — riak_core places partitions
across them, distributed Erlang carries vnode calls and metadata gossip
(reference src/antidote_dc_manager.erl:53-81 staged joins,
src/meta_data_sender.erl:224-255 cross-node gossip,
src/meta_data_manager.erl:64-94 receive side).  This package is the
rebuild's node dimension: a :class:`NodeServer` per OS process, a ring
mapping partitions to nodes, cross-node partition RPC over the node
fabric, and a two-level stable-time plane (per-node tracker fold +
cross-node summary gossip).

Transport selection (ISSUE 12): ``Config.fabric_native`` routes BOTH
hot-path transports — the intra-cluster node fabric
(:func:`~antidote_tpu.cluster.node.build_link`) and the inter-DC
publish fan-out (``interdc.tcp.transport_from_config``) — through one
knob, the ``*_from_config`` factory being the ONE construction path
(concurrency_lint's [knob-routing] rule pins every call site):

====================  =========================  =========================
``fabric_native``     node fabric (intra-DC)     publish fan-out (inter-DC)
====================  =========================  =========================
``"auto"`` (default)  ``NativeNodeLink`` when    C++ hub when built, else
                      the C++ endpoint built,    the staged zero-copy
                      else Python ``NodeLink``   Python fan-out (one
                      (warning logged)           framing, shared views)
``True``              ``NativeNodeLink``;        C++ hub; ``register``
                      ``RuntimeError`` without   raises without a
                      a compiler                 compiler
``False``             Python ``NodeLink``,       legacy per-subscriber
                      bit-for-bit the legacy     framing, bit-for-bit —
                      path                       the bench baseline
====================  =========================  =========================

With no compiler, ``"auto"`` degrades to pure Python everywhere and
everything still works — the native planes are a latency
optimization, never a correctness dependency.  The two wire framings
do not interoperate, so every member of one cluster must resolve to
the same plane (``create_dc_cluster`` refuses a mixed fabric);
Python-NodeLink and native-NodeLink peers still answer
byte-identically (tests/cluster/test_fabric_interop.py), so a
whole-cluster flip of the knob is invisible above the transport.
"""

from antidote_tpu.cluster.link import NodeLink  # noqa: F401
from antidote_tpu.cluster.node import (  # noqa: F401
    ClusterNode,
    ClusterStablePlane,
    NodeServer,
    create_dc_cluster,
    plan_ring,
)
from antidote_tpu.cluster.federation import (  # noqa: F401
    FederatedDescriptor,
    NodeInterDc,
    connect_federation,
    dc_descriptor,
)
from antidote_tpu.cluster.remote import (  # noqa: F401
    RemoteCallError,
    RemotePartition,
)

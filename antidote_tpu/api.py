"""Public API — the surface of the reference's antidote.erl
(reference src/antidote.erl:36-54): start/read/update/commit/abort,
static-transaction variants, get_objects, get_log_operations, and hook
registration, against one DC node.

Bound objects are ``(key, type)`` or ``(key, type, bucket)``; updates are
``(bound_object, op_name, op_param)``; the interactive handle is the
Transaction returned by start_transaction.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.config import Config
from antidote_tpu.crdt import get_type
from antidote_tpu.txn.coordinator import (  # noqa: F401 (re-exported)
    Transaction,
    TransactionAborted,
    TxnProperties,
)
from antidote_tpu.txn.node import Node


class AntidoteTPU:
    """One DC node with the reference's client API."""

    def __init__(self, dc_id="dc1", config: Optional[Config] = None,
                 data_dir: Optional[str] = None,
                 node: Optional[Node] = None):
        self.node = node if node is not None else Node(
            dc_id=dc_id, config=config, data_dir=data_dir)

    # ------------------------------------------------------- interactive txn

    def start_transaction(self, clock: Optional[VC] = None,
                          properties: Optional[TxnProperties] = None
                          ) -> Transaction:
        """Under txn_prot="gr" interactive transactions snapshot at the
        GentleRain scalar GST (reference cure.erl:233-257 applies the
        protocol to every transaction start, not only static reads);
        Clock-SI otherwise."""
        if self.node.config.txn_prot == "gr":
            return self.node.coordinator.start_transaction_gr(
                clock, properties)
        return self.node.coordinator.start_transaction(clock, properties)

    def read_objects(self, objects: List, tx: Transaction) -> List[Any]:
        return self.node.coordinator.read_objects(tx, objects)

    def update_objects(self, updates: List, tx: Transaction) -> None:
        self.node.coordinator.update_objects(tx, updates)

    def commit_transaction(self, tx: Transaction) -> VC:
        return self.node.coordinator.commit_transaction(tx)

    def abort_transaction(self, tx: Transaction) -> None:
        self.node.coordinator.abort_transaction(tx)

    # ------------------------------------------------------------ static txn

    def read_objects_static(self, clock: Optional[VC], objects: List,
                            properties: Optional[TxnProperties] = None
                            ) -> Tuple[List[Any], VC]:
        """One-shot snapshot read (reference cure:obtain_objects fast
        path, src/cure.erl:135-183; reference antidote:read_objects/3
        takes the same txn properties).  Under txn_prot="gr" the
        snapshot is the GentleRain scalar-GST wait instead of the
        Clock-SI max(stable, client) rule (reference src/cure.erl:233-257).

        Fast path (ISSUE 8): when every touched partition is local,
        the read allocates NO interactive transaction — no txid, no
        downstream ctx, no open-transactions gauge, no commit round —
        and goes straight through the read serve plane
        (antidote_tpu/mat/serve.py) at the requested clock, exactly as
        ``cure:obtain_objects`` reads without a coordinator FSM.  A
        reads-only transaction's commit VC is its snapshot, so the
        returned clock is identical to the legacy path's.  Remote ring
        slots (a ClusterNode coordinator) and un-normalizable objects
        fall back to the interactive path, which owns that routing and
        error shape."""
        node = self.node
        plan = self._static_read_plan(objects)
        if plan is None:
            tx = self.start_transaction(clock, properties)
            values = self.read_objects(objects, tx)
            commit_vc = self.commit_transaction(tx)
            return values, commit_vc
        metas, by_pm = plan
        from antidote_tpu import stats
        from antidote_tpu.obs.spans import tracer

        props = properties or TxnProperties()
        coord = node.coordinator
        if node.config.txn_prot == "gr":
            snap = coord.gr_snapshot_wait(
                clock if props.update_clock else None)
        else:
            snap = coord.snapshot_for(clock, props)
        stats.registry.operations.inc(len(objects), type="read")
        tracer.instant("static_read", "coordinator", keys=len(objects))
        # the handoff gate is held for the batch like any txn read: a
        # cutover must not swap the partitions out mid-resolve
        node.txn_gate.enter()
        try:
            from antidote_tpu.mat.serve import read_groups

            values = read_groups(list(by_pm.items()), snap)
        except Exception as e:
            # same error class the legacy path reports for a failed
            # read (there is no transaction here to abort)
            raise TransactionAborted(f"read failed: {e}") from e
        finally:
            node.txn_gate.exit()
        return [cls.value(values[(key, cls.name)])
                for key, cls in metas], snap

    def _static_read_plan(self, objects):
        """(metas, by_pm) when the one-shot read can run on the serve
        fast path — every object normalizable and every partition a
        local PartitionManager; None routes to the interactive path."""
        from antidote_tpu.txn.manager import PartitionManager

        node = self.node
        metas, by_pm = [], {}
        try:
            for bo in objects:
                key, type_name, _bucket = node.normalize_bound(bo)
                cls = get_type(type_name)
                pm = node.partition_of(key)
                if not isinstance(pm, PartitionManager):
                    return None
                metas.append((key, cls))
                by_pm.setdefault(pm, []).append((key, cls.name))
        except Exception:  # noqa: BLE001 — legacy path reports it
            return None
        return metas, by_pm

    def update_objects_static(self, clock: Optional[VC], updates: List,
                              properties: Optional[TxnProperties] = None
                              ) -> VC:
        """One-shot update transaction (reference antidote:update_objects/3)."""
        tx = self.start_transaction(clock, properties)
        self.update_objects(updates, tx)
        return self.commit_transaction(tx)

    # ------------------------------------------------------------- inspection

    def get_objects(self, objects: List, clock: Optional[VC] = None
                    ) -> List[Any]:
        """Latest committed values, no snapshot wait (reference
        antidote:get_objects, src/antidote.erl:69-90)."""
        out = []
        for bo in objects:
            key, type_name, _b = self.node.normalize_bound(bo)
            cls = get_type(type_name)
            pm = self.node.partition_of(key)
            value = pm.value_snapshot(key, type_name, clock)
            out.append(cls.value(value))
        return out

    def get_log_operations(self, object_clock_pairs: List) -> List[List]:
        """Committed log ops per object newer than the given clock
        (reference antidote:get_log_operations)."""
        out = []
        for bo, clock in object_clock_pairs:
            key, _type_name, _b = self.node.normalize_bound(bo)
            pm = self.node.partition_of(key)
            ops = pm.scan_log(
                lambda log: log.committed_payloads(key=key, from_vc=clock))
            out.append([p for _i, p in ops])
        return out

    # ------------------------------------------------------------ admin plane

    def set_flag(self, name: str, value) -> None:
        """Toggle a runtime flag node-wide (reference replicated env
        flags, src/logging_vnode.erl:247-258); DataCenter adds the
        durable + replicated layer."""
        self.node.set_flag(name, value)

    def get_flag(self, name: str):
        return self.node.get_flag(name)

    def create_dc(self, nodes: Optional[List[str]] = None) -> None:
        """Form the DC (reference antidote_dc_manager:create_dc via the
        PB dispatcher, src/antidote_pb_process.erl:102-116).  The
        reference joins the given Erlang nodes into one riak ring; this
        rebuild's DC is a single process that scales through partitions
        and device shards, so forming is recording the membership — a
        list naming anything but this node is rejected rather than
        silently half-honored."""
        me = str(self.node.dc_id)
        nodes = [str(n) for n in (nodes or [me])]
        others = [n for n in nodes if n != me]
        if others:
            raise ValueError(
                f"multi-node DCs are not supported (got {others}); this "
                "DC scales via partitions/device shards — connect "
                "separate DCs with connect_to_dcs instead")

    def start_profiling(self, log_dir: str) -> None:
        """Begin a JAX profiler capture of the node's device work
        (SURVEY §5.1; inspect with TensorBoard/XProf)."""
        from antidote_tpu.obs import prof

        prof.start(log_dir)

    def stop_profiling(self) -> str:
        from antidote_tpu.obs import prof

        return prof.stop()

    def admin_status(self) -> dict:
        """Operator status snapshot (the antidote_console duty,
        reference src/antidote_console.erl:31-60)."""
        node = self.node
        parts = []
        for pm in node.partitions:
            with pm._lock:  # writers mutate these dicts concurrently
                dev = {}
                if pm.device is not None:
                    dev = {t: len(p.key_index)
                           for t, p in pm.device.planes.items()}
                parts.append({
                    "partition": pm.partition,
                    "host_keys": pm.store.entry_count(),
                    "device_keys": dev,
                    "prepared_txns": len(pm.prepared),
                    "log_ops": dict(pm.log.op_counters),
                })
        return {
            "dc_id": node.dc_id,
            "n_partitions": node.config.n_partitions,
            "clock_us": node.clock.now_us(),
            "stable_vc": dict(node.stable_vc()),
            "flags": {n: node.get_flag(n) for n in node.RUNTIME_FLAGS},
            "partitions": parts,
        }

    # ----------------------------------------------------------------- hooks

    def register_pre_hook(self, bucket, hook) -> None:
        self.node.hooks.register_pre_hook(bucket, hook)

    def register_post_hook(self, bucket, hook) -> None:
        self.node.hooks.register_post_hook(bucket, hook)

    def unregister_hook(self, which: str, bucket) -> None:
        self.node.hooks.unregister_hook(which, bucket)

    def close(self) -> None:
        self.node.close()

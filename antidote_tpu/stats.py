"""Ops-plane metrics — the antidote_stats_collector / antidote_error_monitor
equivalent, dependency-free.

The reference defines five Prometheus metrics
(reference src/antidote_stats_collector.erl:80-85) and exposes them over
HTTP :3001 via elli (reference src/antidote_sup.erl:118-128); the same
names and semantics are kept so the packaged Grafana dashboard
(reference monitoring/Antidote-Dashboard.json) reads unchanged:

- ``antidote_error_count``                 counter, bumped by the error
  monitor (reference src/antidote_error_monitor.erl:38-46)
- ``antidote_staleness``                   histogram, ms buckets
  [1, 10, 100, 1000, 10000], sampled every 10 s from the GST
  (reference src/antidote_stats_collector.erl:36-38, 87-93)
- ``antidote_open_transactions``           gauge
- ``antidote_aborted_transactions_total``  counter
- ``antidote_operations_total{type}``      counter by operation type
  (incremented in the coordinator, reference
  src/clocksi_interactive_coord.erl:667, 734, 849, 870, 942, 966)

Exposition is the Prometheus text format served by a stdlib HTTP server
(the elli replacement).
"""

from __future__ import annotations

import bisect
import http.server
import logging
import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple


class _LabeledMetric:
    """Shared labeled-child machinery (label-key construction, locked
    child store, exposition loop) for Counter and LabeledGauge."""

    kind = "untyped"
    #: counters expose a zero sample when childless; gauges expose
    #: nothing until a child exists
    _zero_when_empty = False

    def __init__(self, name: str, help_: str, labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = labels
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict) -> Tuple:
        return tuple(labels.get(n, "") for n in self.label_names)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:
            items = list(self._values.items())
        if not items and self._zero_when_empty:
            items = [((), 0.0)]
        for key, v in items:
            yield f"{self.name}{_fmt_labels(self.label_names, key)} {_fmt(v)}"


class Counter(_LabeledMetric):
    kind = "counter"
    _zero_when_empty = True

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class LabeledGauge(_LabeledMetric):
    """Gauge with label dimensions (the per-DC replication-lag series:
    one child per peer, like client_golang's GaugeVec)."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(v)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._values.get(self._key(labels))

    def remove(self, **labels) -> None:
        """Drop a child series so a departed peer's last sample does
        not expose as a frozen value forever."""
        with self._lock:
            self._values.pop(self._key(labels), None)


class Gauge:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        yield f"{self.name} {_fmt(self.value())}"


class Histogram:
    def __init__(self, name: str, help_: str, buckets: Tuple[float, ...]):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        # bisect_left: first bucket >= v, i.e. the le-semantics bucket;
        # len(buckets) lands on the +Inf tail.  Hot path (stage-latency
        # histograms observe several times per txn) — keep it O(log n)
        # and branch-free under the lock.
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._sum += v
            self._counts[i] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            counts, total = list(self._counts), self._sum
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            yield f'{self.name}_bucket{{le="{_fmt(b)}"}} {cum}'
        cum += counts[-1]
        yield f'{self.name}_bucket{{le="+Inf"}} {cum}'
        yield f"{self.name}_sum {_fmt(total)}"
        yield f"{self.name}_count {cum}"


class LabeledHistogram:
    """Histogram with label dimensions (the per-peer visibility-lag
    family: one child histogram per (dc, peer), like client_golang's
    HistogramVec).  Children share one bucket ladder; exposition emits
    the standard _bucket/_sum/_count triple per child."""

    kind = "histogram"

    def __init__(self, name: str, help_: str, buckets: Tuple[float, ...],
                 labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self.label_names = labels
        self._children: Dict[Tuple, list] = {}
        self._sums: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Dict) -> Tuple:
        return tuple(labels.get(n, "") for n in self.label_names)

    def observe(self, v: float, **labels) -> None:
        i = bisect.bisect_left(self.buckets, v)
        key = self._key(labels)
        with self._lock:
            counts = self._children.get(key)
            if counts is None:
                counts = self._children[key] = \
                    [0] * (len(self.buckets) + 1)
                self._sums[key] = 0.0
            counts[i] += 1
            self._sums[key] += v

    def count(self, **labels) -> int:
        with self._lock:
            return sum(self._children.get(self._key(labels), ()))

    def counts(self, **labels) -> list:
        """Per-bucket raw counts (+Inf tail last) — the monotonicity
        checks in tests read these directly."""
        with self._lock:
            return list(self._children.get(
                self._key(labels), [0] * (len(self.buckets) + 1)))

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            items = [(k, list(c), self._sums[k])
                     for k, c in self._children.items()]
        for key, counts, total in items:
            pairs = [(n, v) for n, v in zip(self.label_names, key)]
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                lbl = _fmt_labels(
                    self.label_names + ("le",), key + (_fmt(b),))
                yield f"{self.name}_bucket{lbl} {cum}"
            cum += counts[-1]
            lbl = _fmt_labels(self.label_names + ("le",), key + ("+Inf",))
            yield f"{self.name}_bucket{lbl} {cum}"
            plain = _fmt_labels(self.label_names, key)
            yield f"{self.name}_sum{plain} {_fmt(total)}"
            yield f"{self.name}_count{plain} {cum}"


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _escape_label(v) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline — exposition-format spec; unescaped values break scrapes)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(names: Tuple[str, ...], values: Tuple) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + pairs + "}"


class Registry:
    """The metric set from reference init_metrics
    (src/antidote_stats_collector.erl:80-85)."""

    def __init__(self):
        self.error_count = Counter(
            "antidote_error_count",
            "The number of error encountered during operation")
        self.staleness = Histogram(
            "antidote_staleness",
            "The staleness of the stable snapshot",
            buckets=(1, 10, 100, 1000, 10000))
        self.open_transactions = Gauge(
            "antidote_open_transactions", "Number of open transactions")
        self.aborted_transactions = Counter(
            "antidote_aborted_transactions_total",
            "Number of aborted transactions")
        self.operations = Counter(
            "antidote_operations_total", "Number of operations executed",
            labels=("type",))
        # ---- stage-latency histograms + replication lag (ISSUE 1):
        # per-plane timing of the txn lifecycle, seconds.  Buckets span
        # 100 µs (a warm device fold) to 5 s (an in-run XLA compile).
        lat_buckets = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05,
                       0.1, 0.5, 1.0, 5.0)
        self.commit_latency = Histogram(
            "antidote_txn_commit_latency_seconds",
            "Commit call latency at the coordinator", buckets=lat_buckets)
        self.log_append_latency = Histogram(
            "antidote_log_append_latency_seconds",
            "Durable commit-record append latency (fsync included when "
            "sync_log)", buckets=lat_buckets)
        self.device_flush_latency = Histogram(
            "antidote_device_flush_latency_seconds",
            "Device-plane append-flush latency per batch",
            buckets=lat_buckets)
        self.device_read_latency = Histogram(
            "antidote_device_read_latency_seconds",
            "Device-plane materialization-fold latency per read",
            buckets=lat_buckets)
        self.depgate_wait = Histogram(
            "antidote_depgate_wait_seconds",
            "Inter-DC txn wait in the dependency gate (enqueue to "
            "apply)", buckets=lat_buckets)
        self.replication_lag = LabeledGauge(
            "antidote_replication_lag_seconds",
            "Local-clock age of the stable snapshot entry per peer DC, "
            "as observed by each local DC (the registry is process-"
            "global and a process may host several DCs)",
            labels=("dc", "peer"))
        # ---- kernel-span layer (ISSUE 2, antidote_tpu/obs/prof.py):
        # per-kernel device-plane timing, compile-cache misses, and the
        # buffer census.  Dispatch buckets reach down to 10 µs (a warm
        # dispatch is host-side only); the completion histogram shares
        # the stage-latency bucket ladder.
        self.kernel_dispatch_latency = Histogram(
            "antidote_kernel_dispatch_latency_seconds",
            "Host wall time to dispatch one profiled device kernel "
            "(async: excludes device execution)",
            buckets=(0.00001, 0.0001, 0.0005, 0.001, 0.005, 0.01,
                     0.05, 0.1, 0.5, 1.0, 5.0))
        self.kernel_complete_latency = Histogram(
            "antidote_kernel_complete_latency_seconds",
            "Dispatch-to-completion wall time of profiled kernels, "
            "measured by a scalar device->host fetch (sampled txns, "
            "detail mode, and open captures only)", buckets=lat_buckets)
        self.kernel_calls = Counter(
            "antidote_kernel_calls_total",
            "Profiled device-kernel dispatches",
            labels=("kernel", "subsystem"))
        self.kernel_compile_misses = Counter(
            "antidote_kernel_compile_cache_misses_total",
            "First dispatches at a new abstract shape per kernel (each "
            "one is an XLA compile; a storm here explains p99 spikes)",
            labels=("kernel",))
        self.device_buffer_hwm = LabeledGauge(
            "antidote_device_buffer_bytes_high_watermark",
            "High-watermark of the LARGEST single state pytree a "
            "subsystem's kernels have returned (a lower bound on its "
            "footprint; /debug/prof's live-buffer census is the total)",
            labels=("subsystem",))
        # ---- device dependency-gate ring (ISSUE 3,
        # antidote_tpu/interdc/dep.py + gate_kernels.py): the batched
        # gate path's dispatch/byte economy.  The ratio of admitted
        # txns to dispatches (and H2D bytes to admitted txns) is the
        # amortization the resident ring buys over per-pass repack —
        # the quantity the steady-stream bench gates on.
        self.gate_dispatches = Counter(
            "antidote_gate_device_dispatches_total",
            "Device dispatches by the dependency gate's batched path "
            "(fixpoint / append / retire / gather ring re-layout)",
            labels=("kind",))
        self.gate_h2d_bytes = Counter(
            "antidote_gate_h2d_bytes_total",
            "Host-to-device bytes uploaded by the gate's batched path "
            "(arrival batches, retire/gather index vectors, per-"
            "dispatch partition clocks)")
        self.gate_d2h_bytes = Counter(
            "antidote_gate_d2h_bytes_total",
            "Device-to-host bytes fetched by the gate's batched path "
            "(the scalar applied-count always; the dense applied mask "
            "+ rounds only when a wave admitted txns)")
        self.gate_admitted_batched = Counter(
            "antidote_gate_admitted_txns_total",
            "Transactions and heartbeats admitted through the batched "
            "device gate path")
        self.gate_coalesced = Counter(
            "antidote_gate_coalesced_enqueues_total",
            "Enqueues absorbed by the gate's coalescing window (staged "
            "for the next dispatch instead of triggering their own)")
        self.gate_ring_rebuilds = Counter(
            "antidote_gate_ring_rebuilds_total",
            "Full device-ring (re)builds — first use or invalidation; "
            "growth/compaction re-layouts are `gather` dispatches")
        self.gate_admitted_per_dispatch = Gauge(
            "antidote_gate_admitted_per_dispatch",
            "Amortization ratio of the batched gate path: admitted "
            "txns per device dispatch over the process lifetime")
        # ---- coalesced materializer ingest (ISSUE 4,
        # antidote_tpu/mat/ingest.py): the shard stores' staging
        # economy — one packed H2D per flush instead of ~10 per-column
        # uploads, with a coalescing window and row budget.  The
        # ops-per-dispatch gauge (and H2D bytes per op derived from
        # these counters) is what the mvreg/RGA bench rows gate on.
        self.ingest_flushes = Counter(
            "antidote_ingest_flushes_total",
            "Materializer ingest flushes by trigger kind (rows "
            "threshold / coalescing window / row-budget backpressure / "
            "read / gc horizon / capacity grow / explicit)",
            labels=("kind",))
        self.ingest_dispatches = Counter(
            "antidote_ingest_device_dispatches_total",
            "Packed-append device dispatches by the coalesced ingest "
            "plane (one per flush chunk; the legacy per-column path "
            "does not count here — it is the comparison baseline)")
        self.ingest_coalesced_ops = Counter(
            "antidote_ingest_coalesced_ops_total",
            "Ops applied through packed coalesced flushes")
        self.ingest_h2d_bytes = Counter(
            "antidote_ingest_h2d_bytes_total",
            "Host-to-device bytes uploaded by packed ingest flushes "
            "(one tensor per dispatch)")
        self.ingest_ops_per_dispatch = Gauge(
            "antidote_ingest_ops_per_dispatch",
            "Amortization ratio of the coalesced ingest plane: ops "
            "per packed device dispatch over the process lifetime")
        # ---- batched inter-DC shipping plane (ISSUE 6,
        # antidote_tpu/interdc/sender.py + wire.py): the wire's frame
        # and byte economy.  Txns per batch frame (up) and encoded
        # bytes per shipped txn (down) are the amortization the
        # steady-stream replication bench gates on.
        self.ship_frames = Counter(
            "antidote_ship_frames_total",
            "Inter-DC pub/sub frames published, by kind (batch = the "
            "ship plane's coalesced frame, txn = legacy per-txn, "
            "ping = standalone heartbeat)",
            labels=("kind",))
        self.ship_txns = Counter(
            "antidote_ship_txns_total",
            "Committed transactions shipped through batch frames")
        self.ship_bytes = Counter(
            "antidote_ship_wire_bytes_total",
            "Encoded wire bytes of txn-carrying frames (batch + legacy "
            "per-txn, partition prefix included; standalone pings are "
            "not txn-carrying and count only in ship_frames)")
        self.ship_piggybacked_pings = Counter(
            "antidote_ship_piggybacked_pings_total",
            "Heartbeats that rode a batch frame instead of paying "
            "their own standalone ping frame")
        self.ship_queue_depth = LabeledGauge(
            "antidote_ship_queue_depth",
            "Committed txns staged in a stream's ship buffer, awaiting "
            "the async sender thread",
            labels=("dc", "partition"))
        self.ship_txns_per_frame = Gauge(
            "antidote_ship_txns_per_frame",
            "Amortization ratio of the shipping plane: txns per "
            "published batch frame over the process lifetime")
        self.ship_bytes_per_txn = Gauge(
            "antidote_ship_wire_bytes_per_txn",
            "Encoded wire bytes per shipped txn over the process "
            "lifetime (txn-carrying frames only)")
        self.ship_subscriber_send = LabeledGauge(
            "antidote_ship_subscriber_send_seconds",
            "Duration of the most recent pub-frame send to each TCP "
            "subscriber (Python fan-out mode).  The per-subscriber "
            "loop is serial, so one slow peer delays every later one "
            "— a climbing series here is the publish-stall ROADMAP "
            "flags before it bites a many-peer mesh",
            labels=("peer",))
        # ---- transaction-journey / visibility plane (ISSUE 7):
        # commit-at-origin -> causally-visible-at-remote is the
        # quantity Cure/GentleRain optimize; these families make it a
        # first-class SLO.  The lag histogram is observed at ingest-
        # visibility time (dependency-gate apply) from the origin
        # commit wallclock the wire now carries; buckets span 1 ms (in-
        # process delivery) to 60 s (a partitioned peer catching up).
        vis_buckets = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                       1.0, 5.0, 15.0, 60.0)
        self.vis_lag = LabeledHistogram(
            "antidote_vis_visibility_lag_seconds",
            "Origin-commit wallclock to local ingest-visibility "
            "(dependency-gate apply) per replicated txn, as observed "
            "by each local DC (dc) per origin peer (peer)",
            buckets=vis_buckets, labels=("dc", "peer"))
        self.vis_safe_time_lag = LabeledGauge(
            "antidote_vis_safe_time_lag_seconds",
            "Local-clock age of each partition's safe/stable time "
            "(the min entry of its dep-gate watermark + min-prepared "
            "vector) — the GST lag a causal read may wait on",
            labels=("dc", "partition"))
        self.vis_probe_staleness = Histogram(
            "antidote_vis_probe_staleness_seconds",
            "Observed write->remote-causal-read round-trip staleness "
            "of the causal-probe auditor (antidote_tpu/obs/probe.py)",
            buckets=vis_buckets)
        self.vis_probe_violations = Counter(
            "antidote_vis_probe_violations_total",
            "Causal-order violations the probe auditor observed (a "
            "causal read at the probe write's commit clock missed the "
            "element); each one dumps the flight recorder")
        # ---- coalesced read serve plane (ISSUE 8,
        # antidote_tpu/mat/serve.py): the serving side of the ingest
        # plane's economy.  Fewer fold dispatches per served key (and
        # more waiters per drain fold) is the amortization the hot-
        # shard read bench gates on; the cache counters feed its hit-
        # ratio row.
        self.read_dispatches = Counter(
            "antidote_read_device_dispatches_total",
            "Device fold captures on the serving read path (each is "
            "at least one XLA program; legacy per-txn reads count "
            "here too — the serve plane's amortization is fewer of "
            "these per served key)")
        self.read_serve_groups = Counter(
            "antidote_read_serve_groups_total",
            "Snapshot-compatible drain groups folded by the read "
            "serve plane (one gathered dispatch each)")
        self.read_serve_waiters = Counter(
            "antidote_read_serve_waiters_total",
            "Concurrent read calls served through the coalescing "
            "window (N waiters sharing one drain group cost one fold "
            "instead of N)")
        self.read_coalesced_keys = Counter(
            "antidote_read_coalesced_keys_total",
            "Key reads served by serve-plane drain groups (waiter-"
            "keys, not unique keys: N waiters of one hot key count N)")
        self.read_cache_hits = Counter(
            "antidote_read_cache_hits_total",
            "Snapshot reads served from the frontier-keyed value "
            "cache (no materialization at all)")
        self.read_cache_misses = Counter(
            "antidote_read_cache_misses_total",
            "Snapshot reads that missed the value cache and paid a "
            "materialization (device fold / host store / log replay)")
        self.read_waiters_per_dispatch = Gauge(
            "antidote_read_waiters_per_dispatch",
            "Amortization ratio of the read serve plane: waiters "
            "served per drain-group fold over the process lifetime")
        # ---- group-commit durable-log plane (ISSUE 9,
        # antidote_tpu/oplog/log.py): the commit path's disk economy.
        # Records made durable per fsync (up) is the amortization the
        # group-commit bench gates on; the sync-wait histogram is what
        # a committer pays between releasing the partition lock and its
        # durability ticket being covered.
        self.log_fsyncs = Counter(
            "antidote_log_fsyncs_total",
            "Durability fsyncs executed by the durable log (group-"
            "commit drains and legacy per-commit syncs both count)")
        self.log_group_records = Counter(
            "antidote_log_group_records_total",
            "Log records whose durability a group-commit drain newly "
            "covered (updates/prepares riding a commit's fsync count)")
        self.log_group_drains = Counter(
            "antidote_log_group_drains_total",
            "Group-commit drains by kind (solo = no other committer "
            "waiting, drained immediately; held = the leader kept the "
            "window open for company)",
            labels=("kind",))
        self.log_group_size = Histogram(
            "antidote_log_group_size_records",
            "Records made durable per group-commit drain",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 1024))
        self.log_sync_wait = Histogram(
            "antidote_log_sync_wait_seconds",
            "Commit-path wait from durability-ticket issue (partition "
            "lock already released) to the synced watermark covering "
            "it", buckets=lat_buckets)
        self.log_staged_records = Gauge(
            "antidote_log_staged_records",
            "Log records currently staged (framed, not yet written "
            "through the backend) across every open durable log")
        self.log_records_per_fsync = Gauge(
            "antidote_log_records_per_fsync",
            "Amortization ratio of the group-commit plane: records "
            "made durable per fsync over the process lifetime")
        # ---- checkpoint + log-truncation plane (ISSUE 10,
        # antidote_tpu/oplog/checkpoint.py): the cold-path economy.
        # Retained/file byte gauges are what makes on-disk log growth
        # observable at all (nothing reported it before); checkpoint
        # age is the recovery-cost bound an operator alarms on (the
        # suffix a restart replays grows with it).
        self.log_retained_bytes = LabeledGauge(
            "antidote_log_retained_bytes",
            "Logical log bytes above the truncation base per "
            "partition (what recovery's suffix scan can still read)",
            labels=("partition",))
        self.log_file_bytes = LabeledGauge(
            "antidote_log_file_bytes",
            "On-disk log file size per partition (retained records "
            "plus the truncation marker)", labels=("partition",))
        self.log_truncated_bytes = Counter(
            "antidote_log_truncated_bytes_total",
            "Logical log bytes reclaimed by checkpoint truncation")
        self.ckpt_writes = Counter(
            "antidote_ckpt_writes_total",
            "Checkpoint documents atomically persisted")
        self.ckpt_duration = Histogram(
            "antidote_ckpt_duration_seconds",
            "Wall time of one checkpoint write (fold + pickle + fsync "
            "+ rename)", buckets=lat_buckets)
        self.ckpt_age = LabeledGauge(
            "antidote_ckpt_age_seconds",
            "Age of the partition's newest checkpoint (the recovery "
            "suffix a restart replays grows with this)",
            labels=("partition",))
        self.ckpt_keys = LabeledGauge(
            "antidote_ckpt_keys",
            "Materialized key seeds carried by the partition's newest "
            "checkpoint", labels=("partition",))
        self.ckpt_truncations = Counter(
            "antidote_ckpt_truncations_total",
            "Log truncations performed after checkpoint writes")
        self.ckpt_bootstraps = Counter(
            "antidote_ckpt_bootstraps_total",
            "SubBuf checkpoint-state bootstraps (a gap repair answered "
            "BELOW_FLOOR and the stream re-seeded from the origin's "
            "checkpoint instead of wedging in repair retries)")
        self.ckpt_recovery = Histogram(
            "antidote_ckpt_recovery_seconds",
            "Per-partition recovery wall time at boot (checkpoint "
            "load + suffix replay; the recovery-time trend panel)",
            buckets=lat_buckets + (30.0, 120.0))
        # ---- segmented checkpoint engine (ISSUE 13,
        # antidote_tpu/oplog/checkpoint.py): persist cost tracks
        # churn, not keyspace — the CKPT_SEG_* families watch the
        # segment economy (count/bytes/dead fraction), the compaction
        # cadence, the headline us-per-dirty-key amortization, and how
        # many seeds a restart re-installed device-resident (the
        # re-earned device economy)
        self.ckpt_seg_count = LabeledGauge(
            "antidote_ckpt_seg_count",
            "Seed segments listed by the partition's newest "
            "checkpoint manifest", labels=("partition",))
        self.ckpt_seg_bytes = LabeledGauge(
            "antidote_ckpt_seg_bytes",
            "Total on-disk bytes across the partition's live seed "
            "segments", labels=("partition",))
        self.ckpt_seg_dead_frac = LabeledGauge(
            "antidote_ckpt_seg_dead_frac",
            "Superseded-entry fraction across the partition's seed "
            "segments (compaction triggers past "
            "Config.ckpt_seg_waste_frac)", labels=("partition",))
        self.ckpt_seg_compactions = Counter(
            "antidote_ckpt_seg_compactions_total",
            "Segment compactions (live seeds folded into one fresh "
            "segment on the checkpointing thread)")
        self.ckpt_seg_persist_us_per_key = Gauge(
            "antidote_ckpt_seg_persist_us_per_dirty_key",
            "Microseconds the last segmented persist paid per dirty "
            "key (segment pickle + fsync + manifest; the "
            "churn-proportional headline the bench gates)")
        self.ckpt_seed_device_keys = Counter(
            "antidote_ckpt_seed_device_keys_total",
            "Checkpoint seeds installed as device-resident bases at "
            "recovery (previously device-resident keys serving from "
            "the device again instead of pinning host-path)")
        # ---- native node fabric + zero-copy publish fan-out (ISSUE
        # 12, cluster/nativelink.py + interdc/tcp.py): the GIL-free
        # answer plane's hit economy and the one-staging publish
        # discipline.  fabric_native_answered / fabric_published are
        # gauges PULLED from the C++ endpoint's counters (the native
        # answers never enter Python, so nothing Python-side can
        # increment a Counter for them) — refreshed by the NodeServer
        # gossip tick and every /debug/pipeline read.
        self.fabric_native_answered = Gauge(
            "antidote_fabric_native_answered_total",
            "Node RPCs answered by the C++ event thread from the "
            "published-answer table — the GIL was never taken")
        self.fabric_py_answers = Counter(
            "antidote_fabric_py_answered_total",
            "PUBLISHABLE node RPCs (the answer policy would cache "
            "them) that entered the interpreter anyway — the "
            "per-served-read GIL-entry counter; never-publishable "
            "kinds (writes, gossip, 2PC) are excluded so the "
            "native/py ratio is the answer plane's true hit rate",
            labels=("kind",))
        self.fabric_published = Gauge(
            "antidote_fabric_published_answers",
            "Live entries in the endpoint's published-answer table")
        self.pub_frames = Counter(
            "antidote_fabric_pub_frames_total",
            "Inter-DC frames published through the fan-out plane — "
            "the copies-per-frame denominator (the staged/native "
            "paths frame each ONCE regardless of subscriber count; "
            "the legacy path re-frames per subscriber)")
        self.pub_sub_copies = Counter(
            "antidote_fabric_pub_subscriber_copies_total",
            "Python-side per-subscriber frame copies on the publish "
            "path — zero on the staged/native paths; the legacy "
            "fabric_native=False path pays one per subscriber (the "
            "bench baseline, gated via fabric_pub_copies_per_frame)")
        self.pub_fanout = Gauge(
            "antidote_fabric_pub_fanout",
            "Subscribers the most recent published frame was staged "
            "to (the staged frame's refcount)")
        self.pub_queue_depth = LabeledGauge(
            "antidote_fabric_pub_queue_depth",
            "Per-subscriber send-queue depth (frames) on the Python "
            "fan-out plane; the native hub's analogue is its bounded "
            "byte queue, exposed as fabric_hub_queued_bytes",
            labels=("peer",))
        self.hub_queued_bytes = Gauge(
            "antidote_fabric_hub_queued_bytes",
            "Bytes queued across the native publish hub's "
            "per-subscriber bounded queues")
        # ---- NATIVE_* telemetry families (ISSUE 16, obs/nativeobs.py):
        # folded from the C++ flight-recorder rings on the existing
        # 50 ms gauge cadence — the observability face of the paths PR
        # 11 moved off the GIL.  Buckets reach down to 1 µs: a native
        # answer is a hash lookup + queue push, orders of magnitude
        # under the stage-latency ladder's 100 µs floor.
        native_buckets = (0.000001, 0.000005, 0.00001, 0.00005,
                          0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05)
        self.native_answer_latency = LabeledHistogram(
            "antidote_native_answer_latency_seconds",
            "C++ event-thread serve time per natively answered RPC "
            "(key build + table lookup + reply queue), by rpc kind — "
            "the latency face of fabric_native_answered's flat count",
            buckets=native_buckets, labels=("kind",))
        self.native_pub_stage = Histogram(
            "antidote_native_pub_stage_seconds",
            "Native hub frame staging duration (one framing copy + "
            "per-subscriber refcount pushes, under the hub mutex)",
            buckets=native_buckets)
        self.native_sub_queue_wait = Histogram(
            "antidote_native_sub_queue_wait_seconds",
            "Enqueue-to-last-byte-written time per subscriber frame "
            "on the native hub (queue wait + socket send)",
            buckets=native_buckets + (0.1, 0.5, 1.0))
        self.native_frame_age = Gauge(
            "antidote_native_frame_age_seconds",
            "Age of the oldest frame still queued on any native-hub "
            "subscriber at the last telemetry drain (0 = queues "
            "empty) — a rising value means a peer is draining slower "
            "than the stream publishes")
        self.native_sub_enqueued = Counter(
            "antidote_native_sub_enqueued_total",
            "Per-subscriber frame enqueues on the native hub (the "
            "fan-out numerator: enqueues / pub_frames = live fan-out)")
        self.native_sub_dropped = Counter(
            "antidote_native_sub_dropped_total",
            "Subscribers dropped by the native hub for queue overflow "
            "— each drop event's forensics (last-frame identity hash, "
            "publish seq) land in the flight recorder")
        self.native_ring_dropped = LabeledGauge(
            "antidote_native_ring_dropped_total",
            "Cumulative telemetry events lost to ring overwrite per "
            "native ring (the consumer lagged the producer) — "
            "telemetry loss is a statistic here, never backpressure",
            labels=("ring",))
        self.native_heartbeat_age = LabeledGauge(
            "antidote_native_heartbeat_age_seconds",
            "Wall-clock age of each native event thread's last "
            "heartbeat at the last telemetry drain; the stall "
            "watchdog force-dumps the flight recorder past "
            "Config.native_watchdog_s",
            labels=("ring",))

        # ---- bounded-counter rights economy (ISSUE 17, bcounter.py)
        # the rights-transfer protocol is the first thing cross-DC
        # chaos breaks, so it must be observable before chaos exists
        self.bcounter_rights_held = LabeledGauge(
            "antidote_bcounter_rights_held",
            "Last-observed local decrement rights per DC (bounded "
            "counters): available permissions after the most recent "
            "local decrement or denial",
            labels=("dc",))
        self.bcounter_denials = Counter(
            "antidote_bcounter_denials_total",
            "Bounded-counter decrements aborted no_permissions — "
            "each denial queues a rights-transfer request")
        self.bcounter_transfer_requests = Counter(
            "antidote_bcounter_transfer_requests_total",
            "Rights-transfer requests sent to remote DCs, labelled "
            "by the peer asked (the richest known holder)",
            labels=("peer",))
        self.bcounter_transfers_granted = Counter(
            "antidote_bcounter_transfers_granted_total",
            "Rights transfers this DC granted to remote requesters, "
            "labelled by requester",
            labels=("peer",))
        self.bcounter_grace_suppressed = Counter(
            "antidote_bcounter_grace_suppressed_total",
            "Remote rights requests refused because the same "
            "requester was granted within the grace period "
            "(duplicate-request shedding, not a denial of rights)")
        self.bcounter_grace_expiries = Counter(
            "antidote_bcounter_grace_expiries_total",
            "Grace-period entries expired by the periodic transfer "
            "pass — each expiry re-opens a requester for grants")

        # ---- interest-routed replication (ISSUE 18,
        # interdc/interest.py + interdc/sender.py): the filtered
        # fan-out's wire economy.  Full-stream clusters must read all
        # zeros here — interest_slices_per_frame's zero IS the bench
        # contract, like the ISSUE-12 copies-per-frame gauge.
        self.interest_peer_ranges = LabeledGauge(
            "antidote_interest_peer_subscribed_ranges",
            "Key ranges in the interest spec each subscribed peer "
            "announced in its hello (absent peer = spec-less = full "
            "stream)",
            labels=("peer",))
        self.interest_frames = Counter(
            "antidote_interest_frames_total",
            "Published frames that went through interest slicing — "
            "the slice-buffers-per-frame denominator")
        self.interest_slice_buffers = Counter(
            "antidote_interest_slice_buffers_total",
            "Per-interest-class staged buffers cut across all sliced "
            "frames (subscribers sharing a spec share one buffer)")
        self.interest_slices_per_frame = Gauge(
            "antidote_interest_slice_buffers_per_frame",
            "Running slice buffers per sliced frame — 0 on a "
            "full-stream cluster (the staged-once contract's "
            "one-buffer baseline; bench-gated at zero)")
        self.interest_filtered_txns = Counter(
            "antidote_interest_filtered_txns_total",
            "Txns elided from at least one interest-class slice "
            "(summed per class: a txn skipped by 3 classes counts 3)")
        self.interest_filtered_bytes = Counter(
            "antidote_interest_filtered_bytes_total",
            "Encoded bytes NOT shipped thanks to slicing, summed "
            "over interest classes vs the full staged frame")
        self.interest_backfills = Counter(
            "antidote_interest_backfills_total",
            "Gap-repair / bootstrap fetches issued with an interest "
            "filter attached — interest widening converges through "
            "these (docs/interest_routing.md §3)")

        # ---- elastic keyspace (ISSUE 19, docs/resharding.md):
        # checkpoint-seeded resizes + streamed segment bootstrap
        self.ckpt_seg_ship_retries = Counter(
            "antidote_ckpt_seg_ship_retries_total",
            "Donor-side bundle reads retried past a concurrent "
            "compaction (the bounded jittered retry that used to be "
            "a log-only warning)")
        self.ckpt_seg_pull_retries = Counter(
            "antidote_ckpt_seg_pull_retries_total",
            "Handoff receiver bundle pulls retried past a transient "
            "donor failure")
        self.reshard_resizes = Counter(
            "antidote_reshard_resizes_total",
            "Ring resizes / partition splits+merges completed")
        self.reshard_seeded_slots = Counter(
            "antidote_reshard_seeded_slots_total",
            "Old slots folded checkpoint-seeded (seeds + suffix "
            "replay, O(delta)) during a resize")
        self.reshard_full_fold_slots = Counter(
            "antidote_reshard_full_fold_slots_total",
            "Old slots folded from log offset 0 during a resize (no "
            "adopted checkpoint, or resize_from_ckpt off)")
        self.reshard_moved_keys = Counter(
            "antidote_reshard_moved_keys_total",
            "Checkpoint seed entries routed to new slots by resizes")
        self.reshard_replayed_txns = Counter(
            "antidote_reshard_replayed_txns_total",
            "Suffix transactions replayed into staged logs by "
            "resizes — the O(delta) term a seeded fold pays instead "
            "of full history")
        self.reshard_duration = Histogram(
            "antidote_reshard_fold_seconds",
            "Wall seconds of one resize fold+swap",
            buckets=(.01, .05, .1, .5, 1, 5, 30, 120))
        self.stream_manifest_fetches = Counter(
            "antidote_stream_manifest_fetches_total",
            "Bundle manifests fetched by streamed transfers (handoff "
            "pulls + CKPT_READ bootstraps)")
        self.stream_seg_fetches = Counter(
            "antidote_stream_seg_fetches_total",
            "Segments fetched, validated, and acked by streamed "
            "transfers")
        self.stream_seg_bytes = Counter(
            "antidote_stream_seg_bytes_total",
            "Segment bytes fetched and acked by streamed transfers")
        self.stream_torn_fetches = Counter(
            "antidote_stream_torn_fetches_total",
            "Segment fetches refused at the cursor (torn/short/CRC "
            "mismatch) — each one resumed at the last acked segment")
        self.stream_restarts = Counter(
            "antidote_stream_restarts_total",
            "Streamed transfers restarted because the donor's "
            "manifest changed under the cursor (re-cut, compaction, "
            "or a different donor after a kill)")
        self.stream_resume_refetch_bytes = Counter(
            "antidote_stream_resume_refetch_bytes_total",
            "Previously acked segment bytes discarded by cursor "
            "restarts — the numerator of the bench's "
            "bootstrap_resume_refetch_pct")

        # ---- fleet health plane (ISSUE 17, obs/fleet.py + obs/slo.py)
        self.vis_probe_rtt = LabeledGauge(
            "antidote_vis_probe_rtt_seconds",
            "Last causal-probe write-to-read round-trip per "
            "(dc, peer) — the per-peer attribution the global "
            "staleness histogram cannot give",
            labels=("dc", "peer"))
        self.fleet_scrape_age = Gauge(
            "antidote_fleet_scrape_age_seconds",
            "Realized gap between the last two fleet scrapes — a "
            "wedged scrape loop freezes this gauge")
        self.fleet_sources = Gauge(
            "antidote_fleet_sources",
            "Sources merged into the last fleet snapshot (local + "
            "reachable remote endpoints)")
        self.fleet_scrape_errors = Counter(
            "antidote_fleet_scrape_errors_total",
            "Fleet scrape failures per unreachable source endpoint",
            labels=("source",))
        self.slo_burn_rate = LabeledGauge(
            "antidote_slo_burn_rate",
            "Error-budget burn rate per SLO objective from the last "
            "evaluation (1.0 = budget exactly spent; obs/slo.py)",
            labels=("objective",))
        self.slo_budget_remaining = LabeledGauge(
            "antidote_slo_error_budget_remaining",
            "Remaining error-budget fraction per SLO objective from "
            "the last evaluation (max(0, 1 - burn_rate))",
            labels=("objective",))
        self.slo_ok = LabeledGauge(
            "antidote_slo_ok",
            "1 when the SLO objective met its burn threshold at the "
            "last evaluation, 0 when it breached",
            labels=("objective",))

        # ---- pod-scale sharded materializer (ISSUE 20,
        # mat/sharded.py + mat/device_plane.py place_sharded): the
        # mesh-sharded live keyspace's residency economy and the
        # fused cross-chip serve plane
        self.shard_resident_keys = LabeledGauge(
            "antidote_shard_resident_keys",
            "Device-resident keys per mesh shard (contiguous key "
            "ranges under the P('part') layout) — refreshed on every "
            "device GC sweep",
            labels=("shard",))
        self.shard_evictions = Counter(
            "antidote_shard_evictions_total",
            "Keys evicted to the host path per owning shard (only "
            "the owning shard's range migrates; the per-shard "
            "routing economy's saturation signal)",
            labels=("shard",))
        self.shard_fused_group_dispatches = Counter(
            "antidote_shard_fused_group_dispatches_total",
            "Cross-chip fused group-read programs launched (one per "
            "serve-window drain on the sharded path — the O(groups) "
            "-> O(1) dispatch economy)")
        self.shard_serve_drains = Counter(
            "antidote_shard_serve_drains_total",
            "Serve-window drains that went through the cross-group "
            "fused dispatch accounting (the dispatches-per-drain "
            "denominator)")
        self.shard_read_dispatches_per_drain = Gauge(
            "antidote_shard_read_dispatches_per_drain",
            "Device read programs dispatched by the most recent "
            "serve-window drain (fused cross-group reads hold this "
            "at O(1); the unfused path pays one per group)")
        self.shard_collective_seconds = Counter(
            "antidote_shard_collective_seconds_total",
            "Wall seconds inside mesh-collective dispatches "
            "(append/GC/read programs under COLLECTIVE_LOCK, lock "
            "wait included — the cross-chip serialization cost)")
        self.shard_device_resident_pct = Gauge(
            "antidote_shard_device_resident_pct",
            "Percent of ever-seen keys currently device-resident "
            "across all shards (100 * resident / (resident + "
            "host_only)) — the per-shard routing economy's headline")

    def metrics(self):
        return (self.error_count, self.staleness, self.open_transactions,
                self.aborted_transactions, self.operations,
                self.commit_latency, self.log_append_latency,
                self.device_flush_latency, self.device_read_latency,
                self.depgate_wait, self.replication_lag,
                self.kernel_dispatch_latency, self.kernel_complete_latency,
                self.kernel_calls, self.kernel_compile_misses,
                self.device_buffer_hwm,
                self.gate_dispatches, self.gate_h2d_bytes,
                self.gate_d2h_bytes, self.gate_admitted_batched,
                self.gate_coalesced, self.gate_ring_rebuilds,
                self.gate_admitted_per_dispatch,
                self.ingest_flushes, self.ingest_dispatches,
                self.ingest_coalesced_ops, self.ingest_h2d_bytes,
                self.ingest_ops_per_dispatch,
                self.ship_frames, self.ship_txns, self.ship_bytes,
                self.ship_piggybacked_pings, self.ship_queue_depth,
                self.ship_txns_per_frame, self.ship_bytes_per_txn,
                self.ship_subscriber_send,
                self.vis_lag, self.vis_safe_time_lag,
                self.vis_probe_staleness, self.vis_probe_violations,
                self.read_dispatches, self.read_serve_groups,
                self.read_serve_waiters, self.read_coalesced_keys,
                self.read_cache_hits, self.read_cache_misses,
                self.read_waiters_per_dispatch,
                self.log_fsyncs, self.log_group_records,
                self.log_group_drains, self.log_group_size,
                self.log_sync_wait, self.log_staged_records,
                self.log_records_per_fsync,
                self.log_retained_bytes, self.log_file_bytes,
                self.log_truncated_bytes, self.ckpt_writes,
                self.ckpt_duration, self.ckpt_age, self.ckpt_keys,
                self.ckpt_truncations, self.ckpt_bootstraps,
                self.ckpt_recovery,
                self.ckpt_seg_count, self.ckpt_seg_bytes,
                self.ckpt_seg_dead_frac, self.ckpt_seg_compactions,
                self.ckpt_seg_persist_us_per_key,
                self.ckpt_seed_device_keys,
                self.fabric_native_answered, self.fabric_py_answers,
                self.fabric_published, self.pub_frames,
                self.pub_sub_copies, self.pub_fanout,
                self.pub_queue_depth, self.hub_queued_bytes,
                self.native_answer_latency, self.native_pub_stage,
                self.native_sub_queue_wait, self.native_frame_age,
                self.native_sub_enqueued, self.native_sub_dropped,
                self.native_ring_dropped, self.native_heartbeat_age,
                self.bcounter_rights_held, self.bcounter_denials,
                self.bcounter_transfer_requests,
                self.bcounter_transfers_granted,
                self.bcounter_grace_suppressed,
                self.bcounter_grace_expiries,
                self.interest_peer_ranges, self.interest_frames,
                self.interest_slice_buffers,
                self.interest_slices_per_frame,
                self.interest_filtered_txns,
                self.interest_filtered_bytes,
                self.interest_backfills,
                self.ckpt_seg_ship_retries, self.ckpt_seg_pull_retries,
                self.reshard_resizes, self.reshard_seeded_slots,
                self.reshard_full_fold_slots, self.reshard_moved_keys,
                self.reshard_replayed_txns, self.reshard_duration,
                self.stream_manifest_fetches, self.stream_seg_fetches,
                self.stream_seg_bytes, self.stream_torn_fetches,
                self.stream_restarts, self.stream_resume_refetch_bytes,
                self.vis_probe_rtt,
                self.fleet_scrape_age, self.fleet_sources,
                self.fleet_scrape_errors,
                self.slo_burn_rate, self.slo_budget_remaining,
                self.slo_ok,
                self.shard_resident_keys, self.shard_evictions,
                self.shard_fused_group_dispatches,
                self.shard_serve_drains,
                self.shard_read_dispatches_per_drain,
                self.shard_collective_seconds,
                self.shard_device_resident_pct)

    def exposition(self) -> str:
        lines = []
        for m in self.metrics():
            lines.extend(m.expose())
        lines.extend(process_metrics())
        return "\n".join(lines) + "\n"


def process_metrics() -> list:
    """Process-level gauges from /proc — the
    prometheus_process_collector role (reference rebar.config dep;
    standard process_* metric names).  Empty off Linux."""
    out = []
    try:
        with open("/proc/self/stat") as f:
            parts = f.read().split()
        tick = os.sysconf("SC_CLK_TCK")
        page = os.sysconf("SC_PAGE_SIZE")
        utime, stime = int(parts[13]), int(parts[14])
        vsize, rss_pages = int(parts[22]), int(parts[23])
        start_ticks = int(parts[21])
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        out += [
            "# TYPE process_cpu_seconds_total counter",
            f"process_cpu_seconds_total {(utime + stime) / tick:.3f}",
            "# TYPE process_virtual_memory_bytes gauge",
            f"process_virtual_memory_bytes {vsize}",
            "# TYPE process_resident_memory_bytes gauge",
            f"process_resident_memory_bytes {rss_pages * page}",
            "# TYPE process_start_time_seconds gauge",
            f"process_start_time_seconds "
            f"{time.time() - uptime + start_ticks / tick:.3f}",
        ]
        out += [
            "# TYPE process_open_fds gauge",
            f"process_open_fds {len(os.listdir('/proc/self/fd'))}",
        ]
        with open("/proc/self/limits") as f:
            for line in f:
                if line.startswith("Max open files"):
                    out += [
                        "# TYPE process_max_fds gauge",
                        f"process_max_fds {line.split()[3]}",
                    ]
                    break
    except (OSError, ValueError, IndexError):
        return []
    return out


#: process-wide registry (the reference's metrics are BEAM-node-global)
registry = Registry()


class ErrorMonitorHandler(logging.Handler):
    """logging handler -> error counter (the error_logger handler,
    reference src/antidote_error_monitor.erl:28-49)."""

    def __init__(self, reg: Optional[Registry] = None):
        super().__init__(level=logging.ERROR)
        self.registry = reg or registry

    def emit(self, record) -> None:
        self.registry.error_count.inc()
        # an error-monitor trip also dumps the flight recorder (rate-
        # limited inside dump(); lazy import — obs pulls nothing heavy
        # but stats must stay importable standalone)
        try:
            from antidote_tpu.obs.events import recorder as _rec

            _rec.record("errors", "monitor_trip",
                        logger=record.name,
                        message=record.getMessage()[:200])
            # anomalies that dump directly (abort, probe violation) also
            # log at ERROR; their forced dump already captured this
            # window, so don't write a redundant file for the log line
            if _rec.last_dump_age_s() >= _rec.min_dump_interval_s:
                _rec.dump("error_monitor")
        except Exception:  # noqa: BLE001 — the handler must not die
            pass


_error_monitor_installed = False
_install_lock = threading.Lock()


def install_error_monitor() -> None:
    """Attach the error-count handler to the root logger, once per
    process (the reference registers its handler with error_logger at
    app start, src/antidote_error_monitor.erl:28-33)."""
    global _error_monitor_installed
    with _install_lock:
        if _error_monitor_installed:
            return
        logging.getLogger().addHandler(ErrorMonitorHandler())
        _error_monitor_installed = True


_shared_server: Optional["MetricsServer"] = None


def ensure_metrics_server(port: int) -> "MetricsServer":
    """One exposition server per process: every DataCenter shares the
    process-global registry, so per-DC servers would race on the port
    and serve identical data anyway."""
    global _shared_server
    with _install_lock:
        if _shared_server is None:
            _shared_server = MetricsServer(port=port).start()
        return _shared_server


def stop_shared_metrics_server() -> None:
    global _shared_server
    with _install_lock:
        if _shared_server is not None:
            _shared_server.stop()
            _shared_server = None


class StalenessSampler:
    """Every 10 s, observe (now - min GST entry) in ms (reference
    src/antidote_stats_collector.erl:87-93: staleness of the stable
    snapshot vs the local clock).

    The same snapshot fetch also feeds the per-peer replication-lag
    gauge when ``peers_source`` is given — the gauge rides this
    sampler's period instead of forcing an extra stable-snapshot fold
    (on device-backed trackers: an XLA launch under COLLECTIVE_LOCK)
    per heartbeat tick."""

    def __init__(self, stable_vc_source, now_us, reg: Optional[Registry] = None,
                 period_s: float = 10.0, peers_source=None,
                 local_dc: str = "", safe_time_sources=None):
        self.stable_vc_source = stable_vc_source
        self.now_us = now_us
        self.registry = reg or registry
        self.period_s = period_s
        #: () -> iterable of peer DC ids to gauge replication lag for
        self.peers_source = peers_source
        #: the observing DC's id — the gauge's ``dc`` label, so several
        #: DCs in one process don't clobber each other's peer series
        self.local_dc = str(local_dc)
        #: () -> iterable of (partition, vc): each partition's safe-
        #: time vector (dep-gate watermarks + min-prepared) — feeds the
        #: per-partition safe-time-lag gauge (ISSUE 7) on the same
        #: cadence as the staleness histogram
        self.safe_time_sources = safe_time_sources
        self._lag_peers: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self) -> float:
        st = self.stable_vc_source()
        now_us = self.now_us()
        staleness_ms = sample_staleness_ms(st, now_us)
        self.registry.staleness.observe(staleness_ms)
        peers = set(self.peers_source()) if self.peers_source else set()
        for peer in peers:
            ts = st.get_dc(peer)
            if ts <= 0:
                continue  # no stable entry yet: lag is undefined, not epoch-sized
            self.registry.replication_lag.set(
                max(now_us - ts, 0) / 1e6, dc=self.local_dc,
                peer=str(peer))
        # a departed peer's series is dropped, not frozen at its last
        # value (only THIS dc's series: another DC in the process may
        # still be replicating from that peer)
        for gone in self._lag_peers - peers:
            self.registry.replication_lag.remove(dc=self.local_dc,
                                                 peer=str(gone))
        self._lag_peers = peers
        if self.safe_time_sources is not None:
            for p, vc in self.safe_time_sources():
                self.registry.vis_safe_time_lag.set(
                    sample_staleness_ms(vc, now_us) / 1e3,
                    dc=self.local_dc, partition=str(p))
        return staleness_ms

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        # one immediate sample so short-lived processes (and the
        # federation smoke test) see the gauges without waiting a period
        while True:
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — sampler must not die
                logging.getLogger(__name__).exception("staleness sample")
            if self._stop.wait(self.period_s):
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class MetricsServer:
    """Prometheus text exposition over HTTP (the elli endpoint on :3001,
    reference src/antidote_sup.erl:118-128)."""

    def __init__(self, port: int = 3001, reg: Optional[Registry] = None,
                 host: str = "127.0.0.1"):
        self.registry = reg or registry
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                path = self.path.split("?", 1)[0].rstrip("/")
                if path in ("", "/metrics"):
                    body = outer.registry.exposition().encode()
                    ctype = "text/plain; version=0.0.4"
                elif path == "/healthz":
                    body = outer.healthz().encode()
                    ctype = "application/json"
                elif path == "/debug/spans":
                    from antidote_tpu.obs.spans import tracer

                    body = tracer.export_chrome_json().encode()
                    ctype = "application/json"
                elif path == "/debug/prof":
                    import json as _json

                    from antidote_tpu.obs.prof import profiler

                    body = _json.dumps(profiler.snapshot()).encode()
                    ctype = "application/json"
                elif path == "/debug/pipeline":
                    from antidote_tpu.obs import pipeline

                    body = pipeline.snapshot_json().encode()
                    ctype = "application/json"
                elif path == "/debug/health":
                    import json as _json

                    from antidote_tpu.obs import slo

                    body = _json.dumps(
                        slo.evaluate_registry(outer.registry),
                        indent=1, sort_keys=True).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence request logging
                pass

        self._server = http.server.ThreadingHTTPServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def healthz(self) -> str:
        """Liveness JSON: serving + a shallow state summary (span ring
        depth + occupancy, flight-recorder dump/drop counts, open
        txns).  Ring occupancy makes a flooded ring visible BEFORE the
        forensic dump that needed its events comes back empty."""
        import json

        from antidote_tpu.obs.events import recorder as _rec
        from antidote_tpu.obs.spans import tracer as _tr

        cap = _tr.capacity
        drops = _rec.drop_counts()
        return json.dumps({
            "status": "ok",
            "open_transactions": self.registry.open_transactions.value(),
            "error_count": self.registry.error_count.value(),
            "spans_buffered": len(_tr),
            "span_ring_capacity": cap,
            "span_ring_fill_pct": round(100.0 * len(_tr) / cap, 4)
            if cap else 0.0,
            "flight_recorder_dumps": len(_rec.dumps),
            "flight_recorder_dropped": drops,
            "flight_recorder_dropped_total": sum(drops.values()),
        })

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)


def sample_staleness_ms(vc, now_us: int) -> float:
    """Pure helper (exported for the device-side staleness kernel)."""
    entries = list(dict(vc).values())
    oldest = min(entries) if entries else 0
    return max(now_us - oldest, 0) / 1000.0

"""Ops-plane metrics — the antidote_stats_collector / antidote_error_monitor
equivalent, dependency-free.

The reference defines five Prometheus metrics
(reference src/antidote_stats_collector.erl:80-85) and exposes them over
HTTP :3001 via elli (reference src/antidote_sup.erl:118-128); the same
names and semantics are kept so the packaged Grafana dashboard
(reference monitoring/Antidote-Dashboard.json) reads unchanged:

- ``antidote_error_count``                 counter, bumped by the error
  monitor (reference src/antidote_error_monitor.erl:38-46)
- ``antidote_staleness``                   histogram, ms buckets
  [1, 10, 100, 1000, 10000], sampled every 10 s from the GST
  (reference src/antidote_stats_collector.erl:36-38, 87-93)
- ``antidote_open_transactions``           gauge
- ``antidote_aborted_transactions_total``  counter
- ``antidote_operations_total{type}``      counter by operation type
  (incremented in the coordinator, reference
  src/clocksi_interactive_coord.erl:667, 734, 849, 870, 942, 966)

Exposition is the Prometheus text format served by a stdlib HTTP server
(the elli replacement).
"""

from __future__ import annotations

import http.server
import logging
import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple


class Counter:
    def __init__(self, name: str, help_: str, labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = labels
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            return self._values.get(key, 0.0)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        with self._lock:
            items = list(self._values.items()) or [((), 0.0)]
        for key, v in items:
            yield f"{self.name}{_fmt_labels(self.label_names, key)} {_fmt(v)}"


class Gauge:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        yield f"{self.name} {_fmt(self.value())}"


class Histogram:
    def __init__(self, name: str, help_: str, buckets: Tuple[float, ...]):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            counts, total = list(self._counts), self._sum
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            yield f'{self.name}_bucket{{le="{_fmt(b)}"}} {cum}'
        cum += counts[-1]
        yield f'{self.name}_bucket{{le="+Inf"}} {cum}'
        yield f"{self.name}_sum {_fmt(total)}"
        yield f"{self.name}_count {cum}"


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _fmt_labels(names: Tuple[str, ...], values: Tuple) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


class Registry:
    """The metric set from reference init_metrics
    (src/antidote_stats_collector.erl:80-85)."""

    def __init__(self):
        self.error_count = Counter(
            "antidote_error_count",
            "The number of error encountered during operation")
        self.staleness = Histogram(
            "antidote_staleness",
            "The staleness of the stable snapshot",
            buckets=(1, 10, 100, 1000, 10000))
        self.open_transactions = Gauge(
            "antidote_open_transactions", "Number of open transactions")
        self.aborted_transactions = Counter(
            "antidote_aborted_transactions_total",
            "Number of aborted transactions")
        self.operations = Counter(
            "antidote_operations_total", "Number of operations executed",
            labels=("type",))

    def metrics(self):
        return (self.error_count, self.staleness, self.open_transactions,
                self.aborted_transactions, self.operations)

    def exposition(self) -> str:
        lines = []
        for m in self.metrics():
            lines.extend(m.expose())
        lines.extend(process_metrics())
        return "\n".join(lines) + "\n"


def process_metrics() -> list:
    """Process-level gauges from /proc — the
    prometheus_process_collector role (reference rebar.config dep;
    standard process_* metric names).  Empty off Linux."""
    out = []
    try:
        with open("/proc/self/stat") as f:
            parts = f.read().split()
        tick = os.sysconf("SC_CLK_TCK")
        page = os.sysconf("SC_PAGE_SIZE")
        utime, stime = int(parts[13]), int(parts[14])
        vsize, rss_pages = int(parts[22]), int(parts[23])
        start_ticks = int(parts[21])
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        out += [
            "# TYPE process_cpu_seconds_total counter",
            f"process_cpu_seconds_total {(utime + stime) / tick:.3f}",
            "# TYPE process_virtual_memory_bytes gauge",
            f"process_virtual_memory_bytes {vsize}",
            "# TYPE process_resident_memory_bytes gauge",
            f"process_resident_memory_bytes {rss_pages * page}",
            "# TYPE process_start_time_seconds gauge",
            f"process_start_time_seconds "
            f"{time.time() - uptime + start_ticks / tick:.3f}",
        ]
        out += [
            "# TYPE process_open_fds gauge",
            f"process_open_fds {len(os.listdir('/proc/self/fd'))}",
        ]
        with open("/proc/self/limits") as f:
            for line in f:
                if line.startswith("Max open files"):
                    out += [
                        "# TYPE process_max_fds gauge",
                        f"process_max_fds {line.split()[3]}",
                    ]
                    break
    except (OSError, ValueError, IndexError):
        return []
    return out


#: process-wide registry (the reference's metrics are BEAM-node-global)
registry = Registry()


class ErrorMonitorHandler(logging.Handler):
    """logging handler -> error counter (the error_logger handler,
    reference src/antidote_error_monitor.erl:28-49)."""

    def __init__(self, reg: Optional[Registry] = None):
        super().__init__(level=logging.ERROR)
        self.registry = reg or registry

    def emit(self, record) -> None:
        self.registry.error_count.inc()


_error_monitor_installed = False
_install_lock = threading.Lock()


def install_error_monitor() -> None:
    """Attach the error-count handler to the root logger, once per
    process (the reference registers its handler with error_logger at
    app start, src/antidote_error_monitor.erl:28-33)."""
    global _error_monitor_installed
    with _install_lock:
        if _error_monitor_installed:
            return
        logging.getLogger().addHandler(ErrorMonitorHandler())
        _error_monitor_installed = True


_shared_server: Optional["MetricsServer"] = None


def ensure_metrics_server(port: int) -> "MetricsServer":
    """One exposition server per process: every DataCenter shares the
    process-global registry, so per-DC servers would race on the port
    and serve identical data anyway."""
    global _shared_server
    with _install_lock:
        if _shared_server is None:
            _shared_server = MetricsServer(port=port).start()
        return _shared_server


def stop_shared_metrics_server() -> None:
    global _shared_server
    with _install_lock:
        if _shared_server is not None:
            _shared_server.stop()
            _shared_server = None


class StalenessSampler:
    """Every 10 s, observe (now - min GST entry) in ms (reference
    src/antidote_stats_collector.erl:87-93: staleness of the stable
    snapshot vs the local clock)."""

    def __init__(self, stable_vc_source, now_us, reg: Optional[Registry] = None,
                 period_s: float = 10.0):
        self.stable_vc_source = stable_vc_source
        self.now_us = now_us
        self.registry = reg or registry
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample_once(self) -> float:
        staleness_ms = sample_staleness_ms(
            self.stable_vc_source(), self.now_us())
        self.registry.staleness.observe(staleness_ms)
        return staleness_ms

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — sampler must not die
                logging.getLogger(__name__).exception("staleness sample")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class MetricsServer:
    """Prometheus text exposition over HTTP (the elli endpoint on :3001,
    reference src/antidote_sup.erl:118-128)."""

    def __init__(self, port: int = 3001, reg: Optional[Registry] = None,
                 host: str = "127.0.0.1"):
        self.registry = reg or registry
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = outer.registry.exposition().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence request logging
                pass

        self._server = http.server.ThreadingHTTPServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)


def sample_staleness_ms(vc, now_us: int) -> float:
    """Pure helper (exported for the device-side staleness kernel)."""
    entries = list(dict(vc).values())
    oldest = min(entries) if entries else 0
    return max(now_us - oldest, 0) / 1000.0

"""Log record model.

Mirrors the reference's #log_record / #log_operation structure and the
op-number watermark scheme (reference include/antidote.hrl:130-136 —
``#op_number{local, global}`` per (partition, origin DC), assigned at
append time, src/logging_vnode.erl:388-439, 995-1009).  Op ids are what
the inter-DC gap-repair protocol compares, so they must be dense and
monotone per origin DC.

Payload kinds (reference log_operation types):
- ``("update", key, type_name, effect)``
- ``("prepare", prepare_time)``
- ``("commit", (dc, commit_time), snapshot_vc, certified)`` — the
  ``certified`` flag records whether write-write certification gated
  this commit; the device data plane's dense dot collapse is only sound
  for certified commits (antidote_tpu/mat/device_plane.py), so the flag
  must survive the log and the inter-DC stream
- ``("abort",)``

Serialization is pickle (internal durability format, not a wire format).
"""

from __future__ import annotations

import pickle
from typing import Any, NamedTuple, Optional, Tuple

from antidote_tpu.clocks import VC


class OpId(NamedTuple):
    """Per-origin-DC dense op number within one partition's stream."""

    dc: Any
    n: int


class LogRecord(NamedTuple):
    op_id: OpId
    txid: Any
    payload: Tuple  # one of the payload kinds above

    def kind(self) -> str:
        return self.payload[0]

    def to_bytes(self) -> bytes:
        return pickle.dumps(self, protocol=pickle.HIGHEST_PROTOCOL)

    @staticmethod
    def from_bytes(b: bytes) -> "LogRecord":
        rec = pickle.loads(b)
        if not isinstance(rec, LogRecord):
            raise ValueError("corrupt log record")
        return rec


def update_record(op_id: OpId, txid, key, type_name: str, effect) -> LogRecord:
    return LogRecord(op_id, txid, ("update", key, type_name, effect))


def prepare_record(op_id: OpId, txid, prepare_time: int) -> LogRecord:
    return LogRecord(op_id, txid, ("prepare", prepare_time))


def commit_record(op_id: OpId, txid, dc, commit_time: int,
                  snapshot_vc: VC, certified: bool = True) -> LogRecord:
    return LogRecord(
        op_id, txid, ("commit", (dc, commit_time), snapshot_vc, certified))


def commit_certified(payload: Tuple) -> bool:
    """Certified flag of a commit payload (older 3-tuple records
    default to True)."""
    return bool(payload[3]) if len(payload) > 3 else True


def abort_record(op_id: OpId, txid) -> LogRecord:
    return LogRecord(op_id, txid, ("abort",))


class TxnAssembler:
    """Buffers update records per txid; emits the full op list when the
    commit record arrives, drops on abort (the reference's
    log_txn_assembler, src/log_txn_assembler.erl:51-60).  Used both by
    the inter-DC sender and by log replay."""

    def __init__(self):
        self._buf: dict = {}

    def process(self, rec: LogRecord) -> Optional[list]:
        kind = rec.kind()
        if kind in ("update", "prepare"):
            self._buf.setdefault(rec.txid, []).append(rec)
            return None
        if kind == "commit":
            ops = self._buf.pop(rec.txid, [])
            return [r for r in ops if r.kind() == "update"] + [rec]
        if kind == "abort":
            self._buf.pop(rec.txid, None)
            return None
        raise ValueError(f"unknown log record kind {kind}")

    def pending_txids(self):
        return list(self._buf.keys())

"""Per-partition checkpoint store — the snapshot half of O(delta)
recovery and log truncation (ISSUE 10).

The reference keeps per-key materialized snapshots precisely so reads
and recovery replay only a log *suffix* (reference
src/materializer_vnode.erl:36-47, 415-419), and Cure-style
geo-replication assumes stable state below the causal cut never needs
re-derivation from the op log.  Before this plane our log grew without
bound and every cold path paid for it: restart scanned the whole
partition log, and every eviction or read-below-base replayed a key's
entire committed history.

A checkpoint document is ONE pickled dict per partition:

- ``cut_offset``: the log's logical end when the cut was taken (under
  the partition lock) — recovery replays only records at/after it;
- ``op_counters`` / ``max_commit_vc``: the log watermarks at the cut,
  so the suffix scan starts from correct seeds instead of offset 0;
- ``pending``: the in-flight (staged-but-uncommitted) update records
  at the cut, ``(txid, offset, record bytes)`` in offset order — a txn
  whose updates precede the cut but whose commit lands after it
  reassembles from this prefeed (the TxnAssembler's cut-crossing
  state);
- ``keys``: ``{key: (type_name, state, frontier VC)}`` — every dirty
  key's materialized latest value at the cut, folded from the device
  plane (one batched fold per type through the PR-8 ``export_state``
  machinery) or the host materializer.  Exactly the seed
  ``HostStore.seed_state`` installs: reads covering the frontier serve
  the state, suffix ops apply on top, replay-gating skips in-base ops;
- ``commit_watermarks``: per-origin last commit opid at the cut — the
  prev-opid chain seed for gap-repair answers above the cut, and the
  watermark a bootstrapping remote SubBuf jumps to;
- ``clock``: the join of every seed frontier (the dependency-clock
  seed a bootstrap hands the receiving gate).

The file write is atomic and checksummed: frame to a temp file, fsync,
rename — a crash mid-checkpoint leaves the previous checkpoint intact,
and recovery then replays the (longer) suffix from the previous cut.
A torn/corrupt file fails the CRC and loads as None (full-scan
recovery), never as a half-document.

``ckpt_from_config`` is the one construction path (the
gate_from_config lesson): Node's partition factory routes through it,
so boot, repartition, and adopt_partition cannot honor different
knobs.  ``Config.ckpt=False`` builds no store at all — recovery,
eviction replay, and gap repair keep today's behavior bit-for-bit.

**Segmented persistence (ISSUE 13).**  The one-document form above
made every watermark checkpoint O(keyspace): the WHOLE carried seed
set re-pickled and double-fsynced per cut, however small the churn.
With ``Config.ckpt_segmented`` (default on) the seed set instead
lives in immutable, individually checksummed **segment** files
(same magic+len+crc framing, same torn-at-every-byte discipline) and
the ``.ckpt`` file becomes a small **manifest** carrying the log cut,
watermarks, floors, pending records, and the ordered segment list —
a checkpoint then writes ONE dirty-delta segment (keys whose frontier
moved since the previous cut) plus the manifest, O(churn).  Recovery
merges segments oldest→newest so each key's NEWEST entry wins; a
missing or torn segment refuses LOUDLY (the manifest loads as None
and recovery falls back to the full scan — degraded cost, never a
silent half-keyspace).  Superseded entries accumulate one per re-fold
of a dirty key; when their fraction crosses ``seg_waste_frac`` the
next checkpoint **compacts** — folds every live seed into one fresh
segment, publishes a manifest listing only it, then unlinks the old
segments — on the checkpointing thread (caller-elected, the
mat/serve.py no-background-thread discipline).  A crash anywhere
mid-compaction leaves the OLD manifest authoritative: segments are
never mutated and the manifest rename is the single commit point.
``Config.ckpt_segmented=False`` keeps the PR-9 monolithic document
bit-for-bit (the bench baseline); loading follows the on-disk
document's shape, so a knob flip across restarts recovers cleanly.
"""

from __future__ import annotations

import glob
import logging
import mmap
import os
import pickle
import random
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.obs.spans import tracer
from antidote_tpu.oplog.log import _fsync_dir

log = logging.getLogger(__name__)

#: checkpoint file framing: magic + [u32 len][u32 crc32(body)][body]
_MAGIC = b"ATPCKPT1"
#: seed-segment framing: same frame, its own magic — a segment file
#: truncated/renamed over a manifest (or vice versa) must parse None
_SEG_MAGIC = b"ATPCKSG1"
_FRAME = struct.Struct("<II")

#: document schema version (bump on layout change; unknown versions
#: load as None — full-scan recovery, never a misread document)
DOC_VERSION = 1


@dataclass(frozen=True)
class CheckpointSettings:
    """The checkpoint plane's knobs — built from Config by
    :func:`ckpt_from_config` (the single factory)."""

    #: write checkpoints at all; False = no store, today's recovery
    enabled: bool = True
    #: published-op watermark: a partition checkpoints after this many
    #: ops since its last cut
    every_ops: int = 4096
    #: appended-byte watermark: ... or after this many new log bytes
    every_bytes: int = 4 * 1024 * 1024
    #: reclaim log bytes below the cut after a successful checkpoint
    #: (gated by the retention floor — see PartitionLog.truncate)
    truncate: bool = True
    #: opid safety margin kept BELOW the peers' ship watermark when
    #: truncating: ordinary gap repair (lost frames) keeps answering
    #: from the log for this much recent history, so only a peer that
    #: fell further behind pays the checkpoint-bootstrap escalation
    retain_ops: int = 4096
    #: dirty-delta segment persistence (ISSUE 13): a cut writes one
    #: segment of the keys folded since the previous cut + a small
    #: manifest, O(churn); False = the PR-9 whole-seed-set document,
    #: bit-for-bit (the bench baseline)
    segmented: bool = True
    #: dead-entry fraction across segments past which the next
    #: checkpoint compacts them into one
    seg_waste_frac: float = 0.5
    #: mmap-backed segment loads (ISSUE 19): manifest merges read each
    #: segment through a page-cache mapping instead of a full heap
    #: read(), so a merged seed set larger than RAM never holds more
    #: than one segment's raw bytes at a time; False = the PR-12
    #: read() path bit-for-bit
    mmap_load: bool = True


def ckpt_from_config(config) -> CheckpointSettings:
    """The one construction path for checkpoint settings."""
    if config is None:
        return CheckpointSettings()
    return CheckpointSettings(
        enabled=config.ckpt,
        every_ops=config.ckpt_ops,
        every_bytes=config.ckpt_bytes,
        truncate=config.ckpt_truncate,
        retain_ops=config.ckpt_retain_ops,
        segmented=config.ckpt_segmented,
        seg_waste_frac=config.ckpt_seg_waste_frac,
        mmap_load=getattr(config, "ckpt_mmap", True))


def retry_bounded(fn: Callable, *, attempts: int, what: str,
                  counter=None, base_delay_s: float = 0.0,
                  exceptions: tuple = (OSError,)):
    """Run ``fn`` up to ``attempts`` times with jittered exponential
    backoff between tries — the ONE bounded-retry shape shared by the
    donor-side bundle read (:meth:`CheckpointStore.ship_bundle`, which
    races compaction) and the handoff receiver's bundle pull
    (cluster/node.py).  Every retry increments ``counter`` (a stats
    Counter — the CKPT_SEG_* family surfaces what used to be log-only
    warnings) and logs the failure it is retrying past; the last
    failure re-raises so exhaustion is never silent."""
    last: Optional[BaseException] = None
    for attempt in range(max(1, attempts)):
        if attempt:
            if counter is not None:
                counter.inc()
            log.warning("%s failed (attempt %d/%d): %r — retrying",
                        what, attempt, attempts, last)
            if base_delay_s > 0.0:
                # full jitter on an exponential base: retries against a
                # shared donor must not synchronize into thundering
                # re-reads of the same racing manifest
                time.sleep(base_delay_s * (1 << (attempt - 1))
                           * (0.5 + random.random()))
        try:
            return fn()
        except exceptions as e:  # noqa: PERF203 — bounded, cold path
            last = e
    assert last is not None
    raise last


def _parse_segment_bytes(raw) -> Optional[dict]:
    """Decode one seed segment from a bytes-like (bytes or a read-only
    mmap): magic + frame + CRC over the body, pickle body to the entry
    dict.  None on ANY violation — the one segment-validation home
    shared by the local load, the streamed-fetch receiver, and the
    ship-side read."""
    hdr = len(_SEG_MAGIC) + _FRAME.size
    if len(raw) < hdr or bytes(raw[:len(_SEG_MAGIC)]) != _SEG_MAGIC:
        return None
    ln, crc = _FRAME.unpack(raw[len(_SEG_MAGIC):hdr])
    body = raw[hdr:hdr + ln]
    if len(body) < ln or zlib.crc32(body) != crc:
        return None
    try:
        entries = pickle.loads(body)
    except Exception:  # noqa: BLE001 — corrupt segments load None
        return None
    return entries if isinstance(entries, dict) else None


def frame_segment_bytes(entries: dict) -> bytes:
    """Frame a seed-entry dict exactly like an on-disk segment (magic
    + length/CRC frame + pickled body) — the streamed CKPT_READ pages
    (interdc/query.py) ride the same torn-fetch validation
    (:func:`_parse_segment_bytes`) as file-backed bundle segments."""
    body = pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)
    return _SEG_MAGIC + _FRAME.pack(len(body), zlib.crc32(body)) + body


def validate_segment_bytes(raw) -> bool:
    """True when ``raw`` is a whole, untorn seed segment (magic, frame,
    CRC, decodable body).  The streamed-bundle receiver refuses a torn
    or short fetch with this BEFORE writing anything — a bad network
    read must resume the cursor, never land on disk."""
    return _parse_segment_bytes(raw) is not None


def segment_glob(ckpt_path: str) -> List[str]:
    """Every seed-segment file belonging to the checkpoint at
    ``ckpt_path`` — the ONE owner of the on-disk naming, shared by the
    store's sweep/delete and by every caller that retires a slot's
    checkpoint wholesale (ring resize, handoff install)."""
    return sorted(glob.glob(glob.escape(ckpt_path) + ".seg-*"))


def delete_checkpoint_files(ckpt_path: str) -> None:
    """Remove a slot's manifest/document, temp, and every segment —
    ring resizes and handoff installs retire checkpoints by PATH
    (their store object lives in another node's process, or nowhere)."""
    for p in (ckpt_path, ckpt_path + ".tmp", *segment_glob(ckpt_path)):
        try:
            os.remove(p)
        except OSError:
            pass


def install_shipped_bundle(ckpt_path: str,
                           bundle: Optional[dict]) -> None:
    """Handoff receiver: retire whatever stale checkpoint lives at
    ``ckpt_path`` (it describes a DIFFERENT log's layout) and, when
    the donor shipped one, install its bundle so the transferred log
    recovers checkpoint-seeded — FULL state even when the donor's
    below-cut bytes were truncated (the pre-ISSUE-13 receiver
    recovered suffix-only, loudly).  Lives here so the blessed module
    constructs the store (the *_from_config factory discipline); the
    settings are irrelevant to an install — only the paths are used,
    and the adopting partition re-reads the files through its own
    config-routed store."""
    # dur-ok: deliberately unlink-BEFORE-commit — the stale local
    # checkpoint describes a DIFFERENT log's layout and must not
    # survive even a crash before the shipped bundle's manifest
    # rename lands: recovery over the transferred log with no
    # checkpoint falls back to the full scan (degraded cost), while
    # adopting the stale one would seed wrong state (the PR-12
    # stale-adoption bug this function exists to prevent)
    delete_checkpoint_files(ckpt_path)
    if bundle:
        CheckpointStore(ckpt_path,
                        CheckpointSettings()).install_bundle(bundle)


class CheckpointStore:
    """Atomic load/store of one partition's checkpoint document —
    monolithic (one pickled doc) or segmented (manifest + immutable
    seed segments), per ``settings.segmented``."""

    def __init__(self, path: str, settings: CheckpointSettings):
        self.path = path
        self.settings = settings
        #: next segment sequence number — never reused, so a staged
        #: compaction output can never collide with a live segment
        self._seg_seq = self._max_seg_seq() + 1

    def _seg_path(self, seq: int) -> str:
        return f"{self.path}.seg-{seq:08d}"

    def _max_seg_seq(self) -> int:
        top = 0
        for p in segment_glob(self.path):
            try:
                top = max(top, int(p.rsplit("-", 1)[1]))
            except ValueError:
                continue
        return top

    def _sweep_segments(self, referenced: set) -> None:
        """Unlink every on-disk segment whose basename is not in
        ``referenced`` — the post-commit garbage sweep shared by the
        segmented persist (compacted-away segments + crashed-persist
        strays), the monolithic knob-flip (all of them), and the
        bundle install (local strays the shipped manifest does not
        list).  Only ever called AFTER the manifest that defines
        ``referenced`` is durably in place."""
        for p in segment_glob(self.path):
            if os.path.basename(p) not in referenced:
                try:
                    os.remove(p)
                except OSError:
                    pass

    # ------------------------------------------------------------- load

    def load_doc(self) -> Optional[dict]:
        """The current checkpoint document, or None when absent, torn,
        or from an unknown schema (recovery then falls back to the full
        scan — a bad checkpoint degrades cost, never correctness).  A
        segmented manifest loads its seed set by merging segments
        oldest→newest (each key's newest entry wins); ANY listed
        segment missing or torn refuses the whole document, loudly —
        a silently partial seed set would recover a half-keyspace as
        if it were everything."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        with tracer.span("ckpt_load", "oplog",
                         path=os.path.basename(self.path),
                         bytes=len(raw)):
            doc = self._parse(raw)
            if doc is not None and "segments" in doc:
                doc = self._load_segments(doc)
        return doc

    def _load_segments(self, doc: dict) -> Optional[dict]:
        """Materialize a manifest's seed set from its segment files."""
        merged: Dict = {}
        for name, _n_keys, _n_bytes in doc["segments"]:
            entries = self._load_segment(
                os.path.join(os.path.dirname(self.path) or ".", name))
            if entries is None:
                log.error(
                    "checkpoint manifest %s lists segment %s but it "
                    "is missing or torn — refusing the whole "
                    "checkpoint (recovery falls back to the full "
                    "scan)", self.path, name)
                return None
            merged.update(entries)
        doc["keys"] = merged
        return doc

    def _load_segment(self, path: str) -> Optional[dict]:
        """A segment file's ``{key: (type_name, state, vc)}``, or None
        when absent/torn/corrupt (same every-byte discipline as the
        document parse).  Under ``settings.mmap_load`` the file is
        CRC-verified through a read-only page-cache mapping — a
        manifest merge over a many-GB seed set never heap-copies more
        than the one segment body being unpickled (ISSUE 19); the
        read() path remains both the knob-off baseline and the
        fallback for files mmap cannot map (empty/virtual)."""
        try:
            f = open(path, "rb")
        except OSError:
            return None
        mm: Optional[mmap.mmap] = None
        try:
            if self.settings.mmap_load:
                try:
                    mm = mmap.mmap(f.fileno(), 0,
                                   access=mmap.ACCESS_READ)
                except (ValueError, OSError):
                    mm = None  # empty or unmappable: read() fallback
            raw = mm if mm is not None else f.read()
            entries = _parse_segment_bytes(raw)
        finally:
            if mm is not None:
                mm.close()
            f.close()
        return entries

    @staticmethod
    def _parse(raw: bytes) -> Optional[dict]:
        hdr = len(_MAGIC) + _FRAME.size
        if len(raw) < hdr or not raw.startswith(_MAGIC):
            return None
        ln, crc = _FRAME.unpack(raw[len(_MAGIC):hdr])
        body = raw[hdr:hdr + ln]
        if len(body) < ln or zlib.crc32(body) != crc:
            return None  # torn mid-write / bit rot: CRC catches it
        try:
            doc = pickle.loads(body)
        except Exception:  # noqa: BLE001 — a corrupt doc must load None
            return None
        if not isinstance(doc, dict) or doc.get("version") != DOC_VERSION:
            return None
        return doc

    # ------------------------------------------------------------ store

    def persist(self, doc: dict) -> None:
        """Persist one checkpoint — THE routing point of the
        ``ckpt_segmented`` knob's write side: the monolithic document
        (``write_doc``, the PR-9 bytes exactly) or a dirty-delta
        segment + manifest.  ``doc`` carries the full merged seed set
        in ``keys`` and, when the caller folded incrementally, the
        dirty-only delta in ``delta`` (manager._ckpt_fold)."""
        tracer.instant("ckpt_persist", "oplog",
                       path=os.path.basename(self.path),
                       segmented=self.settings.segmented)
        if not self.settings.segmented:
            doc.pop("delta", None)  # monolithic docs carry keys only
            self.write_doc(doc)
            # a knob flip back to monolithic strands the previous
            # manifest's segments: the document just written carries
            # every seed inline, so they are garbage now
            self._sweep_segments(set())
            return
        self._persist_segmented(doc)

    def write_doc(self, doc: dict) -> int:
        """Atomically persist ``doc``; returns the file size.  The
        write is temp + fsync + rename, so a crash at ANY byte leaves
        either the previous checkpoint or the new one — never a blend
        (proven by the truncate-at-every-byte differential in
        tests/unit/test_checkpoint.py)."""
        t0 = time.perf_counter()
        body = pickle.dumps(doc, protocol=pickle.HIGHEST_PROTOCOL)
        raw = _MAGIC + _FRAME.pack(len(body), zlib.crc32(body)) + body
        tmp = self.path + ".tmp"
        with tracer.span("ckpt_write", "oplog",
                         path=os.path.basename(self.path),
                         bytes=len(raw), keys=len(doc.get("keys", ()))):
            with open(tmp, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            _fsync_dir(os.path.dirname(self.path),
                       instant="ckpt_dir_fsync")
        reg = stats.registry
        reg.ckpt_writes.inc()
        reg.ckpt_duration.observe(time.perf_counter() - t0)
        return len(raw)

    def _write_segment(self, entries: dict) -> tuple:
        """One immutable seed segment: frame, write, fsync.  No rename
        dance — the file is not live until a MANIFEST lists it, and
        the sequence numbering never reuses a name, so a crash leaves
        only an unreferenced stray (swept by the next persist).
        Returns (basename, n_keys, n_bytes)."""
        seq = self._seg_seq
        self._seg_seq += 1
        path = self._seg_path(seq)
        body = pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)
        raw = _SEG_MAGIC + _FRAME.pack(len(body), zlib.crc32(body)) \
            + body
        with tracer.span("ckpt_seg_write", "oplog",
                         path=os.path.basename(path), bytes=len(raw),
                         keys=len(entries)):
            with open(path, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
        return os.path.basename(path), len(entries), len(raw)

    def _persist_segmented(self, doc: dict) -> None:
        """Dirty-delta persist: write ONE segment holding the keys
        folded since the previous cut, then the manifest (atomic
        rename — the commit point).  Compaction is elected HERE, on
        the checkpointing thread, when the superseded-entry fraction
        across segments crosses ``seg_waste_frac``: every live seed
        folds into one fresh segment and the manifest lists only it.
        Old segments are unlinked only AFTER the new manifest landed —
        a crash at any earlier byte leaves the previous manifest
        authoritative over files that still all exist."""
        t0 = time.perf_counter()
        delta = doc.pop("delta", None)
        if delta is None:
            # no incremental fold (first cut, or a caller handing a
            # fully-materialized doc): the whole seed set is the delta
            delta = doc["keys"]
        prev = doc.pop("prev_segments", [])
        live = len(doc["keys"])
        # elect compaction from the PROSPECTIVE shape (previous
        # segments + the delta about to be written) BEFORE paying for
        # the delta segment: a compacting cut writes ONLY the
        # compacted segment — the delta is a subset of the live set,
        # and writing-then-unlinking it would double the fsyncs on
        # exactly the cuts that are already the most expensive
        n_segs = len(prev) + (1 if delta else 0)
        total = sum(n for _name, n, _b in prev) + len(delta)
        dead_frac = (total - live) / total if total else 0.0
        compacted = (n_segs > 1 and dead_frac >= max(
            self.settings.seg_waste_frac, 1e-9))
        if compacted:
            segments = [self._write_segment(doc["keys"])]
        else:
            segments = list(prev)
            if delta:
                segments.append(self._write_segment(delta))
        tracer.instant("ckpt_manifest", "oplog",
                       path=os.path.basename(self.path),
                       segments=len(segments), compacted=compacted)
        keys = doc.pop("keys")  # the manifest carries the list, not
        try:                    # the seed states themselves
            doc["segments"] = segments
            self.write_doc(doc)
        finally:
            doc["keys"] = keys
        # post-commit sweep: everything the live manifest does not
        # reference (compacted-away segments, strays from a crashed
        # persist) is garbage now
        self._sweep_segments({name for name, _n, _b in segments})
        reg = stats.registry
        if compacted:
            reg.ckpt_seg_compactions.inc()
        lbl = str(doc.get("partition", ""))
        reg.ckpt_seg_count.set(len(segments), partition=lbl)
        reg.ckpt_seg_bytes.set(sum(b for _n, _k, b in segments),
                               partition=lbl)
        total = sum(n for _name, n, _b in segments)
        reg.ckpt_seg_dead_frac.set(
            (total - live) / total if total else 0.0, partition=lbl)
        if delta:
            us = (time.perf_counter() - t0) * 1e6
            reg.ckpt_seg_persist_us_per_key.set(us / len(delta))

    def delete(self) -> None:
        delete_checkpoint_files(self.path)

    # --------------------------------------------- handoff shipping

    class _NoCheckpoint(Exception):
        """Internal: the manifest is absent/torn — 'nothing to ship',
        distinct from a segment read losing to compaction (retried)."""

    def _read_bundle_once(self) -> dict:
        try:
            with open(self.path, "rb") as f:
                manifest_raw = f.read()
        except OSError:
            raise CheckpointStore._NoCheckpoint from None
        doc = self._parse(manifest_raw)
        if doc is None:
            raise CheckpointStore._NoCheckpoint
        segs: Dict[str, bytes] = {}
        for name, _n, _b in doc.get("segments", ()):
            # an OSError here is a compaction unlinking a listed
            # segment between the manifest read and this read — the
            # retry wrapper re-reads the FRESH manifest
            with open(os.path.join(
                    os.path.dirname(self.path) or ".", name),
                    "rb") as f:
                segs[name] = f.read()
        return {"manifest": manifest_raw, "segments": segs}

    def ship_bundle(self) -> Optional[dict]:
        """The checkpoint as one transferable unit (ISSUE 13 handoff):
        raw manifest/document bytes + every referenced segment's raw
        bytes.  Segments are immutable, so they copy without the
        truncation-epoch dance the raw log needs; the only race is a
        compaction unlinking a listed segment between the manifest
        read and the segment read — jittered bounded retries
        (:func:`retry_bounded`, counted in ``ckpt_seg_ship_retries``)
        re-read the fresh manifest.  None when no (valid) checkpoint
        exists; raises when a checkpoint exists but every attempt lost
        the read race — a donor that HAS below-cut history must
        surface as a retryable error, never quietly ship nothing (the
        exact hole this bundle exists to close)."""
        try:
            return retry_bounded(
                self._read_bundle_once, attempts=5,
                what=f"checkpoint bundle read at {self.path}",
                counter=stats.registry.ckpt_seg_ship_retries,
                base_delay_s=0.002)
        except CheckpointStore._NoCheckpoint:
            return None
        except OSError as e:
            raise OSError(
                f"checkpoint bundle read at {self.path} kept losing "
                "to concurrent compaction; retry the pull") from e

    def bundle_manifest(self) -> Optional[dict]:
        """Manifest-only half of :meth:`ship_bundle` — the streamed
        transfer's first message (ISSUE 19): raw manifest bytes plus
        the ordered ``(name, n_keys, n_bytes)`` segment list the
        receiver's cursor walks.  None when no (valid) checkpoint
        exists.  A monolithic document answers with an empty segment
        list — its seed set rides inline in the manifest bytes, so
        the cursor commits after zero fetches."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        doc = self._parse(raw)
        if doc is None:
            return None
        return {"manifest": raw,
                "segments": [tuple(s) for s in doc.get("segments", ())]}

    def read_segment_raw(self, name: str) -> Optional[bytes]:
        """One referenced segment's raw bytes for a streamed fetch, or
        None when it no longer exists (compacted away — the receiver
        re-fetches the manifest and resumes).  ``name`` is confined to
        this store's own segment namespace: a cursor fetch must never
        read an arbitrary path."""
        base = os.path.basename(str(name))
        if not base.startswith(os.path.basename(self.path) + ".seg-"):
            return None
        try:
            with open(os.path.join(
                    os.path.dirname(self.path) or ".", base),
                    "rb") as f:
                return f.read()
        except OSError:
            return None

    def install_bundle(self, bundle: dict) -> None:
        """Install a shipped checkpoint at this store's path: segments
        first (dead files until referenced), then the manifest via the
        atomic temp+rename (the commit point), then a sweep of local
        strays the shipped manifest does not list.  A torn install
        (crash before the rename) leaves whatever manifest was live
        before — never a blend."""
        d = os.path.dirname(self.path) or "."
        with tracer.span("ckpt_install_bundle", "oplog",
                         path=os.path.basename(self.path),
                         segments=len(bundle.get("segments", ()))):
            for name, raw in bundle.get("segments", {}).items():
                base = os.path.basename(name)  # no path traversal
                with open(os.path.join(d, base), "wb") as f:
                    f.write(raw)
                    f.flush()
                    os.fsync(f.fileno())
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(bundle["manifest"])
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            _fsync_dir(d, instant="ckpt_dir_fsync")
        self._sweep_segments({os.path.basename(n)
                              for n in bundle.get("segments", ())})
        self._seg_seq = self._max_seg_seq() + 1


class BundleCursor:
    """Receiver half of a segment-granular bundle transfer (ISSUE 19):
    the resumable cursor the streamed handoff pull and the streamed
    CKPT_READ bootstrap drive.  The donor ships the manifest first
    (:meth:`CheckpointStore.bundle_manifest`), then segments one fetch
    at a time; the cursor validates each fetch (magic + CRC — a torn
    or short read refuses loudly and is NOT acked), stages it durably,
    and tracks the per-segment ack watermark, so a donor kill or a
    torn fetch resumes at the first un-acked segment instead of
    refetching the bundle.  ``begin`` with a DIFFERENT manifest (the
    donor re-cut or compacted between fetches) restarts the cursor and
    counts the discarded progress in ``stream_resume_refetch_bytes``.
    ``commit`` retires the stale local checkpoint and republishes via
    the same segments-then-manifest rename discipline as
    :meth:`CheckpointStore.install_bundle` — a crash before the
    manifest rename leaves the previous checkpoint authoritative."""

    def __init__(self, ckpt_path: str):
        self.path = ckpt_path
        self.manifest_raw: Optional[bytes] = None
        #: ordered (name, n_keys, n_bytes) from the adopted manifest
        self.meta: List[Tuple[str, int, int]] = []
        self._acked: Dict[str, str] = {}  # name -> staged path

    def _stage_path(self, name: str) -> str:
        return f"{self.path}.stage-{os.path.basename(name)}"

    def begin(self, manifest_raw: bytes) -> bool:
        """Adopt (or confirm) the donor's manifest; returns True when
        the cursor (re)started from scratch — first call, or the
        manifest CHANGED and every previously acked segment was
        discarded — and False when it resumed in place.  Raises
        ``ValueError`` on a torn/unparseable manifest."""
        if CheckpointStore._parse(manifest_raw) is None:
            raise ValueError(
                f"torn or unparseable bundle manifest for {self.path} "
                "— refusing the stream")
        if self.manifest_raw == manifest_raw:
            return False
        if self.manifest_raw is not None:
            # the donor's checkpoint moved under us (re-cut/compaction
            # or a different donor after a kill): acked progress is
            # against a dead manifest — discard it, loudly counted
            refetch = sum(b for n, _k, b in self.meta
                          if n in self._acked)
            stats.registry.stream_resume_refetch_bytes.inc(refetch)
            stats.registry.stream_restarts.inc()
            self.discard()
        doc = CheckpointStore._parse(manifest_raw)
        self.manifest_raw = manifest_raw
        self.meta = [tuple(s) for s in doc.get("segments", ())]
        self._acked = {}
        return True

    def pending(self) -> List[Tuple[str, int, int]]:
        """Un-acked (name, n_keys, n_bytes) in manifest order — the
        exact resume point after a donor kill or torn fetch."""
        return [m for m in self.meta if m[0] not in self._acked]

    def acked_segments(self) -> int:
        return len(self._acked)

    def offer(self, name: str, raw: bytes) -> None:
        """Validate + durably stage one fetched segment and advance
        the ack watermark.  A torn/short/corrupt fetch raises
        ``ValueError`` WITHOUT staging or acking — the caller re-pulls
        the same segment (or re-begins when the donor vanished)."""
        if self.manifest_raw is None:
            raise ValueError("BundleCursor.offer before begin")
        if name not in {m[0] for m in self.meta}:
            raise ValueError(
                f"segment {name!r} is not in the adopted manifest")
        if name in self._acked:
            return  # duplicate fetch after a retried round: no-op
        if not validate_segment_bytes(raw):
            stats.registry.stream_torn_fetches.inc()
            raise ValueError(
                f"torn or short segment fetch for {name!r} "
                f"({len(raw)} bytes) — refusing; resume at the last "
                "acked segment")
        staged = self._stage_path(name)
        with tracer.span("ckpt_stream_stage", "oplog",
                         segment=os.path.basename(str(name)),
                         n_bytes=len(raw)):
            with open(staged, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
        self._acked[name] = staged
        stats.registry.stream_seg_fetches.inc()
        stats.registry.stream_seg_bytes.inc(len(raw))

    def commit(self) -> None:
        """Every segment acked: retire the stale local checkpoint and
        install — staged segments rename to their final names first
        (dead files until referenced), then the manifest via the
        atomic temp+rename commit point, then the stray sweep.  Raises
        ``ValueError`` while any segment is still pending."""
        still = self.pending()
        if self.manifest_raw is None or still:
            raise ValueError(
                f"bundle commit for {self.path} with "
                f"{len(still)} segment(s) still pending")
        d = os.path.dirname(self.path) or "."
        with tracer.span("ckpt_stream_commit", "oplog",
                         path=os.path.basename(self.path),
                         segments=len(self._acked)):
            # dur-ok: deliberately unlink-BEFORE-commit — identical
            # rationale to install_shipped_bundle: the stale local
            # checkpoint describes a DIFFERENT log's layout and must
            # not survive even a crash before the streamed manifest's
            # rename lands (no-checkpoint recovery degrades to the
            # full scan; adopting the stale one would seed wrong
            # state)
            delete_checkpoint_files(self.path)
            for name, staged in self._acked.items():
                # dur-ok: the staged bytes were flushed+fsynced by
                # offer() at ack time — this rename republishes
                # already-durable bytes under their final names
                os.replace(staged,
                           os.path.join(d, os.path.basename(name)))
            _fsync_dir(d, instant="ckpt_stream_segs_fsync")
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(self.manifest_raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            _fsync_dir(d, instant="ckpt_dir_fsync")
        referenced = {os.path.basename(n) for n, _k, _b in self.meta}
        for p in segment_glob(self.path):
            if os.path.basename(p) not in referenced:
                try:
                    os.remove(p)
                except OSError:
                    pass
        # staged strays from an earlier ABANDONED cursor at this path
        # (a restarted pull attempt never renames them) die with the
        # commit that supersedes them
        for p in glob.glob(glob.escape(self.path) + ".stage-*"):
            try:
                os.remove(p)
            except OSError:
                pass
        self._acked = {}

    def discard(self) -> None:
        """Drop staged progress (abandoned transfer / restarted
        cursor): unlink every staged-but-uncommitted segment file."""
        for staged in self._acked.values():
            try:
                os.remove(staged)
            except OSError:
                pass
        self._acked = {}
        self.meta = []
        self.manifest_raw = None


# ------------------------------------------------- resize staging

def _frame_doc(doc: dict) -> bytes:
    body = pickle.dumps(doc, protocol=pickle.HIGHEST_PROTOCOL)
    return _MAGIC + _FRAME.pack(len(body), zlib.crc32(body)) + body


def stage_resize_checkpoint(ckpt_path: str, doc: dict,
                            settings: CheckpointSettings) -> None:
    """Durably stage a re-cut checkpoint for one NEW slot of a
    checkpoint-seeded ring resize (ISSUE 19), next to the slot's
    staged ``.resize`` log: segments under the ``{ckpt}.resize``
    namespace plus a staged manifest at ``{ckpt}.resize`` itself.
    Nothing here is live — the old ring's checkpoint at ``ckpt_path``
    stays untouched and authoritative until the resize journal commits
    and :func:`commit_staged_resize_checkpoint` renames the staged
    files in (the install_shipped_bundle manifest-rename discipline).
    All bytes are fsynced HERE because the journal commit point
    asserts the staged ring is durably complete."""
    spath = ckpt_path + ".resize"
    with tracer.span("resize_ckpt_stage", "oplog",
                     path=os.path.basename(ckpt_path),
                     keys=len(doc["keys"])):
        delete_checkpoint_files(spath)  # strays of a crashed stage
        if settings.segmented:
            store = CheckpointStore(spath, settings)
            segments = []
            if doc["keys"]:
                segments.append(
                    store._write_segment(dict(doc["keys"])))
            man = {k: v for k, v in doc.items()
                   if k not in ("keys", "delta", "prev_segments")}
            man["segments"] = segments
            raw = _frame_doc(man)
        else:
            raw = _frame_doc({k: v for k, v in doc.items()
                              if k not in ("delta", "prev_segments")})
        with open(spath, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(os.path.dirname(spath) or ".",
                   instant="resize_ckpt_stage_fsync")


def commit_staged_resize_checkpoint(ckpt_path: str) -> bool:
    """Post-journal half of the seeded resize's checkpoint install,
    run inside the swap completion and idempotent under the boot-time
    crash resume: while the staged manifest exists the whole install
    re-runs from scratch — retire whatever (possibly partially
    committed) checkpoint lives at ``ckpt_path``, HARD-LINK each
    staged segment to its final name (a link never consumes the
    staged file, so a re-run after a crash always still has its
    sources), and publish a manifest rewritten to those final names
    via the atomic temp+rename commit point.  The staged files are
    deliberately LEFT IN PLACE: they are the re-run marker — the
    crash resume re-runs this for every slot while the resize journal
    exists, and only a present staged manifest distinguishes "this
    slot's checkpoint was just committed, keep it" from "stale
    pre-resize checkpoint, retire it".  The caller sweeps them with
    discard_staged_resize_checkpoint AFTER the journal clears (no
    re-run can happen past that point).  Returns False when nothing
    is staged (legacy fold, or already swept)."""
    spath = ckpt_path + ".resize"
    try:
        with open(spath, "rb") as f:
            raw = f.read()
    except OSError:
        return False
    doc = CheckpointStore._parse(raw)
    if doc is None:
        log.error("staged resize checkpoint %s is torn — installing "
                  "nothing (recovery falls back to the suffix-only "
                  "staged log)", spath)
        return False
    d = os.path.dirname(ckpt_path) or "."
    with tracer.span("resize_ckpt_install", "oplog",
                     path=os.path.basename(ckpt_path),
                     segments=len(doc.get("segments", ()))):
        # dur-ok: unlink-BEFORE-commit by design — whatever lives at
        # the final path is either the pre-resize checkpoint
        # (describes the OLD log's layout; the resize journal already
        # committed, so it must not be adopted even across a crash)
        # or a crashed earlier run's partial install; the staged
        # files survive untouched, so the re-run always completes
        # the install
        delete_checkpoint_files(ckpt_path)
        final_segments = []
        for name, n_keys, n_bytes in doc.get("segments", ()):
            staged_seg = os.path.join(d, os.path.basename(name))
            final_name = os.path.basename(ckpt_path) \
                + ".seg-" + name.rsplit(".seg-", 1)[1]
            os.link(staged_seg, os.path.join(d, final_name))
            final_segments.append((final_name, n_keys, n_bytes))
        if final_segments:
            _fsync_dir(d, instant="resize_ckpt_segs_fsync")
        if "segments" in doc:
            doc["segments"] = final_segments
        tmp = ckpt_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_frame_doc(doc))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, ckpt_path)
        _fsync_dir(d, instant="ckpt_dir_fsync")
    return True


def discard_staged_resize_checkpoint(ckpt_path: str) -> None:
    """Abandon a staged re-cut checkpoint (aborted/failed resize
    BEFORE its journal committed): the staged manifest and segments
    are garbage; the live checkpoint was never touched."""
    delete_checkpoint_files(ckpt_path + ".resize")


def empty_doc(partition: int) -> dict:
    """A fresh document skeleton (the writer fills the capture in)."""
    return {
        "version": DOC_VERSION,
        "partition": partition,
        "cut_offset": 0,
        "op_counters": {},
        "max_commit_vc": {},
        "commit_watermarks": {},
        "repair_floors": {},
        "op_floors": {},
        "pending": [],
        "pending_floor": 0,
        "keys": {},
        "clock": {},
        "wall_us": time.time_ns() // 1000,
    }

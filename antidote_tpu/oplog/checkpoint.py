"""Per-partition checkpoint store — the snapshot half of O(delta)
recovery and log truncation (ISSUE 10).

The reference keeps per-key materialized snapshots precisely so reads
and recovery replay only a log *suffix* (reference
src/materializer_vnode.erl:36-47, 415-419), and Cure-style
geo-replication assumes stable state below the causal cut never needs
re-derivation from the op log.  Before this plane our log grew without
bound and every cold path paid for it: restart scanned the whole
partition log, and every eviction or read-below-base replayed a key's
entire committed history.

A checkpoint document is ONE pickled dict per partition:

- ``cut_offset``: the log's logical end when the cut was taken (under
  the partition lock) — recovery replays only records at/after it;
- ``op_counters`` / ``max_commit_vc``: the log watermarks at the cut,
  so the suffix scan starts from correct seeds instead of offset 0;
- ``pending``: the in-flight (staged-but-uncommitted) update records
  at the cut, ``(txid, offset, record bytes)`` in offset order — a txn
  whose updates precede the cut but whose commit lands after it
  reassembles from this prefeed (the TxnAssembler's cut-crossing
  state);
- ``keys``: ``{key: (type_name, state, frontier VC)}`` — every dirty
  key's materialized latest value at the cut, folded from the device
  plane (one batched fold per type through the PR-8 ``export_state``
  machinery) or the host materializer.  Exactly the seed
  ``HostStore.seed_state`` installs: reads covering the frontier serve
  the state, suffix ops apply on top, replay-gating skips in-base ops;
- ``commit_watermarks``: per-origin last commit opid at the cut — the
  prev-opid chain seed for gap-repair answers above the cut, and the
  watermark a bootstrapping remote SubBuf jumps to;
- ``clock``: the join of every seed frontier (the dependency-clock
  seed a bootstrap hands the receiving gate).

The file write is atomic and checksummed: frame to a temp file, fsync,
rename — a crash mid-checkpoint leaves the previous checkpoint intact,
and recovery then replays the (longer) suffix from the previous cut.
A torn/corrupt file fails the CRC and loads as None (full-scan
recovery), never as a half-document.

``ckpt_from_config`` is the one construction path (the
gate_from_config lesson): Node's partition factory routes through it,
so boot, repartition, and adopt_partition cannot honor different
knobs.  ``Config.ckpt=False`` builds no store at all — recovery,
eviction replay, and gap repair keep today's behavior bit-for-bit.

**Segmented persistence (ISSUE 13).**  The one-document form above
made every watermark checkpoint O(keyspace): the WHOLE carried seed
set re-pickled and double-fsynced per cut, however small the churn.
With ``Config.ckpt_segmented`` (default on) the seed set instead
lives in immutable, individually checksummed **segment** files
(same magic+len+crc framing, same torn-at-every-byte discipline) and
the ``.ckpt`` file becomes a small **manifest** carrying the log cut,
watermarks, floors, pending records, and the ordered segment list —
a checkpoint then writes ONE dirty-delta segment (keys whose frontier
moved since the previous cut) plus the manifest, O(churn).  Recovery
merges segments oldest→newest so each key's NEWEST entry wins; a
missing or torn segment refuses LOUDLY (the manifest loads as None
and recovery falls back to the full scan — degraded cost, never a
silent half-keyspace).  Superseded entries accumulate one per re-fold
of a dirty key; when their fraction crosses ``seg_waste_frac`` the
next checkpoint **compacts** — folds every live seed into one fresh
segment, publishes a manifest listing only it, then unlinks the old
segments — on the checkpointing thread (caller-elected, the
mat/serve.py no-background-thread discipline).  A crash anywhere
mid-compaction leaves the OLD manifest authoritative: segments are
never mutated and the manifest rename is the single commit point.
``Config.ckpt_segmented=False`` keeps the PR-9 monolithic document
bit-for-bit (the bench baseline); loading follows the on-disk
document's shape, so a knob flip across restarts recovers cleanly.
"""

from __future__ import annotations

import glob
import logging
import os
import pickle
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional

from antidote_tpu import stats
from antidote_tpu.obs.spans import tracer
from antidote_tpu.oplog.log import _fsync_dir

log = logging.getLogger(__name__)

#: checkpoint file framing: magic + [u32 len][u32 crc32(body)][body]
_MAGIC = b"ATPCKPT1"
#: seed-segment framing: same frame, its own magic — a segment file
#: truncated/renamed over a manifest (or vice versa) must parse None
_SEG_MAGIC = b"ATPCKSG1"
_FRAME = struct.Struct("<II")

#: document schema version (bump on layout change; unknown versions
#: load as None — full-scan recovery, never a misread document)
DOC_VERSION = 1


@dataclass(frozen=True)
class CheckpointSettings:
    """The checkpoint plane's knobs — built from Config by
    :func:`ckpt_from_config` (the single factory)."""

    #: write checkpoints at all; False = no store, today's recovery
    enabled: bool = True
    #: published-op watermark: a partition checkpoints after this many
    #: ops since its last cut
    every_ops: int = 4096
    #: appended-byte watermark: ... or after this many new log bytes
    every_bytes: int = 4 * 1024 * 1024
    #: reclaim log bytes below the cut after a successful checkpoint
    #: (gated by the retention floor — see PartitionLog.truncate)
    truncate: bool = True
    #: opid safety margin kept BELOW the peers' ship watermark when
    #: truncating: ordinary gap repair (lost frames) keeps answering
    #: from the log for this much recent history, so only a peer that
    #: fell further behind pays the checkpoint-bootstrap escalation
    retain_ops: int = 4096
    #: dirty-delta segment persistence (ISSUE 13): a cut writes one
    #: segment of the keys folded since the previous cut + a small
    #: manifest, O(churn); False = the PR-9 whole-seed-set document,
    #: bit-for-bit (the bench baseline)
    segmented: bool = True
    #: dead-entry fraction across segments past which the next
    #: checkpoint compacts them into one
    seg_waste_frac: float = 0.5


def ckpt_from_config(config) -> CheckpointSettings:
    """The one construction path for checkpoint settings."""
    if config is None:
        return CheckpointSettings()
    return CheckpointSettings(
        enabled=config.ckpt,
        every_ops=config.ckpt_ops,
        every_bytes=config.ckpt_bytes,
        truncate=config.ckpt_truncate,
        retain_ops=config.ckpt_retain_ops,
        segmented=config.ckpt_segmented,
        seg_waste_frac=config.ckpt_seg_waste_frac)


def segment_glob(ckpt_path: str) -> List[str]:
    """Every seed-segment file belonging to the checkpoint at
    ``ckpt_path`` — the ONE owner of the on-disk naming, shared by the
    store's sweep/delete and by every caller that retires a slot's
    checkpoint wholesale (ring resize, handoff install)."""
    return sorted(glob.glob(glob.escape(ckpt_path) + ".seg-*"))


def delete_checkpoint_files(ckpt_path: str) -> None:
    """Remove a slot's manifest/document, temp, and every segment —
    ring resizes and handoff installs retire checkpoints by PATH
    (their store object lives in another node's process, or nowhere)."""
    for p in (ckpt_path, ckpt_path + ".tmp", *segment_glob(ckpt_path)):
        try:
            os.remove(p)
        except OSError:
            pass


def install_shipped_bundle(ckpt_path: str,
                           bundle: Optional[dict]) -> None:
    """Handoff receiver: retire whatever stale checkpoint lives at
    ``ckpt_path`` (it describes a DIFFERENT log's layout) and, when
    the donor shipped one, install its bundle so the transferred log
    recovers checkpoint-seeded — FULL state even when the donor's
    below-cut bytes were truncated (the pre-ISSUE-13 receiver
    recovered suffix-only, loudly).  Lives here so the blessed module
    constructs the store (the *_from_config factory discipline); the
    settings are irrelevant to an install — only the paths are used,
    and the adopting partition re-reads the files through its own
    config-routed store."""
    # dur-ok: deliberately unlink-BEFORE-commit — the stale local
    # checkpoint describes a DIFFERENT log's layout and must not
    # survive even a crash before the shipped bundle's manifest
    # rename lands: recovery over the transferred log with no
    # checkpoint falls back to the full scan (degraded cost), while
    # adopting the stale one would seed wrong state (the PR-12
    # stale-adoption bug this function exists to prevent)
    delete_checkpoint_files(ckpt_path)
    if bundle:
        CheckpointStore(ckpt_path,
                        CheckpointSettings()).install_bundle(bundle)


class CheckpointStore:
    """Atomic load/store of one partition's checkpoint document —
    monolithic (one pickled doc) or segmented (manifest + immutable
    seed segments), per ``settings.segmented``."""

    def __init__(self, path: str, settings: CheckpointSettings):
        self.path = path
        self.settings = settings
        #: next segment sequence number — never reused, so a staged
        #: compaction output can never collide with a live segment
        self._seg_seq = self._max_seg_seq() + 1

    def _seg_path(self, seq: int) -> str:
        return f"{self.path}.seg-{seq:08d}"

    def _max_seg_seq(self) -> int:
        top = 0
        for p in segment_glob(self.path):
            try:
                top = max(top, int(p.rsplit("-", 1)[1]))
            except ValueError:
                continue
        return top

    def _sweep_segments(self, referenced: set) -> None:
        """Unlink every on-disk segment whose basename is not in
        ``referenced`` — the post-commit garbage sweep shared by the
        segmented persist (compacted-away segments + crashed-persist
        strays), the monolithic knob-flip (all of them), and the
        bundle install (local strays the shipped manifest does not
        list).  Only ever called AFTER the manifest that defines
        ``referenced`` is durably in place."""
        for p in segment_glob(self.path):
            if os.path.basename(p) not in referenced:
                try:
                    os.remove(p)
                except OSError:
                    pass

    # ------------------------------------------------------------- load

    def load_doc(self) -> Optional[dict]:
        """The current checkpoint document, or None when absent, torn,
        or from an unknown schema (recovery then falls back to the full
        scan — a bad checkpoint degrades cost, never correctness).  A
        segmented manifest loads its seed set by merging segments
        oldest→newest (each key's newest entry wins); ANY listed
        segment missing or torn refuses the whole document, loudly —
        a silently partial seed set would recover a half-keyspace as
        if it were everything."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        with tracer.span("ckpt_load", "oplog",
                         path=os.path.basename(self.path),
                         bytes=len(raw)):
            doc = self._parse(raw)
            if doc is not None and "segments" in doc:
                doc = self._load_segments(doc)
        return doc

    def _load_segments(self, doc: dict) -> Optional[dict]:
        """Materialize a manifest's seed set from its segment files."""
        merged: Dict = {}
        for name, _n_keys, _n_bytes in doc["segments"]:
            entries = self._load_segment(
                os.path.join(os.path.dirname(self.path) or ".", name))
            if entries is None:
                log.error(
                    "checkpoint manifest %s lists segment %s but it "
                    "is missing or torn — refusing the whole "
                    "checkpoint (recovery falls back to the full "
                    "scan)", self.path, name)
                return None
            merged.update(entries)
        doc["keys"] = merged
        return doc

    @staticmethod
    def _load_segment(path: str) -> Optional[dict]:
        """A segment file's ``{key: (type_name, state, vc)}``, or None
        when absent/torn/corrupt (same every-byte discipline as the
        document parse)."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        hdr = len(_SEG_MAGIC) + _FRAME.size
        if len(raw) < hdr or not raw.startswith(_SEG_MAGIC):
            return None
        ln, crc = _FRAME.unpack(raw[len(_SEG_MAGIC):hdr])
        body = raw[hdr:hdr + ln]
        if len(body) < ln or zlib.crc32(body) != crc:
            return None
        try:
            entries = pickle.loads(body)
        except Exception:  # noqa: BLE001 — corrupt segments load None
            return None
        return entries if isinstance(entries, dict) else None

    @staticmethod
    def _parse(raw: bytes) -> Optional[dict]:
        hdr = len(_MAGIC) + _FRAME.size
        if len(raw) < hdr or not raw.startswith(_MAGIC):
            return None
        ln, crc = _FRAME.unpack(raw[len(_MAGIC):hdr])
        body = raw[hdr:hdr + ln]
        if len(body) < ln or zlib.crc32(body) != crc:
            return None  # torn mid-write / bit rot: CRC catches it
        try:
            doc = pickle.loads(body)
        except Exception:  # noqa: BLE001 — a corrupt doc must load None
            return None
        if not isinstance(doc, dict) or doc.get("version") != DOC_VERSION:
            return None
        return doc

    # ------------------------------------------------------------ store

    def persist(self, doc: dict) -> None:
        """Persist one checkpoint — THE routing point of the
        ``ckpt_segmented`` knob's write side: the monolithic document
        (``write_doc``, the PR-9 bytes exactly) or a dirty-delta
        segment + manifest.  ``doc`` carries the full merged seed set
        in ``keys`` and, when the caller folded incrementally, the
        dirty-only delta in ``delta`` (manager._ckpt_fold)."""
        tracer.instant("ckpt_persist", "oplog",
                       path=os.path.basename(self.path),
                       segmented=self.settings.segmented)
        if not self.settings.segmented:
            doc.pop("delta", None)  # monolithic docs carry keys only
            self.write_doc(doc)
            # a knob flip back to monolithic strands the previous
            # manifest's segments: the document just written carries
            # every seed inline, so they are garbage now
            self._sweep_segments(set())
            return
        self._persist_segmented(doc)

    def write_doc(self, doc: dict) -> int:
        """Atomically persist ``doc``; returns the file size.  The
        write is temp + fsync + rename, so a crash at ANY byte leaves
        either the previous checkpoint or the new one — never a blend
        (proven by the truncate-at-every-byte differential in
        tests/unit/test_checkpoint.py)."""
        t0 = time.perf_counter()
        body = pickle.dumps(doc, protocol=pickle.HIGHEST_PROTOCOL)
        raw = _MAGIC + _FRAME.pack(len(body), zlib.crc32(body)) + body
        tmp = self.path + ".tmp"
        with tracer.span("ckpt_write", "oplog",
                         path=os.path.basename(self.path),
                         bytes=len(raw), keys=len(doc.get("keys", ()))):
            with open(tmp, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            _fsync_dir(os.path.dirname(self.path),
                       instant="ckpt_dir_fsync")
        reg = stats.registry
        reg.ckpt_writes.inc()
        reg.ckpt_duration.observe(time.perf_counter() - t0)
        return len(raw)

    def _write_segment(self, entries: dict) -> tuple:
        """One immutable seed segment: frame, write, fsync.  No rename
        dance — the file is not live until a MANIFEST lists it, and
        the sequence numbering never reuses a name, so a crash leaves
        only an unreferenced stray (swept by the next persist).
        Returns (basename, n_keys, n_bytes)."""
        seq = self._seg_seq
        self._seg_seq += 1
        path = self._seg_path(seq)
        body = pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)
        raw = _SEG_MAGIC + _FRAME.pack(len(body), zlib.crc32(body)) \
            + body
        with tracer.span("ckpt_seg_write", "oplog",
                         path=os.path.basename(path), bytes=len(raw),
                         keys=len(entries)):
            with open(path, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
        return os.path.basename(path), len(entries), len(raw)

    def _persist_segmented(self, doc: dict) -> None:
        """Dirty-delta persist: write ONE segment holding the keys
        folded since the previous cut, then the manifest (atomic
        rename — the commit point).  Compaction is elected HERE, on
        the checkpointing thread, when the superseded-entry fraction
        across segments crosses ``seg_waste_frac``: every live seed
        folds into one fresh segment and the manifest lists only it.
        Old segments are unlinked only AFTER the new manifest landed —
        a crash at any earlier byte leaves the previous manifest
        authoritative over files that still all exist."""
        t0 = time.perf_counter()
        delta = doc.pop("delta", None)
        if delta is None:
            # no incremental fold (first cut, or a caller handing a
            # fully-materialized doc): the whole seed set is the delta
            delta = doc["keys"]
        prev = doc.pop("prev_segments", [])
        live = len(doc["keys"])
        # elect compaction from the PROSPECTIVE shape (previous
        # segments + the delta about to be written) BEFORE paying for
        # the delta segment: a compacting cut writes ONLY the
        # compacted segment — the delta is a subset of the live set,
        # and writing-then-unlinking it would double the fsyncs on
        # exactly the cuts that are already the most expensive
        n_segs = len(prev) + (1 if delta else 0)
        total = sum(n for _name, n, _b in prev) + len(delta)
        dead_frac = (total - live) / total if total else 0.0
        compacted = (n_segs > 1 and dead_frac >= max(
            self.settings.seg_waste_frac, 1e-9))
        if compacted:
            segments = [self._write_segment(doc["keys"])]
        else:
            segments = list(prev)
            if delta:
                segments.append(self._write_segment(delta))
        tracer.instant("ckpt_manifest", "oplog",
                       path=os.path.basename(self.path),
                       segments=len(segments), compacted=compacted)
        keys = doc.pop("keys")  # the manifest carries the list, not
        try:                    # the seed states themselves
            doc["segments"] = segments
            self.write_doc(doc)
        finally:
            doc["keys"] = keys
        # post-commit sweep: everything the live manifest does not
        # reference (compacted-away segments, strays from a crashed
        # persist) is garbage now
        self._sweep_segments({name for name, _n, _b in segments})
        reg = stats.registry
        if compacted:
            reg.ckpt_seg_compactions.inc()
        lbl = str(doc.get("partition", ""))
        reg.ckpt_seg_count.set(len(segments), partition=lbl)
        reg.ckpt_seg_bytes.set(sum(b for _n, _k, b in segments),
                               partition=lbl)
        total = sum(n for _name, n, _b in segments)
        reg.ckpt_seg_dead_frac.set(
            (total - live) / total if total else 0.0, partition=lbl)
        if delta:
            us = (time.perf_counter() - t0) * 1e6
            reg.ckpt_seg_persist_us_per_key.set(us / len(delta))

    def delete(self) -> None:
        delete_checkpoint_files(self.path)

    # --------------------------------------------- handoff shipping

    def ship_bundle(self) -> Optional[dict]:
        """The checkpoint as one transferable unit (ISSUE 13 handoff):
        raw manifest/document bytes + every referenced segment's raw
        bytes.  Segments are immutable, so they copy without the
        truncation-epoch dance the raw log needs; the only race is a
        compaction unlinking a listed segment between the manifest
        read and the segment read — bounded retries re-read the fresh
        manifest.  None when no (valid) checkpoint exists."""
        for _attempt in range(5):
            try:
                with open(self.path, "rb") as f:
                    manifest_raw = f.read()
            except OSError:
                return None
            doc = self._parse(manifest_raw)
            if doc is None:
                return None
            segs: Dict[str, bytes] = {}
            ok = True
            for name, _n, _b in doc.get("segments", ()):
                try:
                    with open(os.path.join(
                            os.path.dirname(self.path) or ".",
                            name), "rb") as f:
                        segs[name] = f.read()
                except OSError:
                    ok = False  # compacted away mid-read: re-read
                    break
            if ok:
                return {"manifest": manifest_raw, "segments": segs}
        # exhausted: every attempt lost the read race to a compaction.
        # RAISE rather than return None — None means "no checkpoint to
        # ship" and the receiver proceeds quietly; a donor that HAS
        # one but could not be read must surface as a retryable error
        # so the puller's retry/warning path engages (a truncated
        # donor's below-cut history silently not transferring is the
        # exact hole this bundle exists to close)
        raise OSError(
            f"checkpoint bundle read at {self.path} kept losing to "
            "concurrent compaction; retry the pull")

    def install_bundle(self, bundle: dict) -> None:
        """Install a shipped checkpoint at this store's path: segments
        first (dead files until referenced), then the manifest via the
        atomic temp+rename (the commit point), then a sweep of local
        strays the shipped manifest does not list.  A torn install
        (crash before the rename) leaves whatever manifest was live
        before — never a blend."""
        d = os.path.dirname(self.path) or "."
        with tracer.span("ckpt_install_bundle", "oplog",
                         path=os.path.basename(self.path),
                         segments=len(bundle.get("segments", ()))):
            for name, raw in bundle.get("segments", {}).items():
                base = os.path.basename(name)  # no path traversal
                with open(os.path.join(d, base), "wb") as f:
                    f.write(raw)
                    f.flush()
                    os.fsync(f.fileno())
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(bundle["manifest"])
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            _fsync_dir(d, instant="ckpt_dir_fsync")
        self._sweep_segments({os.path.basename(n)
                              for n in bundle.get("segments", ())})
        self._seg_seq = self._max_seg_seq() + 1


def empty_doc(partition: int) -> dict:
    """A fresh document skeleton (the writer fills the capture in)."""
    return {
        "version": DOC_VERSION,
        "partition": partition,
        "cut_offset": 0,
        "op_counters": {},
        "max_commit_vc": {},
        "commit_watermarks": {},
        "repair_floors": {},
        "op_floors": {},
        "pending": [],
        "pending_floor": 0,
        "keys": {},
        "clock": {},
        "wall_us": time.time_ns() // 1000,
    }

"""Per-partition checkpoint store — the snapshot half of O(delta)
recovery and log truncation (ISSUE 10).

The reference keeps per-key materialized snapshots precisely so reads
and recovery replay only a log *suffix* (reference
src/materializer_vnode.erl:36-47, 415-419), and Cure-style
geo-replication assumes stable state below the causal cut never needs
re-derivation from the op log.  Before this plane our log grew without
bound and every cold path paid for it: restart scanned the whole
partition log, and every eviction or read-below-base replayed a key's
entire committed history.

A checkpoint document is ONE pickled dict per partition:

- ``cut_offset``: the log's logical end when the cut was taken (under
  the partition lock) — recovery replays only records at/after it;
- ``op_counters`` / ``max_commit_vc``: the log watermarks at the cut,
  so the suffix scan starts from correct seeds instead of offset 0;
- ``pending``: the in-flight (staged-but-uncommitted) update records
  at the cut, ``(txid, offset, record bytes)`` in offset order — a txn
  whose updates precede the cut but whose commit lands after it
  reassembles from this prefeed (the TxnAssembler's cut-crossing
  state);
- ``keys``: ``{key: (type_name, state, frontier VC)}`` — every dirty
  key's materialized latest value at the cut, folded from the device
  plane (one batched fold per type through the PR-8 ``export_state``
  machinery) or the host materializer.  Exactly the seed
  ``HostStore.seed_state`` installs: reads covering the frontier serve
  the state, suffix ops apply on top, replay-gating skips in-base ops;
- ``commit_watermarks``: per-origin last commit opid at the cut — the
  prev-opid chain seed for gap-repair answers above the cut, and the
  watermark a bootstrapping remote SubBuf jumps to;
- ``clock``: the join of every seed frontier (the dependency-clock
  seed a bootstrap hands the receiving gate).

The file write is atomic and checksummed: frame to a temp file, fsync,
rename — a crash mid-checkpoint leaves the previous checkpoint intact,
and recovery then replays the (longer) suffix from the previous cut.
A torn/corrupt file fails the CRC and loads as None (full-scan
recovery), never as a half-document.

``ckpt_from_config`` is the one construction path (the
gate_from_config lesson): Node's partition factory routes through it,
so boot, repartition, and adopt_partition cannot honor different
knobs.  ``Config.ckpt=False`` builds no store at all — recovery,
eviction replay, and gap repair keep today's behavior bit-for-bit.
"""

from __future__ import annotations

import os
import pickle
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Optional

from antidote_tpu import stats
from antidote_tpu.obs.spans import tracer
from antidote_tpu.oplog.log import _fsync_dir

#: checkpoint file framing: magic + [u32 len][u32 crc32(body)][body]
_MAGIC = b"ATPCKPT1"
_FRAME = struct.Struct("<II")

#: document schema version (bump on layout change; unknown versions
#: load as None — full-scan recovery, never a misread document)
DOC_VERSION = 1


@dataclass(frozen=True)
class CheckpointSettings:
    """The checkpoint plane's knobs — built from Config by
    :func:`ckpt_from_config` (the single factory)."""

    #: write checkpoints at all; False = no store, today's recovery
    enabled: bool = True
    #: published-op watermark: a partition checkpoints after this many
    #: ops since its last cut
    every_ops: int = 4096
    #: appended-byte watermark: ... or after this many new log bytes
    every_bytes: int = 4 * 1024 * 1024
    #: reclaim log bytes below the cut after a successful checkpoint
    #: (gated by the retention floor — see PartitionLog.truncate)
    truncate: bool = True
    #: opid safety margin kept BELOW the peers' ship watermark when
    #: truncating: ordinary gap repair (lost frames) keeps answering
    #: from the log for this much recent history, so only a peer that
    #: fell further behind pays the checkpoint-bootstrap escalation
    retain_ops: int = 4096


def ckpt_from_config(config) -> CheckpointSettings:
    """The one construction path for checkpoint settings."""
    if config is None:
        return CheckpointSettings()
    return CheckpointSettings(
        enabled=config.ckpt,
        every_ops=config.ckpt_ops,
        every_bytes=config.ckpt_bytes,
        truncate=config.ckpt_truncate,
        retain_ops=config.ckpt_retain_ops)


class CheckpointStore:
    """Atomic load/store of one partition's checkpoint document."""

    def __init__(self, path: str, settings: CheckpointSettings):
        self.path = path
        self.settings = settings

    # ------------------------------------------------------------- load

    def load_doc(self) -> Optional[dict]:
        """The current checkpoint document, or None when absent, torn,
        or from an unknown schema (recovery then falls back to the full
        scan — a bad checkpoint degrades cost, never correctness)."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        with tracer.span("ckpt_load", "oplog",
                         path=os.path.basename(self.path),
                         bytes=len(raw)):
            doc = self._parse(raw)
        return doc

    @staticmethod
    def _parse(raw: bytes) -> Optional[dict]:
        hdr = len(_MAGIC) + _FRAME.size
        if len(raw) < hdr or not raw.startswith(_MAGIC):
            return None
        ln, crc = _FRAME.unpack(raw[len(_MAGIC):hdr])
        body = raw[hdr:hdr + ln]
        if len(body) < ln or zlib.crc32(body) != crc:
            return None  # torn mid-write / bit rot: CRC catches it
        try:
            doc = pickle.loads(body)
        except Exception:  # noqa: BLE001 — a corrupt doc must load None
            return None
        if not isinstance(doc, dict) or doc.get("version") != DOC_VERSION:
            return None
        return doc

    # ------------------------------------------------------------ store

    def write_doc(self, doc: dict) -> int:
        """Atomically persist ``doc``; returns the file size.  The
        write is temp + fsync + rename, so a crash at ANY byte leaves
        either the previous checkpoint or the new one — never a blend
        (proven by the truncate-at-every-byte differential in
        tests/unit/test_checkpoint.py)."""
        t0 = time.perf_counter()
        body = pickle.dumps(doc, protocol=pickle.HIGHEST_PROTOCOL)
        raw = _MAGIC + _FRAME.pack(len(body), zlib.crc32(body)) + body
        tmp = self.path + ".tmp"
        with tracer.span("ckpt_write", "oplog",
                         path=os.path.basename(self.path),
                         bytes=len(raw), keys=len(doc.get("keys", ()))):
            with open(tmp, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            _fsync_dir(os.path.dirname(self.path),
                       instant="ckpt_dir_fsync")
        reg = stats.registry
        reg.ckpt_writes.inc()
        reg.ckpt_duration.observe(time.perf_counter() - t0)
        return len(raw)

    def delete(self) -> None:
        for p in (self.path, self.path + ".tmp"):
            try:
                os.remove(p)
            except OSError:
                pass


def empty_doc(partition: int) -> dict:
    """A fresh document skeleton (the writer fills the capture in)."""
    return {
        "version": DOC_VERSION,
        "partition": partition,
        "cut_offset": 0,
        "op_counters": {},
        "max_commit_vc": {},
        "commit_watermarks": {},
        "repair_floors": {},
        "op_floors": {},
        "pending": [],
        "pending_floor": 0,
        "keys": {},
        "clock": {},
        "wall_us": time.time_ns() // 1000,
    }

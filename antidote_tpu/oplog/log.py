"""Durable append-only log — Python API over the native core.

Mirrors the reference's per-partition disk_log usage (reference
src/logging_vnode.erl:896-919): buffered appends on the update path,
fsync only on commit (``sync``), crash recovery truncating a torn tail.
The record store is byte-payload framing only; record semantics live in
:mod:`antidote_tpu.oplog.records`.

Backend: ctypes over antidote_tpu/native/oplog.cpp (built on demand); a
pure-Python fallback with identical behavior exists for environments
without a compiler and for differential testing.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
import zlib
from typing import Iterator, Optional, Tuple

from antidote_tpu.native.build import ensure_built

_HEADER = struct.Struct("<II")  # len, crc32


class _NativeBackend:
    _lib = None

    @classmethod
    def load(cls):
        if cls._lib is not None:
            return cls._lib
        so = ensure_built("oplog")
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # stale/wrong-platform artifact: auto mode falls back to the
            # pure-Python backend instead of failing node startup
            return None
        lib.oplog_open.restype = ctypes.c_void_p
        lib.oplog_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.oplog_append.restype = ctypes.c_int64
        lib.oplog_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64]
        lib.oplog_flush.argtypes = [ctypes.c_void_p]
        lib.oplog_sync.argtypes = [ctypes.c_void_p]
        lib.oplog_recover.restype = ctypes.c_int64
        lib.oplog_recover.argtypes = [ctypes.c_void_p]
        lib.oplog_end_offset.restype = ctypes.c_int64
        lib.oplog_end_offset.argtypes = [ctypes.c_void_p]
        lib.oplog_read.restype = ctypes.c_int64
        lib.oplog_read.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.c_char_p, ctypes.c_int64]
        lib.oplog_next.restype = ctypes.c_int64
        lib.oplog_next.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.oplog_close.argtypes = [ctypes.c_void_p]
        cls._lib = lib
        return lib


class DurableLog:
    """One append-only log file with CRC-framed records."""

    def __init__(self, path: str, backend: str = "auto"):
        self.path = path
        self._native = None
        self._py = None
        #: guards every native-handle use against close(): a member
        #: shutdown can race an in-flight remote-apply append on a
        #: delivery thread, and calling into the C backend with a freed
        #: handle is a segfault, not an exception (caught live by
        #: tests/cluster/test_causal_federation.py restart chaos).  A
        #: closed log raises OSError from append/read instead.
        self._lock = threading.Lock()
        lib = _NativeBackend.load() if backend in ("auto", "native") else None
        if lib is not None:
            h = lib.oplog_open(path.encode(), 1)
            if not h:
                raise OSError(f"cannot open log {path}")
            self._native = (lib, ctypes.c_void_p(h))
            lib.oplog_recover(self._native[1])
        elif backend == "native":
            raise RuntimeError("native oplog backend unavailable")
        else:
            self._py = _PyLog(path)

    @property
    def backend_name(self) -> str:
        return "native" if self._native else "python"

    def append(self, payload: bytes) -> int:
        """Buffered append; returns the record's offset."""
        if not payload:
            # recovery treats a zero-length frame as a torn tail; storing
            # one would truncate every later record on restart
            raise ValueError("empty log records are not allowed")
        with self._lock:
            if self._native:
                lib, h = self._native
                off = lib.oplog_append(h, payload, len(payload))
                if off < 0:
                    raise OSError("append failed")
                return off
            if self._py is None:
                raise OSError(f"log {self.path} is closed")
            return self._py.append(payload)

    def flush(self) -> None:
        with self._lock:
            if self._native:
                self._native[0].oplog_flush(self._native[1])
            elif self._py is not None:  # no-op on a closed log
                self._py.flush()

    def sync(self) -> None:
        """Flush + fsync — the commit-path durability barrier.

        Holds the log lock across the fsync: same-partition appenders
        already serialize behind the partition lock at every call site,
        so the extra exclusion is cross-path only (handoff byte reads,
        migration scans — rare).  A refcounted close guard would keep
        fsync out of the critical section; deliberately not attempted
        hours before round end (memory safety first)."""
        with self._lock:
            if self._native:
                self._native[0].oplog_sync(self._native[1])
            elif self._py is not None:  # no-op on a closed log
                self._py.sync()

    def end_offset(self) -> int:
        with self._lock:
            if self._native:
                return self._native[0].oplog_end_offset(self._native[1])
            if self._py is None:
                raise OSError(f"log {self.path} is closed")
            return self._py.end

    def read(self, offset: int) -> Optional[bytes]:
        with self._lock:
            if self._native:
                lib, h = self._native
                n = 4096
                while True:
                    buf = ctypes.create_string_buffer(n)
                    got = lib.oplog_read(h, offset, buf, n)
                    if got < 0:
                        return None
                    if got <= n:
                        return buf.raw[:got]
                    n = int(got)
            if self._py is None:
                raise OSError(f"log {self.path} is closed")
            return self._py.read(offset)

    def scan(self, offset: int = 0) -> Iterator[Tuple[int, bytes]]:
        """Iterate (offset, payload) from ``offset`` to the end."""
        while True:
            payload = self.read(offset)
            if payload is None:
                return
            yield offset, payload
            with self._lock:
                if self._native:
                    nxt = self._native[0].oplog_next(
                        self._native[1], offset)
                elif self._py is not None:
                    nxt = self._py.next_offset(offset)
                else:
                    # closed mid-scan: a silent partial history would
                    # be served as a successful replay
                    raise OSError(f"log {self.path} closed mid-scan")
            if nxt < 0:
                return
            offset = nxt

    def close(self) -> None:
        with self._lock:
            if self._native:
                self._native[0].oplog_close(self._native[1])
                self._native = None
            elif self._py:
                self._py.close()
                self._py = None


class _PyLog:
    """Pure-Python twin of the native backend (same on-disk format)."""

    def __init__(self, path: str):
        self.f = open(path, "a+b")
        self.f.seek(0, os.SEEK_END)
        self.end = self.f.tell()
        self._recover()

    def _recover(self) -> None:
        self.f.flush()
        size = os.fstat(self.f.fileno()).st_size
        off = 0
        while off + _HEADER.size <= size:
            self.f.seek(off)
            hdr = self.f.read(_HEADER.size)
            if len(hdr) < _HEADER.size:
                break
            ln, crc = _HEADER.unpack(hdr)
            if ln == 0 or off + _HEADER.size + ln > size:
                break
            payload = self.f.read(ln)
            if len(payload) < ln or zlib.crc32(payload) != crc:
                break
            off += _HEADER.size + ln
        if off < size:
            self.f.truncate(off)
        self.end = off
        self.f.seek(0, os.SEEK_END)

    def append(self, payload: bytes) -> int:
        off = self.end
        self.f.seek(0, os.SEEK_END)
        self.f.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
        self.f.write(payload)
        self.end += _HEADER.size + len(payload)
        return off

    def flush(self) -> None:
        self.f.flush()

    def sync(self) -> None:
        self.f.flush()
        os.fsync(self.f.fileno())

    def read(self, offset: int) -> Optional[bytes]:
        self.f.flush()
        if offset + _HEADER.size > self.end:
            return None
        self.f.seek(offset)
        ln, crc = _HEADER.unpack(self.f.read(_HEADER.size))
        if offset + _HEADER.size + ln > self.end:
            return None
        payload = self.f.read(ln)
        if len(payload) < ln or zlib.crc32(payload) != crc:
            return None
        return payload

    def next_offset(self, offset: int) -> int:
        self.f.flush()
        if offset + _HEADER.size > self.end:
            return -1
        self.f.seek(offset)
        ln, _ = _HEADER.unpack(self.f.read(_HEADER.size))
        nxt = offset + _HEADER.size + ln
        self.f.seek(0, os.SEEK_END)
        return nxt if nxt <= self.end else -1

    def close(self) -> None:
        self.f.close()

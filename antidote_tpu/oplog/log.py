"""Durable append-only log — Python API over the native core.

Mirrors the reference's per-partition disk_log usage (reference
src/logging_vnode.erl:896-919): buffered appends on the update path,
fsync only on commit (``sync``), crash recovery truncating a torn tail.
The record store is byte-payload framing only; record semantics live in
:mod:`antidote_tpu.oplog.records`.

Backend: ctypes over antidote_tpu/native/oplog.cpp (built on demand); a
pure-Python fallback with identical behavior exists for environments
without a compiler and for differential testing.

ISSUE 9 adds the **group-commit plane**: with :class:`GroupSettings`
enabled, appends STAGE framed record bytes (offsets assigned
immediately — staging preserves append order, so the logical offset IS
the final file offset), and durability is ticket-based: a committer
takes ``ticket = end_offset()`` after its commit record stages,
releases its partition lock, and calls :meth:`wait_durable`.  The
first waiter with no drain in flight leads: it may hold the window
open (``group_us``, only while OTHER committers are waiting — a solo
committer drains immediately, so uncontended commits pay zero added
latency), then writes every staged record through the backend in ONE
batch append (``oplog_append_batch`` — one ctypes crossing, one
buffered write) and runs ONE fsync outside the handle lock; the synced
watermark then covers every waiter staged before the write.  The
on-disk format is byte-identical to the per-record legacy path
(asserted by the crash-recovery differential tests), and
``GroupSettings.enabled=False`` keeps the legacy write path exactly.

The fsync itself runs OUTSIDE the handle lock via a refcounted close
guard (the deliberately-deferred item of the round-2 sync design):
``close()`` waits for in-flight backend IO instead of freeing the
handle under a waiting fsync, so handoff byte-reads and migration
scans no longer stall behind disk.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.native.build import ensure_built
from antidote_tpu.obs.spans import tracer

_HEADER = struct.Struct("<II")  # len, crc32

#: truncation-marker payload (ISSUE 10): when log bytes below a
#: checkpoint cut are reclaimed, the rewritten file STARTS with one
#: ordinary CRC-framed record whose payload is this magic + the first
#: retained record's LOGICAL offset.  Every offset ever handed out
#: (op-id index, key-commit index, durability tickets, checkpoint
#: cuts) stays valid across truncation: the log translates logical <->
#: physical by the marker's delta, and the native scanner needs no
#: change (the marker is a well-formed record it skips like any other).
_TRUNC_MAGIC = b"ATPTRUNC\x01"
_TRUNC_BASE = struct.Struct("<q")
#: framed size of a truncation-marker record (constant by construction)
TRUNC_MARKER_LEN = _HEADER.size + len(_TRUNC_MAGIC) + _TRUNC_BASE.size


def _trunc_marker(base: int) -> bytes:
    payload = _TRUNC_MAGIC + _TRUNC_BASE.pack(base)
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def _parse_trunc_marker(payload: Optional[bytes]) -> Optional[int]:
    """The marker's logical base, or None when ``payload`` is not a
    truncation marker."""
    if payload is None or not payload.startswith(_TRUNC_MAGIC):
        return None
    if len(payload) != len(_TRUNC_MAGIC) + _TRUNC_BASE.size:
        return None
    return _TRUNC_BASE.unpack(payload[len(_TRUNC_MAGIC):])[0]


def _peek_trunc_base(path: str) -> int:
    """The truncation base of the log at ``path``, read raw (no
    backend open needed — the recovery-hint translation runs before
    the backend exists); 0 on a never-truncated/absent log."""
    try:
        with open(path, "rb") as f:
            hdr = f.read(_HEADER.size)
            if len(hdr) < _HEADER.size:
                return 0
            ln, crc = _HEADER.unpack(hdr)
            if ln != len(_TRUNC_MAGIC) + _TRUNC_BASE.size:
                return 0
            payload = f.read(ln)
            if len(payload) < ln or zlib.crc32(payload) != crc:
                return 0
            return _parse_trunc_marker(payload) or 0
    except OSError:
        return 0


def _copy_range(src, dst, nbytes: int, chunk: int = 1 << 20) -> None:
    """Copy exactly ``nbytes`` from ``src`` to ``dst`` in bounded
    chunks — the truncation tail copy must stop at the file end
    captured under the lock (an unbounded ``copyfileobj`` would chase
    concurrent appends and could tear a half-written record); 1 MB
    chunks keep RSS flat when the retained suffix is hundreds of MB.
    A short read is an ERROR, not an end condition: silently keeping
    fewer bytes would let the commit rename a log missing bytes in the
    middle — recovery's parse stops at the seam and everything above
    it is lost without a word."""
    while nbytes > 0:
        buf = src.read(min(chunk, nbytes))
        if not buf:
            raise OSError(
                f"truncation copy came up {nbytes} bytes short of the "
                "end captured under the lock — refusing to stage a "
                "log with a hole")
        dst.write(buf)
        nbytes -= len(buf)


def _fsync_dir(d: str, instant: str = "log_dir_fsync") -> None:
    """Durable rename: fsync the containing directory so a power cut
    cannot resurrect the pre-rename inode (best-effort — not every fs
    exposes a directory fd).  The ONE copy of this discipline: the
    checkpoint writer's rename imports it too (``instant`` names the
    trace event per caller)."""
    try:
        fd = os.open(d or ".", os.O_RDONLY)
    except OSError:
        return
    tracer.instant(instant, "oplog", dir=os.path.basename(d))
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@dataclass(frozen=True)
class GroupSettings:
    """The group-commit plane's knobs — built from Config by
    :func:`log_group_from_config` (the single factory) so every
    assembly honors the same values (the gate_from_config lesson)."""

    #: staged batch appends + ticket-based durability; False = the
    #: exact per-record legacy path (the benches' comparison baseline)
    enabled: bool = True
    #: window, µs: a drain leader with company holds the fsync open
    #: this long; a solo committer drains immediately
    group_us: int = 300
    #: staged-record budget: past it the window closes at once and the
    #: non-synced path writes staged records through (backpressure)
    group_records: int = 512
    #: staged-byte budget: bounds the heap a log pins and the process-
    #: crash loss window on the non-synced path (written-through bytes
    #: reach the page cache, which survives a process crash)
    group_bytes: int = 256 * 1024


def log_group_from_config(config) -> GroupSettings:
    """The one construction path for group-commit settings — Node's
    partition factory routes through this, so single-node and cluster
    assemblies cannot silently honor different knobs."""
    if config is None:
        return GroupSettings()
    return GroupSettings(
        enabled=config.log_group,
        group_us=config.log_group_us,
        group_records=config.log_group_records,
        group_bytes=config.log_group_bytes)


class _NativeBackend:
    _lib = None

    @classmethod
    def load(cls):
        if cls._lib is not None:
            return cls._lib
        so = ensure_built("oplog")
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # stale/wrong-platform artifact: auto mode falls back to the
            # pure-Python backend instead of failing node startup
            return None
        lib.oplog_open.restype = ctypes.c_void_p
        lib.oplog_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.oplog_append.restype = ctypes.c_int64
        lib.oplog_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64]
        try:
            lib.oplog_recover_from.restype = ctypes.c_int64
            lib.oplog_recover_from.argtypes = [ctypes.c_void_p,
                                               ctypes.c_int64]
            lib.has_recover_from = True
        except AttributeError:
            # a stale prebuilt .so without the ISSUE-10 symbol (no
            # compiler to rebuild): recovery falls back to the full
            # scan — slower, never wrong
            lib.has_recover_from = False
        lib.oplog_append_batch.restype = ctypes.c_int64
        lib.oplog_append_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.oplog_flush.argtypes = [ctypes.c_void_p]
        lib.oplog_sync.argtypes = [ctypes.c_void_p]
        lib.oplog_recover.restype = ctypes.c_int64
        lib.oplog_recover.argtypes = [ctypes.c_void_p]
        lib.oplog_end_offset.restype = ctypes.c_int64
        lib.oplog_end_offset.argtypes = [ctypes.c_void_p]
        lib.oplog_read.restype = ctypes.c_int64
        lib.oplog_read.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.c_char_p, ctypes.c_int64]
        lib.oplog_next.restype = ctypes.c_int64
        lib.oplog_next.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.oplog_close.argtypes = [ctypes.c_void_p]
        cls._lib = lib
        return lib


class DurableLog:
    """One append-only log file with CRC-framed records."""

    def __init__(self, path: str, backend: str = "auto",
                 group: Optional[GroupSettings] = None,
                 recover_hint: int = 0):
        #: ``recover_hint``: LOGICAL offset the caller trusts as a
        #: valid record boundary with only durable data below it (a
        #: checkpoint cut, ISSUE 10) — open-time torn-tail recovery
        #: then validates only the suffix past it, O(delta) instead of
        #: O(file).  A hint that turns out not to be a boundary falls
        #: back to the full scan; 0 = always full scan.
        self.path = path
        self._native = None
        self._py = None
        # a stray rewrite temp is a truncation the crash beat to the
        # rename: the original file is intact and authoritative
        try:
            os.remove(path + ".trunc-tmp")
        except OSError:
            pass
        #: guards every native-handle use against close(): a member
        #: shutdown can race an in-flight remote-apply append on a
        #: delivery thread, and calling into the C backend with a freed
        #: handle is a segfault, not an exception (caught live by
        #: tests/cluster/test_causal_federation.py restart chaos).  A
        #: closed log raises OSError from append/read instead.  A
        #: Condition (not a bare Lock) so durability waiters and the
        #: refcounted close guard can block on it.
        self._lock = threading.Condition()
        #: out-of-lock backend IO in flight (fsync): close() waits for
        #: this to reach zero before freeing the handle
        self._io_refs = 0
        #: a stage_truncate_below tail copy is composing the rewrite
        #: temp — a second stager would race the one temp path
        self._trunc_staging = False
        #: generation counter stamped into stage tokens: abort/commit
        #: act only on the stage currently in flight, so a late abort
        #: of an already-consumed token cannot unlink a NEWER stage's
        #: temp out from under it
        self._trunc_seq = 0
        # a crash between stage and commit strands a fully composed
        # (retained-suffix-sized) temp nothing will ever redeem — no
        # stage can be in flight at construction, so it is garbage
        try:
            os.remove(path + ".trunc-tmp")
        except OSError:
            pass
        phys_hint = 0
        if recover_hint > 0:
            base = _peek_trunc_base(path)
            delta = (base - TRUNC_MARKER_LEN) if base else 0
            if recover_hint >= base:
                phys_hint = recover_hint - delta
        lib = _NativeBackend.load() if backend in ("auto", "native") else None
        if lib is not None:
            h = lib.oplog_open(path.encode(), 1)
            if not h:
                raise OSError(f"cannot open log {path}")
            self._native = (lib, ctypes.c_void_p(h))
            recovered = -2
            if phys_hint > 0 and lib.has_recover_from:
                recovered = lib.oplog_recover_from(self._native[1],
                                                   phys_hint)
            if recovered < 0:
                lib.oplog_recover(self._native[1])
        elif backend == "native":
            raise RuntimeError("native oplog backend unavailable")
        else:
            self._py = _PyLog(path, recover_hint=phys_hint)
        #: truncation state (ISSUE 10): logical offsets are stable
        #: across truncation — ``_base`` is the first retained logical
        #: offset, ``_delta`` the logical-minus-physical shift every
        #: retained record carries (0 on a never-truncated log)
        self._base = 0
        self._delta = 0
        base = _parse_trunc_marker(self._backend_read_locked(0))
        if base is not None:
            self._base = base
            self._delta = base - TRUNC_MARKER_LEN
        # ---- group-commit state (ISSUE 9); inert when _group is None
        self._group = group if (group is not None and group.enabled) \
            else None
        end = self._backend_end_locked() + self._delta
        #: staged framed-record payloads, stage order == file order
        self._staged: List[bytes] = []
        self._staged_bytes = 0
        #: logical end: written bytes + staged bytes (offset source)
        self._logical_end = end
        #: bytes written through the backend (buffered, not yet synced)
        self._written_end = end
        #: bytes covered by an fsync — the durability watermark tickets
        #: compare against
        self._synced_end = end
        self._written_records = 0
        self._synced_records = 0
        #: per-instance drain accounting (the bench reads these so a
        #: legacy leg in the same process cannot pollute the ratios)
        self.fsyncs = 0
        self.drained_records = 0
        self.held_drains = 0
        self._syncing = False
        self._sync_waiters = 0
        #: monotonic stamp of the first staged record since the last
        #: drain (the group window opens here, the serve-plane recipe)
        self._window_open: Optional[float] = None

    @property
    def backend_name(self) -> str:
        return "native" if self._native else "python"

    @property
    def group_active(self) -> bool:
        return self._group is not None

    @property
    def truncated_base(self) -> int:
        """First logical offset still on disk (0 = never truncated)."""
        return self._base

    def _backend_end_locked(self) -> int:
        """PHYSICAL end of the backing file (callers add _delta)."""
        if self._native:
            return self._native[0].oplog_end_offset(self._native[1])
        if self._py is not None:
            return self._py.end
        raise OSError(f"log {self.path} is closed")

    def _backend_read_locked(self, phys: int) -> Optional[bytes]:
        """Record payload at PHYSICAL offset ``phys`` (None at/past
        end or on corruption); must run under self._lock."""
        if phys < 0:
            return None
        if self._native:
            lib, h = self._native
            n = 4096
            while True:
                buf = ctypes.create_string_buffer(n)
                got = lib.oplog_read(h, phys, buf, n)
                if got < 0:
                    return None
                if got <= n:
                    return buf.raw[:got]
                n = int(got)
        if self._py is None:
            raise OSError(f"log {self.path} is closed")
        return self._py.read(phys)

    # ------------------------------------------------------------- append

    def append(self, payload: bytes) -> int:
        """Buffered append; returns the record's offset.  Group mode
        stages the framed payload (one batch write per drain) — the
        offset is assigned now and is exact: staging preserves order
        and every backend write funnels through the staged queue."""
        if not payload:
            # recovery treats a zero-length frame as a torn tail; storing
            # one would truncate every later record on restart
            raise ValueError("empty log records are not allowed")
        with self._lock:
            if self._group is not None:
                if self._native is None and self._py is None:
                    raise OSError(f"log {self.path} is closed")
                off = self._logical_end
                self._staged.append(payload)
                self._staged_bytes += len(payload)
                self._logical_end += _HEADER.size + len(payload)
                if self._window_open is None:
                    self._window_open = time.monotonic()
                stats.registry.log_staged_records.inc()
                if (len(self._staged) >= self._group.group_records
                        or self._staged_bytes
                        >= self._group.group_bytes):
                    # backpressure: the non-synced path (updates under
                    # sync_on_commit=False) must not grow the staged
                    # queue unboundedly — write through (no fsync)
                    self._write_staged_locked()
                return off
            if self._native:
                lib, h = self._native
                off = lib.oplog_append(h, payload, len(payload))
                if off < 0:
                    raise OSError("append failed")
                return off + self._delta
            if self._py is None:
                raise OSError(f"log {self.path} is closed")
            return self._py.append(payload) + self._delta

    def append_batch(self, payloads: List[bytes]) -> int:
        """Append many records with ONE backend crossing and one
        buffered write; returns the first record's offset.  The drain
        path funnels through here; callers with a batch in hand (log
        replication replay, the resize fold) may use it directly."""
        for p in payloads:
            if not p:
                raise ValueError("empty log records are not allowed")
        with self._lock:
            if self._group is not None:
                if self._native is None and self._py is None:
                    raise OSError(f"log {self.path} is closed")
                off = self._logical_end
                self._staged.extend(payloads)
                self._staged_bytes += sum(len(p) for p in payloads)
                self._logical_end += sum(
                    _HEADER.size + len(p) for p in payloads)
                if self._window_open is None:
                    self._window_open = time.monotonic()
                stats.registry.log_staged_records.inc(len(payloads))
                if (len(self._staged) >= self._group.group_records
                        or self._staged_bytes
                        >= self._group.group_bytes):
                    self._write_staged_locked()
                return off
            return self._append_batch_backend_locked(payloads)

    def _append_batch_backend_locked(self, payloads: List[bytes]) -> int:
        """One backend batch write; must run under self._lock.
        Returns the first record's LOGICAL offset."""
        if self._native:
            lib, h = self._native
            n = len(payloads)
            data = b"".join(payloads)
            lens = (ctypes.c_int64 * n)(*(len(p) for p in payloads))
            off = lib.oplog_append_batch(h, data, lens, n)
            if off < 0:
                raise OSError("batch append failed")
            return off + self._delta
        if self._py is None:
            raise OSError(f"log {self.path} is closed")
        return self._py.append_batch(payloads) + self._delta

    def _write_staged_locked(self) -> None:
        """Write every staged record through the backend (ONE batch
        append — buffered, not yet synced).  Must run under
        self._lock; preserves stage order so assigned offsets hold.

        The staged queue is cleared only AFTER the backend accepted
        the batch: a failed write (disk full, closed handle) must keep
        the records staged — dropping them while ``_logical_end``
        still counts their bytes would shift every later offset off
        the real file, poisoning the op-id index and ``read()``."""
        if not self._staged:
            return
        self._append_batch_backend_locked(self._staged)  # may raise
        n = len(self._staged)
        self._staged = []
        self._staged_bytes = 0
        self._window_open = None
        self._written_end = self._logical_end  # all staged written
        self._written_records += n
        stats.registry.log_staged_records.dec(n)

    # ----------------------------------------------------- durability plane

    def durability_ticket(self) -> int:
        """The logical end offset — everything appended so far is
        durable once the synced watermark reaches it."""
        with self._lock:
            return self._logical_end

    def wait_durable(self, ticket: int, timeout: float = 30.0) -> dict:
        """Block until the synced watermark covers ``ticket``; the
        caller MUST NOT hold its partition lock (that is the point:
        commit-path fsyncs no longer serialize the partition).

        Group commit by caller election: a waiter that finds no drain
        in flight leads — holds the window open (``group_us``) only
        while OTHER committers are waiting, writes the whole staged
        queue as one batch and fsyncs once; everyone whose ticket the
        new watermark covers returns.  Returns ``{led, records}`` for
        the caller's instrumentation."""
        if self._group is None:
            return {"led": False, "records": 0}
        deadline = time.monotonic() + timeout
        info = {"led": False, "records": 0}
        while True:
            lead = False
            with self._lock:
                self._sync_waiters += 1
                try:
                    while self._synced_end < ticket and self._syncing:
                        if self._native is None and self._py is None:
                            raise OSError(
                                f"log {self.path} closed during a "
                                "durability wait")
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise TimeoutError(
                                "durability ticket never covered "
                                "(drain leader wedged?)")
                        self._lock.wait(min(remaining, 0.1))
                    if self._synced_end >= ticket:
                        return info
                    # coverage checked FIRST, deadline second: a
                    # leader whose own slow-but-successful fsync
                    # overran the timeout must ack, not raise for a
                    # txn that is already durable.  The check still
                    # bounds a leader whose drains never cover the
                    # ticket (wedged accounting) — no hot re-election
                    # loop.
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            "durability ticket never covered (drain "
                            "leader wedged?)")
                    self._syncing = True
                    lead = True
                finally:
                    self._sync_waiters -= 1
            if lead:
                try:
                    info["led"] = True
                    info["records"] = self._lead_drain()
                finally:
                    with self._lock:
                        self._syncing = False
                        self._lock.notify_all()

    def _lead_drain(self) -> int:
        """One group-commit drain: optional window hold (company only),
        one batch write, one out-of-lock fsync, watermark advance.
        Returns the number of records the fsync newly covered."""
        s = self._group
        reg = stats.registry
        held = False
        with self._lock:
            if s.group_us > 0:
                opened = self._window_open or time.monotonic()
                deadline = opened + s.group_us / 1e6
                # hold only while there is company: a solo committer
                # pays zero added latency, a burst shares one fsync
                while (self._sync_waiters > 0
                       and len(self._staged) < s.group_records
                       and self._staged_bytes < s.group_bytes):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    held = True
                    self._lock.wait(remaining)
            self._write_staged_locked()
            target = self._written_end
            target_records = self._written_records
            n_cover = target_records - self._synced_records
            io = self._io_begin_locked()
        if io is None:
            raise OSError(f"log {self.path} closed during a drain")
        try:
            with tracer.span("log_group_drain", "oplog",
                             records=n_cover, held=held,
                             path=os.path.basename(self.path)):
                self._backend_sync(io)
        finally:
            with self._lock:
                self._io_done_locked()
                self._synced_end = max(self._synced_end, target)
                # the snapshot captured WITH target, not the live
                # counter: records written during the fsync are not
                # covered by it and must count in the NEXT drain
                self._synced_records = max(self._synced_records,
                                           target_records)
                self.fsyncs += 1
                self.drained_records += n_cover
                if held:
                    self.held_drains += 1
                self._lock.notify_all()
        reg.log_fsyncs.inc()
        reg.log_group_records.inc(n_cover)
        reg.log_group_drains.inc(kind="held" if held else "solo")
        reg.log_group_size.observe(n_cover)
        fsyncs_total = reg.log_fsyncs.value()
        if fsyncs_total:
            reg.log_records_per_fsync.set(
                reg.log_group_records.value() / fsyncs_total)
        return n_cover

    # ------------------------------------------------------------ IO guard

    def _io_begin_locked(self):
        """Capture the backend for out-of-lock IO, pinning it against
        close(); returns None when the log is closed.  Must run under
        self._lock; pair with :meth:`_io_done_locked`."""
        if self._native is None and self._py is None:
            return None
        self._io_refs += 1
        return self._native or self._py

    def _io_done_locked(self) -> None:
        self._io_refs -= 1
        self._lock.notify_all()

    def _backend_sync(self, io) -> None:
        """flush + fsync on a pinned backend, OUTSIDE self._lock (the
        stdio stream serializes concurrent writers internally, and
        fsync covers at least every byte written before it started)."""
        tracer.instant("log_fsync", "oplog",
                       path=os.path.basename(self.path))
        if isinstance(io, tuple):
            io[0].oplog_sync(io[1])
        else:
            io.sync()

    # ----------------------------------------------------------- flush/sync

    def flush(self) -> None:
        with self._lock:
            if self._group is not None:
                self._write_staged_locked()
            if self._native:
                self._native[0].oplog_flush(self._native[1])
            elif self._py is not None:  # no-op on a closed log
                self._py.flush()

    def sync(self) -> None:
        """Flush + fsync — the commit-path durability barrier.

        The fsync runs OUTSIDE the handle lock behind the refcounted
        close guard, so cross-path readers (handoff byte reads,
        migration scans) no longer stall behind disk; same-partition
        appenders already serialize behind the partition lock at every
        call site, exactly as before."""
        with self._lock:
            if self._group is not None:
                self._write_staged_locked()
            target = self._written_end
            target_records = self._written_records
            n_cover = target_records - self._synced_records
            io = self._io_begin_locked()
        if io is None:
            return  # closed log: no-op, like the legacy closed sync
        try:
            self._backend_sync(io)
        finally:
            with self._lock:
                self._io_done_locked()
                self.fsyncs += 1
                if self._group is not None:
                    self._synced_end = max(self._synced_end, target)
                    self._synced_records = max(self._synced_records,
                                               target_records)
                    if n_cover:
                        self.drained_records += n_cover
                    self._lock.notify_all()
        stats.registry.log_fsyncs.inc()
        if self._group is not None and n_cover:
            stats.registry.log_group_records.inc(n_cover)

    def queue_stats(self) -> dict:
        """Staging/durability state for the pipeline snapshot
        (obs/pipeline.py ``log`` section)."""
        with self._lock:
            oldest_us = 0
            if self._window_open is not None:
                oldest_us = int(
                    (time.monotonic() - self._window_open) * 1e6)
            return {
                "group": self._group is not None,
                "staged_records": len(self._staged),
                "staged_bytes": self._staged_bytes,
                "oldest_staged_age_us": oldest_us,
                "written_end": self._written_end,
                "synced_end": self._synced_end,
                "end": self._logical_end,
                "fsyncs": self.fsyncs,
                "drained_records": self.drained_records,
            }

    # --------------------------------------------------------------- reads

    def end_offset(self) -> int:
        with self._lock:
            if self._group is not None:
                if self._native is None and self._py is None:
                    raise OSError(f"log {self.path} is closed")
                return self._logical_end
            return self._backend_end_locked() + self._delta

    def read(self, offset: int) -> Optional[bytes]:
        """Record payload at LOGICAL ``offset``; None past the end or
        below the truncation base (those bytes are reclaimed — callers
        serve that history from the checkpoint seed instead)."""
        with self._lock:
            if self._group is not None:
                self._write_staged_locked()
            if offset < self._base:
                return None
            if self._native is None and self._py is None:
                raise OSError(f"log {self.path} is closed")
            return self._backend_read_locked(offset - self._delta)

    def scan(self, offset: int = 0) -> Iterator[Tuple[int, bytes]]:
        """Iterate (offset, payload) from LOGICAL ``offset`` to the
        end; starts below the truncation base clamp to it (the bytes
        below are gone, and their history lives in the checkpoint)."""
        offset = max(offset, self._base)
        while True:
            payload = self.read(offset)
            if payload is None:
                return
            yield offset, payload
            with self._lock:
                if self._native:
                    nxt = self._native[0].oplog_next(
                        self._native[1], offset - self._delta)
                elif self._py is not None:
                    nxt = self._py.next_offset(offset - self._delta)
                else:
                    # closed mid-scan: a silent partial history would
                    # be served as a successful replay
                    raise OSError(f"log {self.path} closed mid-scan")
                if nxt >= 0:
                    nxt += self._delta
            if nxt < 0:
                return
            offset = nxt

    # -------------------------------------------------------- truncation

    def truncate_below(self, offset: int) -> int:
        """Reclaim log bytes below LOGICAL ``offset`` (ISSUE 10): the
        retained suffix is rewritten behind a truncation-marker record
        and atomically renamed over the log, so every logical offset
        ever handed out keeps resolving to the same record and a crash
        at any point leaves either the old or the new file.  Returns
        the (possibly unchanged) truncation base; no-op at or below
        the current base.  Callers gate the cut by the checkpoint and
        the retention floor (oplog/partition.py) — the log itself only
        guarantees mechanics, not retention policy.

        Two phases (ISSUE 11): :meth:`stage_truncate_below` composes
        the rewritten file OUTSIDE every lock — the retained tail can
        be hundreds of MB (the retention floor holds the cut back for
        lagging peers), and the PR-9 form copied it under both the
        handle lock and the caller's partition lock, stalling every
        commit for the whole copy — and :meth:`commit_truncate`
        re-validates the cut, catches up the (bounded) bytes appended
        during the copy, and atomically renames under the lock.  This
        wrapper runs both back to back for callers that hold no lock
        (tests, resize tooling); the checkpoint plane drives the
        phases itself so the partition lock is held only for the
        cheap commit.

        One-shot means one-shot: if another driver's stage is in
        flight the wrapper WAITS it out and retries rather than
        silently returning the old base — a success-looking return
        with zero bytes reclaimed gave tooling no signal to retry."""
        idle_refusal = False
        while True:
            stage = self.stage_truncate_below(offset)
            if stage is not None:
                return self.commit_truncate(stage)
            with self._lock:
                busy = self._trunc_staging
                base = self._base
            if busy:
                idle_refusal = False
                time.sleep(0.002)
                continue
            if offset <= base:
                return base  # genuine no-op: at/below the live base
            # not busy, yet the stage refused a cut above the base:
            # either a racing stage committed between our attempt and
            # the flag sample (retry once — the next attempt runs
            # unraced) or the cut clamps to the live end (base ==
            # logical end: nothing retained to rewrite; a second idle
            # refusal confirms it)
            if idle_refusal:
                return base
            idle_refusal = True

    def stage_truncate_below(self, offset: int) -> Optional[dict]:
        """Phase 1 of a truncation: compose ``<log>.trunc-tmp`` —
        truncation marker + the retained suffix at/above LOGICAL
        ``offset``, bounded by the file end captured under the lock —
        then flush+fsync it, ALL outside the handle lock (appends,
        reads, and commits proceed during the copy).  Returns the
        stage token :meth:`commit_truncate` redeems, or None when the
        cut is a no-op (at/below the current base) or another stage is
        already in flight (the caller's next checkpoint retries).

        Callers serialize stage->commit pairs (the checkpoint plane's
        ``_ckpt_inflight`` guard); the ``_trunc_staging`` flag is the
        belt to that suspenders — two concurrent stagers would race
        one temp path."""
        with self._lock:
            if self._native is None and self._py is None:
                raise OSError(f"log {self.path} is closed")
            if self._trunc_staging:
                return None
            if self._group is not None:
                self._write_staged_locked()
            if self._native:
                self._native[0].oplog_flush(self._native[1])
            else:
                self._py.flush()
            end_logical = self._backend_end_locked() + self._delta
            offset = min(offset, end_logical)
            if offset <= self._base:
                return None
            self._trunc_staging = True
            self._trunc_seq += 1
            seq = self._trunc_seq
            delta = self._delta
            staged_end_phys = end_logical - delta
        tmp = self.path + ".trunc-tmp"
        try:
            with tracer.span("log_truncate_stage", "oplog",
                             path=os.path.basename(self.path),
                             base=offset,
                             bytes=staged_end_phys - (offset - delta)):
                with open(self.path, "rb") as src, open(tmp, "wb") as f:
                    src.seek(offset - delta)
                    f.write(_trunc_marker(offset))
                    # bounded chunked copy up to the captured end:
                    # concurrent appends land PAST it and are caught
                    # up under the lock at commit; copying an
                    # unbounded growing tail here could chase a busy
                    # writer forever (and risk copying a half-written
                    # buffered record)
                    _copy_range(src, f, staged_end_phys
                                - (offset - delta))
                    f.flush()
                    os.fsync(f.fileno())
            return {"offset": offset, "delta": delta, "seq": seq,
                    "staged_end_phys": staged_end_phys, "tmp": tmp}
        except BaseException:
            # unlink BEFORE the flag drops, under the lock: clearing
            # first would let a new stager open this same path and
            # then lose its temp to our late remove
            with self._lock:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                self._trunc_staging = False
            raise

    def abort_truncate(self, stage: dict) -> None:
        """Discard a staged truncation that will never be committed
        (the checkpoint failed between stage and commit): clear the
        in-flight flag and remove the temp so the next checkpoint can
        stage afresh.  Idempotent — a no-op after a successful commit
        (the rename consumed the temp, the flag is already down).
        Ownership-checked: the token's generation must match the stage
        currently in flight — aborting a consumed token while a NEWER
        stage is composing must not unlink that stage's temp.  The
        unlink runs under the lock, BEFORE the flag drops — the other
        order would let a fresh stage open the shared temp path and
        then lose it to this late remove."""
        with self._lock:
            if not (self._trunc_staging
                    and stage.get("seq") == self._trunc_seq):
                return  # consumed, superseded, or never ours
            try:
                os.remove(stage["tmp"])
            except OSError:
                pass
            self._trunc_staging = False

    def commit_truncate(self, stage: dict) -> int:
        """Phase 2: under the handle lock, re-validate the staged cut,
        append the (bounded — whatever arrived during the copy) byte
        delta to the temp file, fsync it, atomically rename over the
        log, and swap the backend handle.  Returns the new truncation
        base.  The blocking calls below are audited rather than moved:
        the catch-up is bounded by the stage->commit window, and the
        rename must serialize against appenders or a racing append
        would land on the unlinked inode and vanish."""
        tmp = stage["tmp"]
        offset = stage["offset"]
        committed = False
        with self._lock:
            # ownership check OUTSIDE the try: a stale token (aborted,
            # or a newer stage took the slot) must fail loudly WITHOUT
            # the finally below clearing the live stage's flag or
            # unlinking its temp
            if not (self._trunc_staging
                    and stage.get("seq") == self._trunc_seq):
                raise OSError(
                    f"stale truncation stage for {self.path}: token "
                    "was aborted or superseded — re-stage before "
                    "committing")
            try:
                if self._native is None and self._py is None:
                    raise OSError(f"log {self.path} is closed")
                if offset <= self._base:
                    return self._base  # superseded: nothing to do
                if self._group is not None:
                    self._write_staged_locked()
                if self._native:
                    self._native[0].oplog_flush(self._native[1])
                else:
                    self._py.flush()
                old_base = self._base
                # an out-of-lock fsync still holds the handle we are
                # about to close — wait it out (same guard as close())
                while self._io_refs:
                    self._lock.wait()
                cur_end_phys = self._backend_end_locked()
                catchup = cur_end_phys - stage["staged_end_phys"]
                with tracer.span("log_truncate", "oplog",
                                 path=os.path.basename(self.path),
                                 base=offset, catchup_bytes=catchup,
                                 reclaimed=offset - old_base):
                    if catchup > 0:
                        # "r+b", NOT "ab": a vanished temp must raise,
                        # not be silently recreated as a marker-less
                        # catch-up-only file the rename would install
                        # over the whole log
                        with open(self.path, "rb") as src, \
                                open(tmp, "r+b") as f:
                            src.seek(stage["staged_end_phys"])
                            f.seek(0, os.SEEK_END)
                            _copy_range(src, f, catchup)
                            f.flush()
                            # lock-ok: bounded by the stage->commit
                            # window (bytes appended DURING the tail
                            # copy), not by the retained suffix — the
                            # unbounded copy already ran out of lock
                            os.fsync(f.fileno())
                    # lock-ok: the rename must serialize against
                    # appenders — a racing append to the old inode
                    # would be lost; metadata-only, no data copy here
                    os.replace(tmp, self.path)
                    # lock-ok: directory fsync pins the rename — the
                    # watermark bump below marks catch-up bytes
                    # durable, and without this a power cut could
                    # resurrect the old inode whose tail was never
                    # fsynced (an acked commit gone on recovery)
                    _fsync_dir(os.path.dirname(self.path))
                    committed = True
                    self._reopen_backend_locked()
                self._base = offset
                self._delta = offset - TRUNC_MARKER_LEN
                if self._group is not None:
                    # the whole rewritten file was just fsynced:
                    # written and synced watermarks cover its end
                    end = self._backend_end_locked() + self._delta
                    self._logical_end = end
                    self._written_end = end
                    self._synced_end = max(self._synced_end, end)
                stats.registry.log_truncated_bytes.inc(
                    offset - old_base)
                return self._base
            finally:
                self._trunc_staging = False
                self._lock.notify_all()
                if not committed:
                    # superseded/failed commit: the staged file is
                    # stale — never leave it to poison a later stage
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass

    def _reopen_backend_locked(self) -> None:
        """Swap the backend handle onto the (just-renamed) file — the
        old handle points at the unlinked inode.  The rewritten file
        was composed and fsynced by US moments ago, so open-time
        recovery SKIPS re-validating it (resume at the file size): a
        full CRC re-scan of possibly hundreds of retained MB would run
        under both the log and partition locks."""
        size = os.path.getsize(self.path)
        if self._native:
            lib, h = self._native
            lib.oplog_close(h)
            self._native = None
            nh = lib.oplog_open(self.path.encode(), 1)
            if not nh:
                raise OSError(f"cannot reopen log {self.path}")
            self._native = (lib, ctypes.c_void_p(nh))
            if lib.has_recover_from and \
                    lib.oplog_recover_from(self._native[1], size) >= 0:
                return
            lib.oplog_recover(self._native[1])
        elif self._py is not None:
            self._py.close()
            self._py = _PyLog(self.path, recover_hint=size)

    def close(self) -> None:
        with self._lock:
            if self._group is not None and (self._native or self._py):
                self._write_staged_locked()
            # the refcounted close guard: an out-of-lock fsync still
            # holds the handle — freeing it under the syncer is a
            # segfault on the native backend, not an exception
            while self._io_refs:
                self._lock.wait()
            if self._native:
                self._native[0].oplog_close(self._native[1])
                self._native = None
            elif self._py:
                self._py.close()
                self._py = None
            self._lock.notify_all()


class _PyLog:
    """Pure-Python twin of the native backend (same on-disk format)."""

    def __init__(self, path: str, recover_hint: int = 0):
        self.f = open(path, "a+b")
        self.f.seek(0, os.SEEK_END)
        self.end = self.f.tell()
        if recover_hint <= 0 or not self._recover(recover_hint):
            self._recover(0)

    def _recover(self, start: int) -> bool:
        """Validate records from PHYSICAL ``start`` and truncate a
        torn tail (the oplog_recover_from twin).  False when ``start``
        is not a valid record boundary — the caller reruns from 0 (a
        bogus resume point must never truncate good data)."""
        self.f.flush()
        size = os.fstat(self.f.fileno()).st_size
        if start < 0 or start > size:
            return False
        off = start
        validated_one = False
        while off + _HEADER.size <= size:
            self.f.seek(off)
            hdr = self.f.read(_HEADER.size)
            if len(hdr) < _HEADER.size:
                break
            ln, crc = _HEADER.unpack(hdr)
            if ln == 0 or off + _HEADER.size + ln > size:
                break
            payload = self.f.read(ln)
            if len(payload) < ln or zlib.crc32(payload) != crc:
                break
            off += _HEADER.size + ln
            validated_one = True
        if off < size and start > 0 and not validated_one:
            return False
        if off < size:
            self.f.truncate(off)
        self.end = off
        self.f.seek(0, os.SEEK_END)
        return True

    def append(self, payload: bytes) -> int:
        off = self.end
        self.f.seek(0, os.SEEK_END)
        self.f.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
        self.f.write(payload)
        self.end += _HEADER.size + len(payload)
        return off

    def append_batch(self, payloads: List[bytes]) -> int:
        """Twin of the native oplog_append_batch: frame every payload
        into one buffer and write it with a single call."""
        off = self.end
        buf = bytearray()
        for p in payloads:
            buf += _HEADER.pack(len(p), zlib.crc32(p))
            buf += p
        self.f.seek(0, os.SEEK_END)
        self.f.write(bytes(buf))
        self.end += len(buf)
        return off

    def flush(self) -> None:
        self.f.flush()

    def sync(self) -> None:
        self.f.flush()
        os.fsync(self.f.fileno())

    def read(self, offset: int) -> Optional[bytes]:
        self.f.flush()
        if offset + _HEADER.size > self.end:
            return None
        self.f.seek(offset)
        ln, crc = _HEADER.unpack(self.f.read(_HEADER.size))
        if offset + _HEADER.size + ln > self.end:
            return None
        payload = self.f.read(ln)
        if len(payload) < ln or zlib.crc32(payload) != crc:
            return None
        return payload

    def next_offset(self, offset: int) -> int:
        self.f.flush()
        if offset + _HEADER.size > self.end:
            return -1
        self.f.seek(offset)
        ln, _ = _HEADER.unpack(self.f.read(_HEADER.size))
        nxt = offset + _HEADER.size + ln
        self.f.seek(0, os.SEEK_END)
        return nxt if nxt <= self.end else -1

    def close(self) -> None:
        self.f.close()

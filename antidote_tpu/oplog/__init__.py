from antidote_tpu.oplog.log import DurableLog  # noqa: F401
from antidote_tpu.oplog.partition import PartitionLog  # noqa: F401
from antidote_tpu.oplog.records import (  # noqa: F401
    LogRecord,
    OpId,
    TxnAssembler,
)

"""Per-partition durable op log with op-id watermarks and commit-joined
replay.

The reference equivalent is logging_vnode (reference
src/logging_vnode.erl): append assigns per-DC op numbers from counters
recovered at boot (:263-283, 995-1009), commits optionally fsync
(:157-162), snapshot reads scan the log joining updates with their
commit records and filtering by VC window (:522-545, 663-773), and
restart recovers both the op-id counters and the max commit VC
(:595-643).
"""

from __future__ import annotations

import array
import bisect
import os
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.clocks import VC
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer
from antidote_tpu.mat.materializer import Payload, op_in_read_snapshot
from antidote_tpu.oplog.log import DurableLog, GroupSettings
from antidote_tpu.oplog.records import (
    LogRecord,
    OpId,
    TxnAssembler,
    abort_record,
    commit_certified,
    commit_record,
    prepare_record,
    update_record,
)


class PartitionLog:
    """One partition's durable stream of transaction records."""

    def __init__(self, path: str, partition: int, sync_on_commit: bool = False,
                 backend: str = "auto", enabled: bool = True,
                 on_append: Optional[Callable[[LogRecord], None]] = None,
                 group: Optional[GroupSettings] = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.partition = partition
        self.sync_on_commit = sync_on_commit
        #: reference enable_logging flag: when False no durable writes
        #: happen (op ids and the inter-DC stream still work; recovery
        #: and log-replay reads see an empty log)
        self.enabled = enabled
        self.log = DurableLog(path, backend=backend, group=group) \
            if enabled else None
        #: next op number per origin DC (recovered from the log at boot)
        self.op_counters: Dict[Any, int] = {}
        #: keys with at least one logged update — lets readers skip the
        #: full-log scan for keys that have no history at all (the
        #: reference's ETS cache answers this implicitly; a miss there
        #: scans only the per-key log via its key index)
        self.keys_seen: set = set()
        #: key -> flat int64 array of (update_offset, commit_offset)
        #: pairs in commit order — THE per-key log index (the
        #: reference's disk_log is scanned via the materializer's
        #: per-key ETS ops cache; here the index lets a cache-miss
        #: exact read replay ONE key's history instead of the whole
        #: partition log, which grows without bound)
        self.key_commits: Dict[Any, "array.array"] = {}
        #: per-(origin DC, op-id) sparse offset index (ISSUE 9): for
        #: each origin, parallel arrays of record op numbers and file
        #: offsets in append order.  Op numbers are dense per origin at
        #: this partition (local appends) or arrive in stream order
        #: (SubBuf-gated remote groups), so the arrays are sorted and
        #: ``records_in_range`` — the inter-DC gap-repair read path —
        #: becomes O(requested range) preads instead of a full-
        #: partition scan-and-decode.  ~16 B/record of host memory;
        #: an origin whose order ever breaks falls back to the scan
        #: (``_index_irregular``) instead of serving a wrong answer.
        self._op_ns: Dict[Any, "array.array"] = {}
        self._op_offs: Dict[Any, "array.array"] = {}
        #: per-origin COMMITTED-txn index: commit op numbers + each
        #: txn's record offsets (updates in append order, commit last —
        #: exactly the TxnAssembler emission shape), feeding the
        #: gap-repair answer (``committed_txns_in_range``)
        self._commit_ns: Dict[Any, "array.array"] = {}
        self._commit_offs: Dict[Any, List["array.array"]] = {}
        #: origins whose op-number order broke (out-of-order remote
        #: replay): range reads fall back to the full scan for them
        self._index_irregular: set = set()
        #: txid -> [(key, update_offset)] awaiting their commit record
        self._pending_updates: Dict[Any, List[Tuple[Any, int]]] = {}
        #: max committed time seen per DC (recovered; seeds the dependency
        #: clock on restart, reference src/logging_vnode.erl:301-322)
        self.max_commit_vc = VC()
        #: tap for the inter-DC sender (every local append streams out,
        #: reference src/logging_vnode.erl:422)
        self.on_append = on_append
        self._recover()

    # ------------------------------------------------------------- append

    def _next_op_id(self, dc) -> OpId:
        n = self.op_counters.get(dc, 0) + 1
        self.op_counters[dc] = n
        return OpId(dc, n)

    def _append(self, rec: LogRecord, sync: bool) -> int:
        """Write + tap one record; returns its log offset (-1 when
        logging is disabled) and maintains the per-key commit index.

        Under the group-commit plane a requested sync is DEFERRED: the
        record only stages, and the caller waits on a durability
        ticket (:meth:`commit_ticket` / :meth:`wait_durable`) after
        releasing its partition lock — that is where the fsync
        coalesces across committers."""
        off = -1
        if self.enabled:
            off = self.log.append(rec.to_bytes())
            if sync and not self.log.group_active:
                # legacy per-record path: the inline fsync the group
                # plane amortizes away (Config.log_group=False keeps
                # this exact sequencing as the bench baseline)
                tracer.instant("log_sync_inline", "oplog",
                               txid=rec.txid, partition=self.partition)
                self.log.sync()
            self._index(rec, off)
        if self.on_append is not None:
            self.on_append(rec)
        return off

    def _index(self, rec: LogRecord, off: int) -> None:
        kind = rec.kind()
        dc = rec.op_id.dc
        ns = self._op_ns.get(dc)
        if ns is None:
            ns = self._op_ns[dc] = array.array("q")
            self._op_offs[dc] = array.array("q")
        if ns and ns[-1] >= rec.op_id.n:
            self._index_irregular.add(dc)
        elif dc not in self._index_irregular:
            ns.append(rec.op_id.n)
            self._op_offs[dc].append(off)
        if kind == "update":
            self._pending_updates.setdefault(rec.txid, []).append(
                (rec.payload[1], off))
        elif kind == "commit":
            ups = self._pending_updates.pop(rec.txid, ())
            for k, off_u in ups:
                self.key_commits.setdefault(
                    k, array.array("q")).extend((off_u, off))
            if dc not in self._index_irregular:
                cns = self._commit_ns.get(dc)
                if cns is None:
                    cns = self._commit_ns[dc] = array.array("q")
                    self._commit_offs[dc] = []
                if cns and cns[-1] >= rec.op_id.n:
                    self._index_irregular.add(dc)
                else:
                    cns.append(rec.op_id.n)
                    self._commit_offs[dc].append(array.array(
                        "q", [o for _k, o in ups] + [off]))
        elif kind == "abort":
            self._pending_updates.pop(rec.txid, None)

    def append_update(self, dc, txid, key, type_name, effect) -> LogRecord:
        self.keys_seen.add(key)
        rec = update_record(self._next_op_id(dc), txid, key, type_name,
                            effect)
        self._append(rec, sync=False)
        return rec

    def append_prepare(self, dc, txid, prepare_time: int) -> LogRecord:
        rec = prepare_record(self._next_op_id(dc), txid, prepare_time)
        self._append(rec, sync=False)
        return rec

    def append_commit(self, dc, txid, commit_time: int,
                      snapshot_vc: VC, certified: bool = True) -> LogRecord:
        """Commit record; fsyncs when sync_on_commit (reference
        append_commit / ?SYNC_LOG).  Under the group-commit plane the
        fsync is deferred to the caller's durability ticket
        (:meth:`commit_ticket` + :meth:`wait_durable`), so the latency
        observed here is staging only."""
        t0 = time.perf_counter()
        with tracer.span("log_append_commit", "oplog", txid=txid,
                         partition=self.partition):
            rec = commit_record(self._next_op_id(dc), txid, dc,
                                commit_time, snapshot_vc, certified)
            self._append(rec, sync=self.sync_on_commit)
        stats.registry.log_append_latency.observe(
            time.perf_counter() - t0)
        return rec

    def commit_ticket(self) -> Optional[int]:
        """Durability ticket for everything appended so far, or None
        when there is nothing to wait on (logging disabled, sync off,
        or the legacy path — whose fsync already ran inline).  Take it
        under the partition lock right after the commit append; redeem
        with :meth:`wait_durable` AFTER releasing the lock."""
        if not (self.enabled and self.sync_on_commit
                and self.log.group_active):
            return None
        return self.log.durability_ticket()

    def wait_durable(self, ticket: Optional[int], txid=None) -> None:
        """Block until the group-commit plane's synced watermark covers
        ``ticket`` (the commit ack gate).  Must run WITHOUT the
        partition lock — committers coalesce here, one leader drains
        the window, and the per-committer wait feeds the
        ``log_sync_wait`` histogram + sampled txn trees."""
        if ticket is None:
            return
        t0 = time.perf_counter()
        info = self.log.wait_durable(ticket)
        wait_s = time.perf_counter() - t0
        stats.registry.log_sync_wait.observe(wait_s)
        tracer.instant("log_sync_wait", "oplog", txid=txid,
                       partition=self.partition,
                       wait_us=round(wait_s * 1e6, 1), led=info["led"])

    def append_abort(self, dc, txid) -> LogRecord:
        rec = abort_record(self._next_op_id(dc), txid)
        self._append(rec, sync=False)
        recorder.record("oplog", "abort_record", txid=txid,
                        partition=self.partition)
        return rec

    def append_remote_group(self, records: List[LogRecord]
                            ) -> Optional[int]:
        """Store replicated records from another DC without assigning
        local ids (reference append_group handler :448-520) — but advance
        that DC's counter watermark so gap detection stays correct.
        Returns a durability ticket when the group-commit plane defers
        the sync (the remote-apply path redeems it after releasing the
        partition lock, like a local commit); None otherwise."""
        for rec in records:
            self.op_counters[rec.op_id.dc] = max(
                self.op_counters.get(rec.op_id.dc, 0), rec.op_id.n)
            if rec.kind() == "update":
                self.keys_seen.add(rec.payload[1])
            self._append(rec, sync=False)
        if self.sync_on_commit and records and self.enabled:
            if self.log.group_active:
                return self.log.durability_ticket()
            tracer.instant("log_sync_inline", "oplog",
                           partition=self.partition,
                           records=len(records))
            self.log.sync()
        return None

    # --------------------------------------------------------------- read

    def read_bytes(self, offset: int, max_bytes: int) -> Tuple[bytes, int]:
        """Raw byte range of the log file plus the current end offset —
        the cross-node handoff transfer unit: the log is self-framed
        and CRC'd, so the receiver validates it by ordinary recovery
        (the reference streams fold chunks between vnodes the same way,
        src/logging_vnode.erl:781-812).  Returns (b"", end) when
        logging is disabled (nothing to hand off) or offset >= end."""
        if not self.enabled:
            return b"", 0
        self.log.flush()
        end = self.log.end_offset()
        if offset >= end:
            return b"", end
        with open(self.path, "rb") as f:
            f.seek(offset)
            return f.read(min(max_bytes, end - offset)), end

    def records(self, offset: int = 0) -> Iterator[LogRecord]:
        if not self.enabled:
            return
        # push buffered appends down before scanning: the append path is
        # write-buffered (fwrite / buffered file) while scans read the
        # file, so an unflushed tail would be invisible — which would make
        # log replay lose recent ops and gap-repair answers silently omit
        # committed txns (the requester treats the answer as covering the
        # whole range)
        self.log.flush()
        for _off, payload in self.log.scan(offset):
            yield LogRecord.from_bytes(payload)

    def committed_payloads(
        self,
        key: Any = None,
        to_vc: Optional[VC] = None,
        from_vc: Optional[VC] = None,
    ) -> List[Tuple[int, Payload]]:
        """Replay the log, joining updates with their commit records and
        filtering by VC window — the materializer's cache-miss path
        (reference get_ops_from_log/filter_terms_for_key/handle_commit,
        src/logging_vnode.erl:663-773).

        Returns [(op_seq, Payload)] in log order.  ``to_vc``: only ops in
        that snapshot; ``from_vc``: drop ops already covered by it.

        With ``key`` given, the per-key commit index replays ONLY that
        key's records (O(key history) file reads instead of an
        assembling scan of the whole partition log — the cache-miss
        exact-state read runs this on every recently-written set/map
        key, and the full scan was the measured dominant cost of the
        logged txn path)."""
        if key is not None and self.enabled:
            self.log.flush()
            out = []
            seq = 0
            idx = self.key_commits.get(key)
            for i in range(0, len(idx) if idx is not None else 0, 2):
                upd = LogRecord.from_bytes(self.log.read(idx[i]))
                commit = LogRecord.from_bytes(self.log.read(idx[i + 1]))
                _, k, type_name, effect = upd.payload
                (dc, ct), svc = commit.payload[1], commit.payload[2]
                p = Payload(key=k, type_name=type_name, effect=effect,
                            commit_dc=dc, commit_time=ct,
                            snapshot_vc=svc, txid=upd.txid,
                            certified=commit_certified(commit.payload))
                if to_vc is not None and \
                        not op_in_read_snapshot(to_vc, p):
                    continue
                if from_vc is not None and p.commit_vc().le(from_vc):
                    continue
                seq += 1
                out.append((seq, p))
            return out
        asm = TxnAssembler()
        out: List[Tuple[int, Payload]] = []
        seq = 0
        for rec in self.records():
            done = asm.process(rec)
            if done is None:
                continue
            commit = done[-1]
            (dc, ct), svc = commit.payload[1], commit.payload[2]
            certified = commit_certified(commit.payload)
            for upd in done[:-1]:
                _, k, type_name, effect = upd.payload
                if key is not None and k != key:
                    continue
                p = Payload(key=k, type_name=type_name, effect=effect,
                            commit_dc=dc, commit_time=ct, snapshot_vc=svc,
                            txid=upd.txid, certified=certified)
                if to_vc is not None and not op_in_read_snapshot(to_vc, p):
                    continue
                if from_vc is not None and p.commit_vc().le(from_vc):
                    continue
                seq += 1
                out.append((seq, p))
        return out

    def records_in_range(self, dc, first: int, last: int) -> List[LogRecord]:
        """Records from origin ``dc`` with first <= op_id.n <= last — the
        log-reader side of inter-DC gap repair (reference
        inter_dc_query_response:get_entries, src/inter_dc_query_response.erl:97-126).

        Served from the per-origin op-id offset index: O(requested
        range) preads instead of a full-partition scan-and-decode (the
        measured repair cost grew with UNRELATED log volume).  Origins
        whose op order ever broke fall back to the scan."""
        if not self.enabled:
            return []
        if dc in self._index_irregular:
            return self._records_in_range_scan(dc, first, last)
        ns = self._op_ns.get(dc)
        if ns is None:
            return []
        self.log.flush()
        offs = self._op_offs[dc]
        out = []
        for i in range(bisect.bisect_left(ns, first), len(ns)):
            if ns[i] > last:
                break
            out.append(LogRecord.from_bytes(self.log.read(offs[i])))
        return out

    def _records_in_range_scan(self, dc, first: int, last: int
                               ) -> List[LogRecord]:
        """The legacy full-scan form of :meth:`records_in_range` —
        the irregular-origin fallback AND the oracle the gap-repair
        differential tests compare the index against."""
        return [r for r in self.records()
                if r.op_id.dc == dc and first <= r.op_id.n <= last]

    def committed_txns_in_range(self, dc, first: int, last: int,
                                scan: bool = False
                                ) -> List[Tuple[int, List[LogRecord]]]:
        """Committed transactions of origin ``dc`` whose commit op
        number lies in [first, last], each as (prev_commit_opid,
        [update records..., commit record]) — the inter-DC gap-repair
        answer unit (interdc/query.py answer_log_read).  ``prev`` is
        the origin's previous commit op number in log order (0 at the
        stream head), reproducing the live sender's watermark chain.

        Index path: one bisect + O(records in the requested txns)
        preads via the per-origin commit index.  ``scan=True`` forces
        the legacy full-scan (the differential tests' oracle); origins
        with broken op order fall back to it automatically."""
        if not self.enabled:
            return []
        if scan or dc in self._index_irregular:
            return self._committed_txns_scan(dc, first, last)
        cns = self._commit_ns.get(dc)
        if cns is None:
            return []
        self.log.flush()
        offlists = self._commit_offs[dc]
        lo = bisect.bisect_left(cns, first)
        prev = cns[lo - 1] if lo > 0 else 0
        out = []
        for i in range(lo, len(cns)):
            if cns[i] > last:
                break
            recs = [LogRecord.from_bytes(self.log.read(off))
                    for off in offlists[i]]
            # a mixed-origin txn's foreign updates are excluded by the
            # scan path's origin filter — match it exactly
            recs = [r for r in recs if r.op_id.dc == dc]
            out.append((prev, recs))
            prev = cns[i]
        return out

    def _committed_txns_scan(self, dc, first: int, last: int
                             ) -> List[Tuple[int, List[LogRecord]]]:
        """Full-scan oracle for :meth:`committed_txns_in_range`: replay
        the whole partition log, reassemble this origin's transactions,
        and emit the in-range ones with the prev-opid chain."""
        asm = TxnAssembler()
        out: List[Tuple[int, List[LogRecord]]] = []
        prev = 0
        for rec in self.records():
            if rec.op_id.dc != dc:
                continue
            done = asm.process(rec)
            if done is None:
                continue
            commit_opid = done[-1].op_id.n
            if first <= commit_opid <= last:
                out.append((prev, done))
            prev = commit_opid
        return out

    def log_stats(self) -> dict:
        """This partition log's staging/durability state for the
        pipeline snapshot (obs/pipeline.py ``log`` section)."""
        if not self.enabled:
            return {"enabled": False}
        return {"enabled": True, **self.log.queue_stats()}

    # ----------------------------------------------------------- recovery

    def _recover(self) -> None:
        """Rebuild op-id counters, the per-key commit index, and the
        max commit VC from the log (reference get_last_op_from_log,
        src/logging_vnode.erl:595-643)."""
        if not self.enabled:
            return
        self.log.flush()
        for off, payload_bytes in self.log.scan(0):
            rec = LogRecord.from_bytes(payload_bytes)
            self._index(rec, off)
            cur = self.op_counters.get(rec.op_id.dc, 0)
            if rec.op_id.n > cur:
                self.op_counters[rec.op_id.dc] = rec.op_id.n
            if rec.kind() == "update":
                self.keys_seen.add(rec.payload[1])
            if rec.kind() == "commit":
                (dc, ct) = rec.payload[1]
                if ct > self.max_commit_vc.get_dc(dc):
                    self.max_commit_vc = self.max_commit_vc.set_dc(dc, ct)
                # join the commit's full snapshot VC: an applied commit's
                # dependencies were covered when it applied, so the
                # recovered dependency clock may include them — without
                # this, a restarted DC whose local commits depended on a
                # now-unreachable peer cannot cover its OWN history in
                # the stable snapshot (the reference recovers its stable
                # meta for the same reason, recover_meta_data_on_start)
                self.max_commit_vc = self.max_commit_vc.join(
                    rec.payload[2])

    def close(self) -> None:
        if self.enabled:
            self.log.flush()
            self.log.close()

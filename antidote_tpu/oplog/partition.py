"""Per-partition durable op log with op-id watermarks and commit-joined
replay.

The reference equivalent is logging_vnode (reference
src/logging_vnode.erl): append assigns per-DC op numbers from counters
recovered at boot (:263-283, 995-1009), commits optionally fsync
(:157-162), snapshot reads scan the log joining updates with their
commit records and filtering by VC window (:522-545, 663-773), and
restart recovers both the op-id counters and the max commit VC
(:595-643).
"""

from __future__ import annotations

import array
import bisect
import os
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.clocks import VC
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer
from antidote_tpu.mat.materializer import Payload, op_in_read_snapshot
from antidote_tpu.oplog.checkpoint import CheckpointStore, empty_doc
from antidote_tpu.oplog.log import DurableLog, GroupSettings
from antidote_tpu.oplog.records import (
    LogRecord,
    OpId,
    TxnAssembler,
    abort_record,
    commit_certified,
    commit_record,
    prepare_record,
    update_record,
)


class BelowRetentionFloor(Exception):
    """A log-range read asked below the truncation/retention floor:
    the records are reclaimed and the history lives in the checkpoint.
    The inter-DC answer path turns this into the explicit BELOW_FLOOR
    wire answer, which makes the requesting SubBuf escalate to a
    checkpoint-state bootstrap instead of wedging in repair retries
    (interdc/query.py, interdc/sub_buf.py)."""

    def __init__(self, floor: int):
        super().__init__(f"requested range reaches below the log "
                         f"retention floor (opid {floor})")
        self.floor = floor


class PartitionLog:
    """One partition's durable stream of transaction records."""

    def __init__(self, path: str, partition: int, sync_on_commit: bool = False,
                 backend: str = "auto", enabled: bool = True,
                 on_append: Optional[Callable[[LogRecord], None]] = None,
                 group: Optional[GroupSettings] = None,
                 checkpoint: Optional[CheckpointStore] = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.partition = partition
        self.sync_on_commit = sync_on_commit
        #: reference enable_logging flag: when False no durable writes
        #: happen (op ids and the inter-DC stream still work; recovery
        #: and log-replay reads see an empty log)
        self.enabled = enabled
        # preload the checkpoint BEFORE opening the log: its cut is
        # the recovery hint that lets open-time torn-tail validation
        # skip the (possibly huge, possibly truncated) prefix —
        # O(suffix) instead of O(file) (ISSUE 10)
        self._boot_doc: Optional[dict] = None
        hint = 0
        if enabled and checkpoint is not None:
            tracer.instant("ckpt_recover_load", "oplog",
                           partition=partition)
            self._boot_doc = checkpoint.load_doc()
            if self._boot_doc is not None:
                hint = min(self._boot_doc.get("cut_offset", 0),
                           self._boot_doc.get("pending_floor", 1 << 62))
        self.log = DurableLog(path, backend=backend, group=group,
                              recover_hint=hint) \
            if enabled else None
        #: next op number per origin DC (recovered from the log at boot)
        self.op_counters: Dict[Any, int] = {}
        #: keys with at least one logged update — lets readers skip the
        #: full-log scan for keys that have no history at all (the
        #: reference's ETS cache answers this implicitly; a miss there
        #: scans only the per-key log via its key index)
        self.keys_seen: set = set()
        #: key -> flat int64 array of (update_offset, commit_offset)
        #: pairs in commit order — THE per-key log index (the
        #: reference's disk_log is scanned via the materializer's
        #: per-key ETS ops cache; here the index lets a cache-miss
        #: exact read replay ONE key's history instead of the whole
        #: partition log, which grows without bound)
        self.key_commits: Dict[Any, "array.array"] = {}
        #: per-(origin DC, op-id) sparse offset index (ISSUE 9): for
        #: each origin, parallel arrays of record op numbers and file
        #: offsets in append order.  Op numbers are dense per origin at
        #: this partition (local appends) or arrive in stream order
        #: (SubBuf-gated remote groups), so the arrays are sorted and
        #: ``records_in_range`` — the inter-DC gap-repair read path —
        #: becomes O(requested range) preads instead of a full-
        #: partition scan-and-decode.  ~16 B/record of host memory;
        #: an origin whose order ever breaks falls back to the scan
        #: (``_index_irregular``) instead of serving a wrong answer.
        self._op_ns: Dict[Any, "array.array"] = {}
        self._op_offs: Dict[Any, "array.array"] = {}
        #: per-origin COMMITTED-txn index: commit op numbers + each
        #: txn's record offsets (updates in append order, commit last —
        #: exactly the TxnAssembler emission shape), feeding the
        #: gap-repair answer (``committed_txns_in_range``)
        self._commit_ns: Dict[Any, "array.array"] = {}
        self._commit_offs: Dict[Any, List["array.array"]] = {}
        #: origins whose op-number order broke (out-of-order remote
        #: replay): range reads fall back to the full scan for them
        self._index_irregular: set = set()
        #: txid -> [(key, update_offset)] awaiting their commit record
        self._pending_updates: Dict[Any, List[Tuple[Any, int]]] = {}
        #: max committed time seen per DC (recovered; seeds the dependency
        #: clock on restart, reference src/logging_vnode.erl:301-322)
        self.max_commit_vc = VC()
        #: tap for the inter-DC sender (every local append streams out,
        #: reference src/logging_vnode.erl:422)
        self.on_append = on_append
        # ---- checkpoint plane (ISSUE 10); all inert when ckpt is None
        #: atomic checkpoint file store (None = Config.ckpt off: every
        #: path below keeps the pre-checkpoint behavior bit-for-bit)
        self.ckpt = checkpoint if enabled else None
        #: the last loaded/written checkpoint document
        self.ckpt_doc: Optional[dict] = None
        #: key -> (type_name, state, frontier VC): the checkpoint's
        #: materialized seeds — what eviction migration and read-below-
        #: base replay start from instead of offset 0
        self.ckpt_seeds: Dict[Any, Tuple[str, Any, VC]] = {}
        #: per-origin HARD commit-opid floor: at/below it the record
        #: bytes are truncated — no path (index or scan) can answer,
        #: and range reads raise BelowRetentionFloor.  Persisted across
        #: restarts in the checkpoint (``repair_floors``), so the
        #: physically retained window below the cut keeps serving
        #: ordinary gap repair after a reboot (the ckpt_retain_ops
        #: margin survives restarts).
        self.commit_floor: Dict[Any, int] = {}
        #: per-origin INDEX floor: at/below it the in-memory commit
        #: index is incomplete (it only covers the recovery suffix) —
        #: requests there fall back to the full scan, which is exact
        #: while the bytes remain.  Also the prev-opid chain seed for
        #: the first indexed txn.
        self._commit_index_floor: Dict[Any, int] = {}
        #: hard / index floors for the RAW op-id index
        #: (records_in_range), same split
        self._op_floor: Dict[Any, int] = {}
        self._op_index_floor: Dict[Any, int] = {}
        #: logical offset recovery's suffix scan started from (0 =
        #: full scan; >0 = checkpoint-seeded recovery engaged)
        self.suffix_start = 0
        #: True when this log was produced by a checkpoint-SEEDED ring
        #: resize (ISSUE 19): per-origin op numbers restarted from the
        #: contributing checkpoints' counters instead of the dense
        #: renumbering a full fold produces, so two DCs resizing the
        #: same history independently may DISAGREE on stream numbering.
        #: The inter-DC layer must re-handshake such partitions through
        #: a checkpoint bootstrap rather than trust local counters as
        #: subscription watermarks (interdc/dc.py observe_dc).
        #: Persisted in the checkpoint document (capture_cut) so the
        #: flag survives restarts until a fresh federation handshake
        #: has re-based every stream.
        self.renumbered = False
        #: >0 while a live resize fold scans the suffix above the
        #: current checkpoint cut: adopting a NEWER checkpoint must
        #: not truncate the bytes the fold's cursor still needs
        #: (hold_truncation / release_truncation; adopt_checkpoint
        #: aborts the staged truncation instead of committing it)
        self._trunc_hold = 0
        #: pending update records captured by the checkpoint cut, in
        #: offset order — the TxnAssembler prefeed for suffix replay
        self._suffix_prefeed: List[LogRecord] = []
        #: retention floor source wired by the inter-DC layer: the min
        #: over peers of this partition's OWN-origin ship watermark, as
        #: a bare opid (None = no peers / standalone node: truncation
        #: may reach the cut; a later-joining peer bootstraps from the
        #: checkpoint)
        self.retention_opid_source: Optional[Callable[[], Optional[int]]] \
            = None
        #: this partition's own origin-DC id (set by the owning
        #: PartitionManager) — the stream the retention floor protects
        self.own_dc: Any = None
        #: fired after a truncation prunes the indexes (ISSUE 12): the
        #: node fabric clears its published-answer table here —
        #: reclaimed bytes may back published gap-repair range answers
        #: and handoff byte-reads, and truncation is the ONE event
        #: that rewrites bytes under them (wired by cluster/node.py's
        #: _refresh_fabric_plane)
        self.on_truncate: Optional[Callable[[], None]] = None
        self._recover()

    # ------------------------------------------------------------- append

    def _next_op_id(self, dc) -> OpId:
        n = self.op_counters.get(dc, 0) + 1
        self.op_counters[dc] = n
        return OpId(dc, n)

    def _append(self, rec: LogRecord, sync: bool) -> int:
        """Write + tap one record; returns its log offset (-1 when
        logging is disabled) and maintains the per-key commit index.

        Under the group-commit plane a requested sync is DEFERRED: the
        record only stages, and the caller waits on a durability
        ticket (:meth:`commit_ticket` / :meth:`wait_durable`) after
        releasing its partition lock — that is where the fsync
        coalesces across committers."""
        off = -1
        if self.enabled:
            off = self.log.append(rec.to_bytes())
            if sync and not self.log.group_active:
                # legacy per-record path: the inline fsync the group
                # plane amortizes away (Config.log_group=False keeps
                # this exact sequencing as the bench baseline)
                tracer.instant("log_sync_inline", "oplog",
                               txid=rec.txid, partition=self.partition)
                # lock-ok: legacy per-record path (Config.log_group=
                # False) — the inline fsync under the partition lock
                # IS the bench baseline being preserved; the group
                # plane defers durability to out-of-lock tickets
                self.log.sync()
            self._index(rec, off)
        if self.on_append is not None:
            self.on_append(rec)
        return off

    def _index(self, rec: LogRecord, off: int) -> None:
        kind = rec.kind()
        dc = rec.op_id.dc
        ns = self._op_ns.get(dc)
        if ns is None:
            ns = self._op_ns[dc] = array.array("q")
            self._op_offs[dc] = array.array("q")
        if ns and ns[-1] >= rec.op_id.n:
            self._index_irregular.add(dc)
        elif dc not in self._index_irregular:
            ns.append(rec.op_id.n)
            self._op_offs[dc].append(off)
        if kind == "update":
            self._pending_updates.setdefault(rec.txid, []).append(
                (rec.payload[1], off))
        elif kind == "commit":
            ups = self._pending_updates.pop(rec.txid, ())
            for k, off_u in ups:
                self.key_commits.setdefault(
                    k, array.array("q")).extend((off_u, off))
            if dc not in self._index_irregular:
                cns = self._commit_ns.get(dc)
                if cns is None:
                    cns = self._commit_ns[dc] = array.array("q")
                    self._commit_offs[dc] = []
                if cns and cns[-1] >= rec.op_id.n:
                    self._index_irregular.add(dc)
                else:
                    cns.append(rec.op_id.n)
                    self._commit_offs[dc].append(array.array(
                        "q", [o for _k, o in ups] + [off]))
        elif kind == "abort":
            self._pending_updates.pop(rec.txid, None)

    def append_update(self, dc, txid, key, type_name, effect) -> LogRecord:
        self.keys_seen.add(key)
        rec = update_record(self._next_op_id(dc), txid, key, type_name,
                            effect)
        self._append(rec, sync=False)
        return rec

    def append_prepare(self, dc, txid, prepare_time: int) -> LogRecord:
        rec = prepare_record(self._next_op_id(dc), txid, prepare_time)
        self._append(rec, sync=False)
        return rec

    def append_commit(self, dc, txid, commit_time: int,
                      snapshot_vc: VC, certified: bool = True) -> LogRecord:
        """Commit record; fsyncs when sync_on_commit (reference
        append_commit / ?SYNC_LOG).  Under the group-commit plane the
        fsync is deferred to the caller's durability ticket
        (:meth:`commit_ticket` + :meth:`wait_durable`), so the latency
        observed here is staging only."""
        t0 = time.perf_counter()
        with tracer.span("log_append_commit", "oplog", txid=txid,
                         partition=self.partition):
            rec = commit_record(self._next_op_id(dc), txid, dc,
                                commit_time, snapshot_vc, certified)
            self._append(rec, sync=self.sync_on_commit)
        stats.registry.log_append_latency.observe(
            time.perf_counter() - t0)
        return rec

    def commit_ticket(self) -> Optional[int]:
        """Durability ticket for everything appended so far, or None
        when there is nothing to wait on (logging disabled, sync off,
        or the legacy path — whose fsync already ran inline).  Take it
        under the partition lock right after the commit append; redeem
        with :meth:`wait_durable` AFTER releasing the lock."""
        if not (self.enabled and self.sync_on_commit
                and self.log.group_active):
            return None
        return self.log.durability_ticket()

    def wait_durable(self, ticket: Optional[int], txid=None) -> None:
        """Block until the group-commit plane's synced watermark covers
        ``ticket`` (the commit ack gate).  Must run WITHOUT the
        partition lock — committers coalesce here, one leader drains
        the window, and the per-committer wait feeds the
        ``log_sync_wait`` histogram + sampled txn trees."""
        if ticket is None:
            return
        t0 = time.perf_counter()
        info = self.log.wait_durable(ticket)
        wait_s = time.perf_counter() - t0
        stats.registry.log_sync_wait.observe(wait_s)
        tracer.instant("log_sync_wait", "oplog", txid=txid,
                       partition=self.partition,
                       wait_us=round(wait_s * 1e6, 1), led=info["led"])

    def append_abort(self, dc, txid) -> LogRecord:
        rec = abort_record(self._next_op_id(dc), txid)
        self._append(rec, sync=False)
        recorder.record("oplog", "abort_record", txid=txid,
                        partition=self.partition)
        return rec

    def append_remote_group(self, records: List[LogRecord]
                            ) -> Optional[int]:
        """Store replicated records from another DC without assigning
        local ids (reference append_group handler :448-520) — but advance
        that DC's counter watermark so gap detection stays correct.
        Returns a durability ticket when the group-commit plane defers
        the sync (the remote-apply path redeems it after releasing the
        partition lock, like a local commit); None otherwise."""
        for rec in records:
            self.op_counters[rec.op_id.dc] = max(
                self.op_counters.get(rec.op_id.dc, 0), rec.op_id.n)
            if rec.kind() == "update":
                self.keys_seen.add(rec.payload[1])
            self._append(rec, sync=False)
        if self.sync_on_commit and records and self.enabled:
            if self.log.group_active:
                return self.log.durability_ticket()
            tracer.instant("log_sync_inline", "oplog",
                           partition=self.partition,
                           records=len(records))
            # lock-ok: legacy per-record path (Config.log_group=False)
            # — the remote-apply inline fsync matches the local
            # commit path's baseline sequencing exactly
            self.log.sync()
        return None

    # --------------------------------------------------------------- read

    def read_bytes(self, offset: int, max_bytes: int) -> Tuple[bytes, int]:
        """Raw byte range of the log FILE plus its current size — the
        cross-node handoff transfer unit: the log is self-framed and
        CRC'd, so the receiver validates it by ordinary recovery (the
        reference streams fold chunks between vnodes the same way,
        src/logging_vnode.erl:781-812).  Offsets here are PHYSICAL
        file positions (the handoff cursor walks the file as bytes):
        on a truncated log the stream starts with the truncation
        marker, so the receiver's recovery parses the same base and
        every logical offset stays stable across the move.  Returns
        (b"", size) when logging is disabled (nothing to hand off) or
        offset >= size."""
        if not self.enabled:
            return b"", 0
        self.log.flush()
        end = os.path.getsize(self.path)
        if offset >= end:
            return b"", end
        with open(self.path, "rb") as f:
            f.seek(offset)
            return f.read(min(max_bytes, end - offset)), end

    def records(self, offset: int = 0) -> Iterator[LogRecord]:
        if not self.enabled:
            return
        # push buffered appends down before scanning: the append path is
        # write-buffered (fwrite / buffered file) while scans read the
        # file, so an unflushed tail would be invisible — which would make
        # log replay lose recent ops and gap-repair answers silently omit
        # committed txns (the requester treats the answer as covering the
        # whole range)
        self.log.flush()
        for _off, payload in self.log.scan(offset):
            yield LogRecord.from_bytes(payload)

    def committed_payloads(
        self,
        key: Any = None,
        to_vc: Optional[VC] = None,
        from_vc: Optional[VC] = None,
        scan: bool = False,
    ) -> List[Tuple[int, Payload]]:
        """Replay the log, joining updates with their commit records and
        filtering by VC window — the materializer's cache-miss path
        (reference get_ops_from_log/filter_terms_for_key/handle_commit,
        src/logging_vnode.erl:663-773).

        Returns [(op_seq, Payload)] in log order.  ``to_vc``: only ops in
        that snapshot; ``from_vc``: drop ops already covered by it.

        With ``key`` given, the per-key commit index replays ONLY that
        key's records (O(key history) file reads instead of an
        assembling scan of the whole partition log — the cache-miss
        exact-state read runs this on every recently-written set/map
        key, and the full scan was the measured dominant cost of the
        logged txn path).  ``scan=True`` forces the assembling
        whole-log scan even for a single key: after a checkpoint-
        seeded recovery the per-key index only covers the suffix, and
        a read the seed cannot base (below/concurrent with its
        frontier) needs the key's FULL retained history — exact while
        the below-cut bytes remain on disk (ISSUE 10)."""
        if key is not None and self.enabled and not scan:
            self.log.flush()
            out = []
            seq = 0
            idx = self.key_commits.get(key)
            for i in range(0, len(idx) if idx is not None else 0, 2):
                upd = LogRecord.from_bytes(self.log.read(idx[i]))
                commit = LogRecord.from_bytes(self.log.read(idx[i + 1]))
                _, k, type_name, effect = upd.payload
                (dc, ct), svc = commit.payload[1], commit.payload[2]
                p = Payload(key=k, type_name=type_name, effect=effect,
                            commit_dc=dc, commit_time=ct,
                            snapshot_vc=svc, txid=upd.txid,
                            certified=commit_certified(commit.payload))
                if to_vc is not None and \
                        not op_in_read_snapshot(to_vc, p):
                    continue
                if from_vc is not None and p.commit_vc().le(from_vc):
                    continue
                seq += 1
                out.append((seq, p))
            return out
        asm = TxnAssembler()
        out: List[Tuple[int, Payload]] = []
        seq = 0
        for rec in self.records():
            done = asm.process(rec)
            if done is None:
                continue
            commit = done[-1]
            (dc, ct), svc = commit.payload[1], commit.payload[2]
            certified = commit_certified(commit.payload)
            for upd in done[:-1]:
                _, k, type_name, effect = upd.payload
                if key is not None and k != key:
                    continue
                p = Payload(key=k, type_name=type_name, effect=effect,
                            commit_dc=dc, commit_time=ct, snapshot_vc=svc,
                            txid=upd.txid, certified=certified)
                if to_vc is not None and not op_in_read_snapshot(to_vc, p):
                    continue
                if from_vc is not None and p.commit_vc().le(from_vc):
                    continue
                seq += 1
                out.append((seq, p))
        return out

    def records_in_range(self, dc, first: int, last: int) -> List[LogRecord]:
        """Records from origin ``dc`` with first <= op_id.n <= last — the
        log-reader side of inter-DC gap repair (reference
        inter_dc_query_response:get_entries, src/inter_dc_query_response.erl:97-126).

        Served from the per-origin op-id offset index: O(requested
        range) preads instead of a full-partition scan-and-decode (the
        measured repair cost grew with UNRELATED log volume).  Origins
        whose op order ever broke fall back to the scan.

        Raises :class:`BelowRetentionFloor` when the range reaches
        below a truncated prefix (ISSUE 10) — there are no bytes left
        to answer from, and the caller must escalate to the
        checkpoint-bootstrap path instead of receiving a silently
        partial answer."""
        if not self.enabled:
            return []
        self._check_floor(dc, first, self._op_floor)
        if dc in self._index_irregular \
                or first <= self._op_index_floor.get(dc, 0):
            return self._records_in_range_scan(dc, first, last)
        ns = self._op_ns.get(dc)
        if ns is None:
            return []
        self.log.flush()
        offs = self._op_offs[dc]
        out = []
        for i in range(bisect.bisect_left(ns, first), len(ns)):
            if ns[i] > last:
                break
            out.append(LogRecord.from_bytes(self.log.read(offs[i])))
        return out

    def _check_floor(self, dc, first: int, floors: Dict[Any, int]
                     ) -> None:
        """Raise :class:`BelowRetentionFloor` when ``first`` reaches
        below origin ``dc``'s floor AND the log prefix is physically
        truncated — the scan fallback would silently under-serve.  On
        an un-truncated log the caller falls back to the scan (all
        bytes still present), so a below-floor request stays exact."""
        floor = floors.get(dc, 0)
        # renumbered (checkpoint-seeded resize, ISSUE 19): the history
        # below the floor never existed in THIS log's numbering — the
        # file is whole (truncated_base == 0) yet the scan fallback
        # would silently under-serve, so below-floor requests must
        # escalate to the checkpoint bootstrap exactly as on a
        # truncated log
        if first <= floor and (self.log.truncated_base > 0
                               or self.renumbered):
            raise BelowRetentionFloor(floor)

    def _records_in_range_scan(self, dc, first: int, last: int
                               ) -> List[LogRecord]:
        """The legacy full-scan form of :meth:`records_in_range` —
        the irregular-origin fallback AND the oracle the gap-repair
        differential tests compare the index against."""
        return [r for r in self.records()
                if r.op_id.dc == dc and first <= r.op_id.n <= last]

    def committed_txns_in_range(self, dc, first: int, last: int,
                                scan: bool = False
                                ) -> List[Tuple[int, List[LogRecord]]]:
        """Committed transactions of origin ``dc`` whose commit op
        number lies in [first, last], each as (prev_commit_opid,
        [update records..., commit record]) — the inter-DC gap-repair
        answer unit (interdc/query.py answer_log_read).  ``prev`` is
        the origin's previous commit op number in log order (0 at the
        stream head), reproducing the live sender's watermark chain.

        Index path: one bisect + O(records in the requested txns)
        preads via the per-origin commit index.  ``scan=True`` forces
        the legacy full-scan (the differential tests' oracle); origins
        with broken op order fall back to it automatically.  A range
        reaching below a TRUNCATED prefix raises
        :class:`BelowRetentionFloor` (the bytes are reclaimed); below
        an un-truncated checkpoint cut the index is partial, so the
        call transparently falls back to the full scan instead."""
        if not self.enabled:
            return []
        self._check_floor(dc, first, self.commit_floor)
        if scan or dc in self._index_irregular \
                or first <= self._commit_index_floor.get(dc, 0):
            return self._committed_txns_scan(dc, first, last)
        cns = self._commit_ns.get(dc)
        if cns is None:
            return []
        self.log.flush()
        offlists = self._commit_offs[dc]
        lo = bisect.bisect_left(cns, first)
        prev = cns[lo - 1] if lo > 0 \
            else self._commit_index_floor.get(dc, 0)
        out = []
        for i in range(lo, len(cns)):
            if cns[i] > last:
                break
            recs = [LogRecord.from_bytes(self.log.read(off))
                    for off in offlists[i]]
            # a mixed-origin txn's foreign updates are excluded by the
            # scan path's origin filter — match it exactly
            recs = [r for r in recs if r.op_id.dc == dc]
            out.append((prev, recs))
            prev = cns[i]
        return out

    def _committed_txns_scan(self, dc, first: int, last: int
                             ) -> List[Tuple[int, List[LogRecord]]]:
        """Full-scan oracle for :meth:`committed_txns_in_range`: replay
        the whole (retained) partition log, reassemble this origin's
        transactions, and emit the in-range ones with the prev-opid
        chain — seeded from the hard floor: on a truncated log the
        first retained commit's predecessor is the last reclaimed one,
        not 0."""
        asm = TxnAssembler()
        out: List[Tuple[int, List[LogRecord]]] = []
        prev = self.commit_floor.get(dc, 0)
        for rec in self.records():
            if rec.op_id.dc != dc:
                continue
            done = asm.process(rec)
            if done is None:
                continue
            commit_opid = done[-1].op_id.n
            if first <= commit_opid <= last:
                out.append((prev, done))
            prev = commit_opid
        return out

    def log_stats(self) -> dict:
        """This partition log's staging/durability/retention state for
        the pipeline snapshot (obs/pipeline.py ``log`` section); also
        refreshes the LOG_*/CKPT_* on-disk-growth gauges (ISSUE 10 —
        before them nothing reported on-disk log growth at all)."""
        if not self.enabled:
            return {"enabled": False}
        out = {"enabled": True, **self.log.queue_stats()}
        # queue_stats()["end"] is the group plane's staged watermark —
        # frozen at its boot value when Config.log_group=False.
        # end_offset() is right on both paths (backend end + delta in
        # non-group mode), so the growth gauges never freeze.
        try:
            out["end"] = self.log.end_offset()
        except OSError:
            pass  # closing: keep the queue-stats snapshot value
        base = self.log.truncated_base
        retained = max(out["end"] - base, 0)
        try:
            file_bytes = os.path.getsize(self.path)
        except OSError:
            file_bytes = 0
        out["truncated_bytes"] = base
        out["retained_bytes"] = retained
        out["file_bytes"] = file_bytes
        reg = stats.registry
        lbl = str(self.partition)
        reg.log_retained_bytes.set(retained, partition=lbl)
        reg.log_file_bytes.set(file_bytes, partition=lbl)
        ck: dict = {"present": self.ckpt_doc is not None}
        if self.ckpt_doc is not None:
            age_s = max(0.0, time.time() - self.ckpt_doc["wall_us"] / 1e6)
            ck.update(age_s=round(age_s, 3),
                      keys=len(self.ckpt_doc["keys"]),
                      cut_offset=self.ckpt_doc["cut_offset"])
            reg.ckpt_age.set(age_s, partition=lbl)
        out["ckpt"] = ck
        return out

    # --------------------------------------------------------- checkpoint

    def capture_cut(self) -> dict:
        """The log-side half of a checkpoint document, captured at the
        CURRENT logical end — op-id counters, commit watermarks, max
        commit VC, and the cut-crossing pending update records (with
        their bytes, so recovery never needs the below-cut file).
        Must run under the owning partition's lock: the cut is only a
        cut because nothing appends or publishes while it is taken
        (PartitionManager.checkpoint_now is the one caller)."""
        doc = empty_doc(self.partition)
        doc["cut_offset"] = self.log.end_offset()
        doc["op_counters"] = dict(self.op_counters)
        doc["max_commit_vc"] = dict(self.max_commit_vc)
        wm = dict(self.commit_floor)
        for dc, cns in self._commit_ns.items():
            if cns:
                wm[dc] = max(wm.get(dc, 0), cns[-1])
        for dc in self._index_irregular:
            # an irregular origin's commit chain is scan-only; after a
            # truncation nothing below the cut can be served for it,
            # so its watermark must cover the whole captured stream
            wm[dc] = max(wm.get(dc, 0), self.op_counters.get(dc, 0))
        doc["commit_watermarks"] = wm
        pending = sorted(
            ((txid, off) for txid, ups in self._pending_updates.items()
             for _key, off in ups),
            key=lambda t: t[1])
        doc["pending"] = [(txid, off, self.log.read(off))
                          for txid, off in pending]
        doc["pending_floor"] = (pending[0][1] if pending
                                else doc["cut_offset"])
        # plan the truncation NOW and persist its outcome: the HARD
        # floors must land in the SAME document as the cut they result
        # from, or a restart would refuse the physically retained
        # (floor, cut] window (bouncing every lagging peer to the
        # bootstrap the ckpt_retain_ops margin exists to avoid).
        # adopt_checkpoint executes exactly this plan.
        trunc_cut = self.log.truncated_base
        if self.ckpt is not None and self.ckpt.settings.truncate:
            cut = min(doc["cut_offset"], doc["pending_floor"])
            ret_off = self._retention_offset()
            if ret_off is not None:
                cut = min(cut, ret_off)
            trunc_cut = max(cut, self.log.truncated_base)
        doc["trunc_cut"] = trunc_cut
        cf, of = self._floors_at(trunc_cut)
        doc["repair_floors"] = cf
        doc["op_floors"] = of
        if self.renumbered:
            doc["renumbered"] = True
        return doc

    def _floors_at(self, base: int) -> Tuple[dict, dict]:
        """(commit floors, op floors) as they will stand once the log
        is truncated below LOGICAL ``base`` — the ONE derivation home:
        the checkpoint document persists this pair and
        :meth:`note_truncated` adopts it when executing the plan."""
        cf = dict(self.commit_floor)
        of = dict(self._op_floor)
        if base <= self.log.truncated_base:
            return cf, of
        if self.log.truncated_base < self.suffix_start:
            # checkpoint-seeded restart: the rebuilt index is blind
            # below the boot cut, so reclaiming ANY blind bytes must
            # push the floors to the cut watermarks — the index cannot
            # enumerate what the reclaim swallowed, and an under-raised
            # floor turns a repair read into a silently under-served
            # answer instead of BELOW_FLOOR.  Conservative for origins
            # whose blind records all sit above ``base`` (they bounce
            # to a checkpoint bootstrap instead of a served scan) —
            # a safe degradation, never a hole.
            for dc, n in self._commit_index_floor.items():
                if n > cf.get(dc, 0):
                    cf[dc] = n
            for dc, n in self._op_index_floor.items():
                if n > of.get(dc, 0):
                    of[dc] = n
        for dc, cns in self._commit_ns.items():
            for n, ol in zip(cns, self._commit_offs[dc]):
                if min(ol) < base and n > cf.get(dc, 0):
                    cf[dc] = n
        for dc, ns in self._op_ns.items():
            offs = self._op_offs[dc]
            cut_i = bisect.bisect_left(offs, base)
            if cut_i and ns[cut_i - 1] > of.get(dc, 0):
                of[dc] = ns[cut_i - 1]
        for dc in self._index_irregular:
            n = self.op_counters.get(dc, 0)
            cf[dc] = max(cf.get(dc, 0), n)
            of[dc] = max(of.get(dc, 0), n)
        return cf, of

    def persist_checkpoint(self, doc: dict) -> None:
        """Atomically write ``doc`` to disk — the monolithic document
        or, under ``ckpt_segmented``, one dirty-delta segment + the
        manifest (CheckpointStore.persist routes the knob).
        Deliberately does NOT need the partition lock: the document is
        an immutable snapshot once captured, and the pickle + fsyncs +
        rename must not stall the partition's commits and reads (the
        PR-8 no-fsync-under-the-lock lesson).  The caller serializes
        writers (PartitionManager._ckpt_inflight) so documents — and
        segment/manifest pairs — land in cut order, which is also what
        keeps compaction single-flight against a concurrent
        checkpoint."""
        if self.ckpt is None:
            raise RuntimeError("checkpointing is disabled (Config.ckpt)")
        tracer.instant("ckpt_commit", "oplog", partition=self.partition,
                       cut=doc["cut_offset"], keys=len(doc["keys"]))
        if self.ckpt.settings.segmented:
            # the previous manifest's segment list is the base the new
            # dirty-delta segment stacks on
            doc["prev_segments"] = list(
                self.ckpt_doc.get("segments", ())) \
                if self.ckpt_doc else []
        self.ckpt.persist(doc)

    def stage_truncation(self, doc: dict) -> Optional[dict]:
        """Phase 1 of the document's truncation plan — compose the
        rewritten log file (truncation marker + retained suffix) via
        :meth:`DurableLog.stage_truncate_below`, OUTSIDE the partition
        lock: the retained tail can be hundreds of MB (the retention
        floor holds the cut back for lagging peers) and the PR-9 form
        copied it with every commit stalled behind the lock.  Returns
        the stage token :meth:`adopt_checkpoint` redeems, or None when
        truncation is off, the cut is a no-op, or another stage is in
        flight (the caller's next checkpoint retries).  The cut is
        bounded by the retention floor — ``min`` over peers of the
        inter-DC ship/ack watermark minus the ``retain_ops`` margin —
        so the persisted floors describe exactly the file the commit
        leaves behind."""
        if self.ckpt is None or not self.ckpt.settings.truncate \
                or self._trunc_hold:
            return None
        cut = min(doc.get("trunc_cut", 0), doc["cut_offset"],
                  doc["pending_floor"])
        if cut <= self.log.truncated_base:
            return None
        token = self.log.stage_truncate_below(cut)
        if token is None:
            return None
        return {"cut": cut, "token": token}

    def abort_truncation(self, trunc_stage: dict) -> None:
        """Discard a :meth:`stage_truncation` token whose checkpoint
        failed before :meth:`adopt_checkpoint` could redeem it — the
        stage/abort pair lives at ONE layer so callers never unwrap
        the DurableLog token themselves.  Idempotent after a landed
        commit (the token's generation no longer matches)."""
        self.log.abort_truncate(trunc_stage["token"])

    def adopt_checkpoint(self, doc: dict,
                         trunc_stage: Optional[dict] = None) -> None:
        """Make a persisted document's seeds live for the replay paths
        (eviction migration, read-below-base, host-store cache misses)
        and commit the staged truncation of log bytes below its cut
        (``trunc_stage``, from :meth:`stage_truncation` — run BEFORE
        taking the partition lock; only the bounded catch-up + rename
        half runs here).  Must run under the owning partition's lock,
        like :meth:`capture_cut` — the seed swap and the index prune
        race the readers otherwise."""
        doc.pop("delta", None)  # persisted (or folded into keys)
        self.ckpt_doc = doc
        self.ckpt_seeds = {
            key: (tn, state, VC(vc))
            for key, (tn, state, vc) in doc["keys"].items()}
        stats.registry.ckpt_keys.set(len(doc["keys"]),
                                     partition=str(self.partition))
        recorder.record("oplog", "ckpt_write", partition=self.partition,
                        cut=doc["cut_offset"], keys=len(doc["keys"]))
        if trunc_stage is not None:
            if self._trunc_hold:
                # a live resize fold is scanning the suffix above the
                # PREVIOUS cut (it froze the hold under this same
                # lock): committing would reclaim bytes its cursor
                # still needs — drop the stage; the next checkpoint
                # retries the truncation
                self.abort_truncation(trunc_stage)
            else:
                self._commit_truncation(doc, trunc_stage)

    def _commit_truncation(self, doc: dict, trunc_stage: dict) -> None:
        """Phase 2: redeem the staged rewrite — re-validate + bounded
        catch-up + atomic rename inside :meth:`DurableLog.
        commit_truncate` — and advance the below-base answer floors to
        match the file the rename left behind."""
        cut = trunc_stage["cut"]
        tracer.instant("ckpt_truncate", "oplog",
                       partition=self.partition, cut=cut)
        # the document's floors were derived for exactly trunc_cut; a
        # cut that diverged (defensive — capture computes trunc_cut as
        # this same min) re-derives BEFORE the base advances
        floors = (doc["repair_floors"], doc["op_floors"]) \
            if doc.get("trunc_cut") == cut else self._floors_at(cut)
        base = self.log.commit_truncate(trunc_stage["token"])
        if base > cut:
            # superseded: someone already truncated PAST our cut (a
            # superseded commit_truncate returns the higher live base,
            # never less) — our floors were derived for the lower cut
            # and would under-fence the reclaimed window
            return
        self.note_truncated(base, floors=floors)
        stats.registry.ckpt_truncations.inc()
        recorder.record("oplog", "log_truncate",
                        partition=self.partition, base=base)

    def _retention_offset(self) -> Optional[int]:
        """Lowest logical offset the retention floor requires us to
        keep, or None when unconstrained (no peers / no source: a
        later-joining peer bootstraps from the checkpoint)."""
        src = self.retention_opid_source
        dc = self.own_dc
        if src is None or dc is None:
            return None
        opid = src()
        if opid is None:
            return None
        keep_from = max(0, int(opid) - self.ckpt.settings.retain_ops)
        if self._commit_index_floor.get(dc, 0) >= keep_from:
            # the retained history the floor protects is below the
            # suffix-only index (a checkpoint-seeded restart): we
            # cannot place keep_from in the file, so hold the current
            # base — truncation resumes once the live index grows past
            # the margin, and the retained window stays answerable
            return self.log.truncated_base
        cns = self._commit_ns.get(dc)
        if not cns:
            return None  # no committed own-origin txns at all
        i = bisect.bisect_right(cns, keep_from)
        offlists = self._commit_offs[dc]
        if i >= len(cns):
            return None  # everything already covered by the floor
        # min over ALL retained txns' record offsets: interleaved
        # staging can put a later txn's update below an earlier txn's
        # — a retained txn must never lose a record to the cut
        return min(min(ol) for ol in offlists[i:])

    def note_truncated(self, base: int,
                       floors: Optional[Tuple[dict, dict]] = None
                       ) -> None:
        """Prune every in-memory index entry whose record bytes fell
        below the new truncation ``base`` and adopt the per-origin
        floors that gate range reads (BELOW_FLOOR) and seed the
        prev-opid chain.  ``floors`` is the (commit, op) pair
        :meth:`_floors_at` derived for this exact cut — normally the
        checkpoint document's persisted repair_floors/op_floors, so
        the executed truncation and the document can never disagree
        (one derivation home).  Without it the pair is re-derived,
        which only works BEFORE the log's truncated_base advances
        past ``base``."""
        if floors is None:
            floors = self._floors_at(base)
        cf, of = floors
        for dc, n in cf.items():
            if n > self.commit_floor.get(dc, 0):
                self.commit_floor[dc] = n
        for dc, n in of.items():
            if n > self._op_floor.get(dc, 0):
                self._op_floor[dc] = n
        # structural prune (the floor bookkeeping is above): reclaimed
        # records must leave the index, or range reads would seek
        # freed bytes
        for key in list(self.key_commits):
            arr = self.key_commits[key]
            kept = array.array("q")
            for i in range(0, len(arr), 2):
                if arr[i] >= base and arr[i + 1] >= base:
                    kept.extend((arr[i], arr[i + 1]))
            if len(kept) != len(arr):
                if kept:
                    self.key_commits[key] = kept
                else:
                    del self.key_commits[key]
        for dc in list(self._op_ns):
            ns, offs = self._op_ns[dc], self._op_offs[dc]
            cut_i = bisect.bisect_left(offs, base)
            if cut_i:
                self._op_ns[dc] = ns[cut_i:]
                self._op_offs[dc] = offs[cut_i:]
        for dc in list(self._commit_ns):
            cns, ols = self._commit_ns[dc], self._commit_offs[dc]
            new_cns = array.array("q")
            new_ols: List[array.array] = []
            for n, ol in zip(cns, ols):
                if min(ol) >= base:
                    new_cns.append(n)
                    new_ols.append(ol)
            self._commit_ns[dc] = new_cns
            self._commit_offs[dc] = new_ols
        # the index floors can never sit below the hard floors (the
        # scan the fallback would run cannot read reclaimed bytes)
        for dc, f in self.commit_floor.items():
            self._commit_index_floor[dc] = max(
                self._commit_index_floor.get(dc, 0), f)
        for dc, f in self._op_floor.items():
            self._op_index_floor[dc] = max(
                self._op_index_floor.get(dc, 0), f)
        if self.on_truncate is not None:
            self.on_truncate()

    def hold_truncation(self) -> None:
        """Pin the log's truncation base for the duration of a resize
        fold's suffix scan (take under the partition lock, so the pin
        and :meth:`adopt_checkpoint`'s commit decision serialize);
        release with :meth:`release_truncation`.  While held,
        :meth:`stage_truncation` declines and a staged truncation
        reaching :meth:`adopt_checkpoint` aborts instead of
        committing — checkpoints themselves keep landing."""
        self._trunc_hold += 1

    def release_truncation(self) -> None:
        self._trunc_hold -= 1

    def seed_for(self, key) -> Optional[Tuple[str, Any, VC]]:
        """The checkpoint's (type_name, state, frontier VC) seed for
        ``key``, or None — what eviction migration, read-below-base
        replay, and host-store cache misses start from instead of
        offset 0 (the below-cut history may be truncated)."""
        return self.ckpt_seeds.get(key)

    def suffix_payloads(self) -> List[Tuple[int, Payload]]:
        """Committed payloads of the RECOVERY SUFFIX only: transactions
        whose commit record lies at/after the checkpoint cut, with the
        cut-crossing pending updates prefed into the assembler.  With
        no checkpoint this is exactly :meth:`committed_payloads` —
        recovery's one replay entry point either way."""
        if not self.enabled:
            return []
        asm = TxnAssembler()
        for rec in self._suffix_prefeed:
            asm.process(rec)
        out: List[Tuple[int, Payload]] = []
        seq = 0
        for rec in self.records(self.suffix_start):
            done = asm.process(rec)
            if done is None:
                continue
            commit = done[-1]
            (dc, ct), svc = commit.payload[1], commit.payload[2]
            certified = commit_certified(commit.payload)
            for upd in done[:-1]:
                _, k, type_name, effect = upd.payload
                seq += 1
                out.append((seq, Payload(
                    key=k, type_name=type_name, effect=effect,
                    commit_dc=dc, commit_time=ct, snapshot_vc=svc,
                    txid=upd.txid, certified=certified)))
        return out

    # ----------------------------------------------------------- recovery

    def _recover(self) -> None:
        """Rebuild op-id counters, the per-key commit index, and the
        max commit VC from the log (reference get_last_op_from_log,
        src/logging_vnode.erl:595-643).

        With a valid checkpoint (ISSUE 10) the scan starts at the CUT,
        not offset 0: the document seeds the op-id counters, the max
        commit VC, the per-origin commit floors, and the cut-crossing
        pending update records, so recovery cost is O(suffix) however
        long the log below the cut grew — and keeps working after that
        prefix is physically truncated."""
        if not self.enabled:
            return
        self.log.flush()
        start = 0
        doc = self._boot_doc if self.ckpt is not None else None
        self._boot_doc = None
        if doc is not None and not self._ckpt_matches_log(doc):
            recorder.record("oplog", "ckpt_stale_ignored",
                            partition=self.partition,
                            cut=doc.get("cut_offset"))
            doc = None
        if doc is None and self.log.truncated_base > 0:
            # the log was truncated below a cut whose checkpoint is
            # now missing/corrupt: the suffix still recovers, but the
            # below-cut history (and op-id continuity!) is gone — keep
            # the loss loud, never silent
            import logging

            logging.getLogger(__name__).error(
                "partition %d: truncated log %s has no valid "
                "checkpoint — recovering the retained suffix only; "
                "op-id counters may under-recover", self.partition,
                self.path)
        if doc is not None:
            self.ckpt_doc = doc
            self.op_counters.update(doc["op_counters"])
            self.max_commit_vc = self.max_commit_vc.join(
                VC(doc["max_commit_vc"]))
            # HARD floors = what truncation reclaimed (persisted);
            # INDEX floors = the cut, below which the rebuilt index is
            # blind and the scan serves — the retained (floor, cut]
            # window keeps answering ordinary repair after a restart
            self.commit_floor.update(doc.get("repair_floors", {}))
            self._op_floor.update(doc.get("op_floors", {}))
            self._commit_index_floor.update(doc["commit_watermarks"])
            self._op_index_floor.update(doc["op_counters"])
            self.ckpt_seeds = {
                key: (tn, state, VC(vc))
                for key, (tn, state, vc) in doc["keys"].items()}
            self.keys_seen.update(doc["keys"])
            self.renumbered = bool(doc.get("renumbered", False))
            # cut-crossing txns: updates staged before the cut whose
            # commit lands in the suffix — prefeed the assembler state
            # exactly as the live run had it at the cut
            for _txid, off, rec_bytes in doc["pending"]:
                rec = LogRecord.from_bytes(rec_bytes)
                self._suffix_prefeed.append(rec)
                self._index(rec, off)
            start = self.suffix_start = doc["cut_offset"]
        for off, payload_bytes in self.log.scan(start):
            rec = LogRecord.from_bytes(payload_bytes)
            self._index(rec, off)
            cur = self.op_counters.get(rec.op_id.dc, 0)
            if rec.op_id.n > cur:
                self.op_counters[rec.op_id.dc] = rec.op_id.n
            if rec.kind() == "update":
                self.keys_seen.add(rec.payload[1])
            if rec.kind() == "commit":
                (dc, ct) = rec.payload[1]
                if ct > self.max_commit_vc.get_dc(dc):
                    self.max_commit_vc = self.max_commit_vc.set_dc(dc, ct)
                # join the commit's full snapshot VC: an applied commit's
                # dependencies were covered when it applied, so the
                # recovered dependency clock may include them — without
                # this, a restarted DC whose local commits depended on a
                # now-unreachable peer cannot cover its OWN history in
                # the stable snapshot (the reference recovers its stable
                # meta for the same reason, recover_meta_data_on_start)
                self.max_commit_vc = self.max_commit_vc.join(
                    rec.payload[2])

    def _ckpt_matches_log(self, doc: dict) -> bool:
        """A checkpoint is only usable when its cut lies inside the
        CURRENT log file AND lands on a record boundary there: a cut
        beyond the end means the log was deleted/replaced after the
        checkpoint, and a cut that does not parse as a record start
        means the file was REWRITTEN under the document (a resize or
        handoff installed different bytes at the same path — those
        paths also delete the .ckpt, this is the belt to that
        suspenders).  Recovery then falls back to the full scan."""
        cut = doc.get("cut_offset", -1)
        if doc.get("partition") != self.partition:
            return False
        if not self.log.truncated_base <= cut <= self.log.end_offset():
            return False
        return cut == self.log.end_offset() \
            or self.log.read(cut) is not None

    def close(self) -> None:
        if self.enabled:
            self.log.flush()
            self.log.close()

"""antidote_tpu — a TPU-native geo-replicated transactional CRDT store.

A from-scratch rebuild of the capabilities of AntidoteDB (reference at
/root/reference, Erlang/OTP + riak_core): Clock-SI/Cure causally-consistent
snapshot transactions over an op-based CRDT type system, per-partition
durable op logs with crash recovery, inter-DC replication with causal
dependency gating and gap repair, and a gossiped stable-snapshot (GST)
clock plane.

The design is TPU-first, not a port: the data plane (CRDT materialization,
vector-clock dominance, GST min-merge, causal gating) runs as batched
JAX/XLA kernels over dense arrays of keys sharded across a device mesh;
the control plane (transaction coordination, logging, replication
transport) is host-side Python/C++.
"""

import os as _os

import jax as _jax

# Timestamps are int64 microseconds throughout (the reference uses Erlang
# µs clocks); JAX defaults to 32-bit without this. NOTE: this is a
# process-global flag — import antidote_tpu before building unrelated JAX
# arrays, or set ANTIDOTE_TPU_NO_X64=1 and manage dtypes yourself (device
# kernels are dtype-polymorphic; hot paths can rebase to int32 ticks).
if not _os.environ.get("ANTIDOTE_TPU_NO_X64"):
    _jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

"""Transaction coordinator — the clocksi_interactive_coord equivalent.

The reference runs one gen_statem per transaction with states
execute_op / receive_prepared / committing / ... (reference
src/clocksi_interactive_coord.erl:90-105).  In-process, the same
protocol is a plain object driven synchronously by the caller:

- snapshot = stable snapshot ⊔ client clock, local entry bumped to now,
  with a clock wait if the client clock runs ahead (:906-926)
- updates: type check -> pre-commit hook -> downstream generation
  (reading own writes) -> durable log append + staging (:965-1038)
- commit: 0 partitions -> reads-only, causal clock = snapshot;
  1 partition -> single-commit fast path; N -> 2PC with
  commit time = max prepare time (:1043-1120)
"""

from __future__ import annotations

import itertools
import os
import time as _time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.clocks import VC
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import traced, tracer
from antidote_tpu.crdt import DownstreamCtx, DownstreamError, get_type, is_type
from antidote_tpu.mat.materializer import materialize_eager
from antidote_tpu.txn.manager import (
    _RAW_OP,
    CertificationError,
    PartitionManager,
    _is_raw,
)


def _batch_never_ran(exc) -> bool:
    """True only for whole-batch refusals raised BEFORE any element
    executed (the receiving handler's own guards) — the cases where
    re-sending the batch's mutating calls cannot double-apply."""
    from antidote_tpu.cluster.remote import RemoteCallError

    if not isinstance(exc, RemoteCallError):
        return False
    msg = str(exc)
    return ("unknown node RPC kind" in msg
            or "node not assembled yet" in msg)


def _is_retryable_route(exc) -> bool:
    """Errors the synchronous proxy path self-heals: a moved partition
    (re-resolve the ring) or a drain-window refusal (back off and
    re-send) — both transient routing states, not txn outcomes."""
    from antidote_tpu.cluster.remote import HandoffParked, WrongOwner

    return isinstance(exc, (WrongOwner, HandoffParked))


class TxnState(Enum):
    ACTIVE = "active"
    COMMITTED = "committed"
    ABORTED = "aborted"
    #: a 2PC commit round failed after at least one partition durably
    #: committed: the outcome is NOT a clean abort and must not be
    #: retried blindly
    UNKNOWN = "unknown"


class TransactionAborted(Exception):
    pass


class CommitOutcomeUnknown(Exception):
    """Raised when the commit decision was reached (all prepares
    succeeded) but applying it failed on some partition — effects may
    be partially durable, so reporting an abort would invite a retry
    and double-apply."""


@dataclass
class TxnProperties:
    """Reference txn properties (src/antidote.erl:202-238)."""

    update_clock: bool = True   # False = ignore the client clock
    certify: Optional[bool] = None  # None = node default


@dataclass
class Transaction:
    txid: Any
    snapshot_vc: VC
    properties: TxnProperties
    ctx: DownstreamCtx
    state: TxnState = TxnState.ACTIVE
    #: key -> (type_name, [effects]) in update order
    writeset: Dict[Any, Tuple[str, List[Any]]] = field(default_factory=dict)
    #: partitions touched by updates
    partitions: List[int] = field(default_factory=list)
    #: (bucket, key, type_name, op) for post-commit hooks
    client_ops: List[Tuple] = field(default_factory=list)
    #: partition -> [(key, type_name, effect)] buffered for DEFERRED
    #: staging (remote partitions: shipped with prepare/single-commit
    #: in one fabric round trip).  Entries whose effect is the tagged
    #: pair ("__raw_op__", op) are RAW OPERATIONS: downstream is
    #: generated at the owner against its own materialized state
    #: (reference clocksi_downstream runs at the vnode,
    #: src/clocksi_downstream.erl:41-68) — saving the exact-state read
    #: round trip the coordinator would otherwise pay per update
    deferred_ops: Dict[int, List[Tuple]] = field(default_factory=dict)
    #: keys with raw ops pending in deferred_ops: a read of one inside
    #: this txn must materialize them first (read-your-writes)
    raw_keys: set = field(default_factory=set)
    #: True while this txn holds the node's TxnGate shared (from first
    #: staged mutation to commit/abort) — live handoff drains these
    gated: bool = False
    commit_vc: Optional[VC] = None

    def own_effects(self, key) -> List[Any]:
        entry = self.writeset.get(key)
        return entry[1] if entry else []


#: process-unique txid suffix source: one random prefix per process +
#: a monotone counter — globally unique like uuid4 but without a
#: urandom syscall per transaction (the txn path runs thousands/s)
_TXID_PREFIX = os.urandom(6).hex()
_TXID_SEQ = itertools.count(1)


def _fresh_txid_suffix() -> str:
    return f"{_TXID_PREFIX}{next(_TXID_SEQ):x}"


def _fan_out(pairs, fn, spec=None):
    """Run ``fn(p, pm)`` for every 2PC participant, overlapping the
    REMOTE ones (their cost is a fabric round trip whose wait releases
    the GIL — the reference broadcasts prepare/commit and collects
    replies, src/clocksi_vnode.erl:168-200).  Results return in
    participant order; the first exception re-raises only after every
    call finished (a half-collected prepare round must not leak
    in-flight work).

    When ``spec(p, pm) -> (method, args, kwargs)`` is given and the
    remote link is pipelined (cluster/nativelink.py), the remote calls
    are batched PER OWNER MEMBER into one "part_batch" frame each —
    one fabric round trip per node, not per partition — started first
    from this thread (zero thread spawns — the reference's async
    broadcast, src/clocksi_interactive_coord.erl:514-577), local calls
    run while the frames are in flight, and the round is collected in
    one native wait.  Element failures inside a batch stay
    element-wise (a certification conflict on one partition does not
    mask the others' prepare times); a whole-batch refusal
    (resize parking, an older peer) self-heals per participant on the
    synchronous path.  Otherwise remote calls fall back to a thread
    per participant."""
    import threading as _threading

    remote = [(i, p, pm) for i, (p, pm) in enumerate(pairs)
              if getattr(pm, "deferred_stage", False)]
    results: list = [None] * len(pairs)
    errs: list = []
    handles = []
    if spec is not None and remote:
        link = remote[0][2].link
        if hasattr(link, "finish_many") and all(
                pm.link is link for _i, _p, pm in remote):
            by_owner: dict = {}
            for i, p, pm in remote:
                method, args, kwargs = spec(p, pm)
                by_owner.setdefault(pm.owner, []).append(
                    (i, pm.partition, method, tuple(args),
                     dict(kwargs)))
            try:
                for owner, calls in by_owner.items():
                    payload = [(part, m, a, kw)
                               for _i, part, m, a, kw in calls]
                    handles.append((owner, calls, link.start_request(
                        owner, "part_batch", (payload,))))
            except BaseException:
                # a failed start (unknown peer) must not leak the
                # already-started calls' native completion slots
                link.abandon([h for _o, _c, h in handles])
                raise
    if handles:
        for i, (p, pm) in enumerate(pairs):
            if not getattr(pm, "deferred_stage", False):
                try:
                    results[i] = fn(p, pm)
                except BaseException as e:  # noqa: BLE001 — below
                    errs.append(e)

        def heal(i):
            # moved/draining mid-round (cross-node handoff): the
            # synchronous path re-resolves / backs off and retries
            # (RemotePartition._call self-heals)
            try:
                results[i] = fn(pairs[i][0], pairs[i][1])
            except BaseException as e:  # noqa: BLE001 — below
                errs.append(e)

        from antidote_tpu.cluster.link import _raise_remote

        link = remote[0][2].link
        for (owner, calls, _h), (ok, val) in zip(
                handles, link.finish_many([h for _o, _c, h in
                                           handles])):
            if ok:
                for (i, pt, m, _a, _kw), (ok_i, v) in zip(calls, val):
                    if ok_i:
                        results[i] = v
                        continue
                    try:
                        # (err_kind, message); keep the owner + call
                        # in the message — a batched element failure
                        # must stay as diagnosable as a lone RPC's
                        _raise_remote(v[0],
                                      f"{owner!r} p{pt} {m}: {v[1]}")
                    except BaseException as e:  # noqa: BLE001
                        if _is_retryable_route(e):
                            heal(i)
                        else:
                            errs.append(e)
            elif _is_retryable_route(val) or _batch_never_ran(val):
                # provably PRE-EXECUTION refusals only: resize
                # parking, an old peer without the RPC, a member not
                # yet assembled.  Any other whole-batch error (a
                # timeout whose first execution may still complete, a
                # duplicate-request ambiguity) must NOT re-send
                # mutating 2PC calls — re-executing an applied commit
                # is a silent double-apply; surface it instead (the
                # commit round maps it to CommitOutcomeUnknown).
                for i, _pt, _m, _a, _kw in calls:
                    heal(i)
            else:
                errs.append(val)
        if errs:
            raise errs[0]
        return results
    if len(remote) <= 1:
        for i, (p, pm) in enumerate(pairs):
            results[i] = fn(p, pm)
        return results

    def run(i, p, pm):
        try:
            results[i] = fn(p, pm)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errs.append(e)

    threads = [_threading.Thread(target=run, args=(i, p, pm))
               for i, p, pm in remote]
    for t in threads:
        t.start()
    for i, (p, pm) in enumerate(pairs):
        if not getattr(pm, "deferred_stage", False):
            run(i, p, pm)
    for t in threads:
        t.join()
    if errs:
        raise errs[0]
    return results


class Coordinator:
    """Drives transactions against a Node (antidote_tpu/txn/node.py)."""

    def __init__(self, node):
        self.node = node

    # ------------------------------------------------------------ lifecycle

    def snapshot_for(self, client_clock: Optional[VC],
                     props: TxnProperties) -> VC:
        """The Clock-SI snapshot rule — stable ⊔ client clock (after
        the causal wait), local entry bumped to now — shared by
        start_transaction and the static-read fast path
        (api.read_objects_static): a one-shot read snapshots exactly
        like a transaction, it just skips the transaction."""
        node = self.node
        if client_clock and props.update_clock:
            snap = self._wait_for_clock(client_clock).join(client_clock)
        else:
            snap = VC(node.stable_vc())
        return snap.set_dc(node.dc_id, max(snap.get_dc(node.dc_id),
                                           node.clock.now_us()))

    def start_transaction(self, client_clock: Optional[VC] = None,
                          properties: Optional[TxnProperties] = None
                          ) -> Transaction:
        props = properties or TxnProperties()
        node = self.node
        snap = self.snapshot_for(client_clock, props)
        txid = (snap.get_dc(node.dc_id), _fresh_txid_suffix())
        stats.registry.open_transactions.inc()
        tracer.instant("txn_start", "coordinator", txid=txid,
                       dc=str(node.dc_id))
        return Transaction(
            txid=txid, snapshot_vc=snap, properties=props,
            ctx=DownstreamCtx(actor=(str(node.dc_id), txid[1]),
                              mint=node.mint_dot))

    def _wait_for_clock(self, client_clock: VC) -> VC:
        """Spin until the snapshot (stable GST with the local entry at
        `now`) dominates the client's causal clock — THE cross-DC causal
        wait (reference wait_for_clock,
        src/clocksi_interactive_coord.erl:915-926).  The local entry
        covers clock skew; remote entries block until replication has
        applied everything the client has already seen."""
        import time as _time

        node = self.node
        deadline = _time.monotonic() + node.config.clock_wait_timeout_s
        while True:
            snap = VC(node.stable_vc())
            snap = snap.set_dc(node.dc_id, max(snap.get_dc(node.dc_id),
                                               node.clock.now_us()))
            if snap.ge(client_clock):
                return snap
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"snapshot never caught up with client clock "
                    f"{dict(client_clock)}; stable={dict(snap)}")
            node.wait_hook()

    def gr_snapshot_wait(self, client_clock: Optional[VC]) -> VC:
        """GentleRain snapshot choice (reference gr_snapshot_obtain,
        src/cure.erl:233-257): block until the client's entry for THIS
        DC is covered by the scalar GST, then read at a snapshot whose
        every entry is the GST — the min over known DCs, replicated to
        all entries (reference dc_utilities:get_stable_snapshot GR
        branch, src/dc_utilities.erl:246-279).  One scalar per snapshot
        is what makes GentleRain's metadata O(1) instead of O(#DCs)."""
        import time as _time

        node = self.node
        want = client_clock.get_dc(node.dc_id) if client_clock else 0
        deadline = _time.monotonic() + node.config.clock_wait_timeout_s
        while True:
            st = VC(node.stable_vc())
            entries = dict(st)
            gst = min(entries.values()) if entries else 0
            if want <= gst:
                snap = VC({dc: gst for dc in entries})
                return snap.set_dc(node.dc_id, gst)
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"GST {gst} never caught up with client clock entry "
                    f"{want} for {node.dc_id}")
            node.wait_hook()

    def start_transaction_gr(self, client_clock: Optional[VC] = None,
                             properties: Optional[TxnProperties] = None
                             ) -> Transaction:
        """A transaction pinned to the GentleRain snapshot (static-read
        path, reference cure:obtain_objects Protocol=gr)."""
        props = properties or TxnProperties()
        snap = self.gr_snapshot_wait(
            client_clock if props.update_clock else None)
        txid = (snap.get_dc(self.node.dc_id), _fresh_txid_suffix())
        stats.registry.open_transactions.inc()
        tracer.instant("txn_start", "coordinator", txid=txid,
                       dc=str(self.node.dc_id), protocol="gr")
        return Transaction(
            txid=txid, snapshot_vc=snap, properties=props,
            ctx=DownstreamCtx(actor=(str(self.node.dc_id), txid[1]),
                              mint=self.node.mint_dot))

    def _check_active(self, tx: Transaction) -> None:
        if tx.state is not TxnState.ACTIVE:
            raise TransactionAborted(f"transaction is {tx.state.value}")

    # ---------------------------------------------------------------- reads

    def _multi_or_fallback(self, link, owner, payload, groups, tx):
        """One per-owner batched read over a non-pipelined link, with
        the per-partition self-healing path as fallback."""
        try:
            return link.request(owner, "part_multi", payload)
        except Exception as e:  # noqa: BLE001 — heal per partition
            return self._read_groups_fallback(groups, tx, e)

    def _read_groups_fallback(self, groups, tx, err):
        """Resolve a failed per-owner batch partition by partition.
        Only ROUTING-class failures fall back (a moved/draining slot,
        or a RemoteCallError — which also covers an older peer that
        does not speak part_multi): the per-partition path self-heals
        those.  A real error (a read timeout on a prepared txn, a
        link failure) re-raises immediately — re-issuing every
        partition's read would serialize the same wait N times over
        before surfacing the same failure."""
        from antidote_tpu.cluster.remote import RemoteCallError

        if not (_is_retryable_route(err)
                or isinstance(err, RemoteCallError)):
            raise err
        values: dict = {}
        for pm, items in groups:
            values.update(pm.read_many(items, tx.snapshot_vc,
                                       txid=tx.txid))
        return values

    @traced("txn_read", "coordinator")
    def read_objects(self, tx: Transaction, bound_objects: List) -> List[Any]:
        """Reads grouped per partition and executed as one batched call
        each (async batched reads, reference
        src/clocksi_interactive_coord.erl:731-747): a multi-key read
        costs one lock pass + one device fold per (partition, type)
        instead of one per key."""
        self._check_active(tx)
        stats.registry.operations.inc(len(bound_objects), type="read")
        # hold the handoff gate for the batch unless the txn already
        # does: a cutover swaps the partition objects out mid-resolve
        gate = None if tx.gated else self.node.txn_gate
        if gate is not None:
            try:
                gate.enter()
            except TimeoutError:
                self.abort_transaction(tx)  # see update_objects
                raise
        try:
            metas = []
            by_pm: dict = {}
            for bo in bound_objects:
                key, type_name, _bucket = self.node.normalize_bound(bo)
                cls = get_type(type_name)
                pm = self.node.partition_of(key)
                if key in tx.raw_keys:
                    # this txn updated the key with owner-deferred raw
                    # ops — materialize them into effects so the read
                    # below observes them (read-your-writes)
                    self._materialize_raw_ops(tx, key)
                metas.append((key, cls, pm))
                by_pm.setdefault(pm, []).append((key, cls.name))
            values: dict = {}
            # remote partitions batch PER OWNER MEMBER (one fabric
            # round trip per node, fused per-chip server-side —
            # cluster/node.py "part_multi"), started first on a
            # pipelined link so local partitions resolve while the
            # frames are in flight (the reference's async batched
            # reads, src/clocksi_interactive_coord.erl:731-747)
            handles = []
            link = None
            try:
                local_groups = []
                by_owner: dict = {}
                for pm, items in by_pm.items():
                    if isinstance(pm, PartitionManager):
                        local_groups.append((pm, items))
                    elif hasattr(pm, "owner") and hasattr(pm, "link"):
                        by_owner.setdefault(pm.owner, []).append(
                            (pm, items))
                    else:
                        # a stand-in without the proxy surface (the
                        # mocked test tier): plain per-partition call
                        values.update(pm.read_many(
                            items, tx.snapshot_vc, txid=tx.txid))
                for owner, groups in by_owner.items():
                    payload = ([(pm.partition, items)
                                for pm, items in groups],
                               tx.snapshot_vc, tx.txid)
                    l = groups[0][0].link
                    if hasattr(l, "finish_many"):
                        link = l
                        handles.append((l.start_request(
                            owner, "part_multi", payload), groups))
                    else:
                        values.update(self._multi_or_fallback(
                            l, owner, payload, groups, tx))
                if local_groups:
                    # local partitions route through the read serve
                    # plane (mat/serve.py): concurrent transactions'
                    # snapshot reads coalesce into one gathered fold
                    # per window; read_serve=False (or a bare pm
                    # without a server) keeps the per-txn paths —
                    # single-partition read_many / the fused cross-
                    # partition fold (manager.read_many_fused)
                    from antidote_tpu.mat.serve import read_groups

                    values.update(read_groups(
                        local_groups, tx.snapshot_vc, txid=tx.txid))
            except BaseException:
                # a local read failed mid-round: started remote calls
                # must not leak their native completion slots
                if handles:
                    link.abandon([h for h, _g in handles])
                raise
            if handles:
                for (ok, val), (_h, groups) in zip(
                        link.finish_many([h for h, _g in handles]),
                        handles):
                    if ok:
                        values.update(val)
                    else:
                        # moved/parked/unsupported mid-read: the
                        # per-partition path self-heals each proxy
                        values.update(self._read_groups_fallback(
                            groups, tx, val))
            out = []
            for key, cls, pm in metas:
                value = values[(key, cls.name)]
                own = tx.own_effects(key)
                if own:
                    value = materialize_eager(cls.name, value, own)
                out.append(cls.value(value))
        except Exception as e:
            # a failed read aborts the transaction, as the coordinator
            # FSM does on a read error (reference
            # receive_read_objects_result error path)
            self.abort_transaction(tx)
            raise TransactionAborted(f"read failed: {e}") from e
        finally:
            if gate is not None:
                gate.exit()
        return out

    # -------------------------------------------------------------- updates

    @traced("txn_update", "coordinator")
    def update_objects(self, tx: Transaction, updates: List) -> None:
        """[(bound_object, op_name, op_param)] — validate, hook,
        generate downstream, log, stage."""
        self._check_active(tx)
        stats.registry.operations.inc(len(updates), type="update")
        if not tx.gated:
            # shared handoff gate, held to commit/abort: a cutover must
            # never swap the logs out from under a txn's staged records
            try:
                self.node.txn_gate.enter()
            except TimeoutError:
                # admission blocked by a cutover: the txn dies here —
                # without the abort, the open-transactions gauge leaks
                self.abort_transaction(tx)
                raise
            tx.gated = True
        try:
            self._apply_updates(tx, updates)
        except TransactionAborted:
            raise  # abort paths already released the gate
        except BaseException:
            # an unexpected escape (bad op shape, a remote fabric
            # error) must not leak the shared gate — callers like the
            # PB server report generic errors without aborting
            if tx.state is TxnState.ACTIVE:
                self.abort_transaction(tx)
            raise

    def _apply_updates(self, tx: Transaction, updates: List) -> None:
        for upd in updates:
            bo, op_name, op_param = self.node.normalize_update(upd)
            key, type_name, bucket = self.node.normalize_bound(bo)
            cls = get_type(type_name) if is_type(type_name) else None
            op = (op_name, op_param)
            if cls is None or not cls.is_operation(op):
                # abort like the hook/downstream failure paths below —
                # leaving the txn ACTIVE would leak staged effects and
                # the open-transactions gauge
                self.abort_transaction(tx)
                raise TypeError(f"type_check failed: {type_name} {op!r}")
            try:
                key2, type_name2, op = self.node.hooks.run_pre(
                    bucket, key, type_name, op)
            except Exception as e:
                self.abort_transaction(tx)
                raise TransactionAborted(f"pre-commit hook failed: {e}") from e
            cls = get_type(type_name2)
            pm = self.node.partition_of(key2)
            remote = getattr(pm, "deferred_stage", False)
            if (remote and cls.require_state_downstream(op)
                    and cls.name != "counter_b"):
                # REMOTE + state-requiring: ship the raw op and let the
                # OWNER generate downstream against its local
                # materialized state (the reference generates at the
                # vnode, src/clocksi_downstream.erl:41-68) — this
                # removes a full exact-state read round trip per
                # update.  counter_b keeps the coordinator detour: its
                # downstream consults the bcounter permission manager,
                # which lives with the coordinator's node.
                tx.deferred_ops.setdefault(pm.partition, []).append(
                    (key2, cls.name, (_RAW_OP, op)))
                tx.raw_keys.add(key2)
                if pm.partition not in tx.partitions:
                    tx.partitions.append(pm.partition)
                tx.client_ops.append((bucket, key2, cls.name, op))
                continue
            try:
                state = None
                if cls.require_state_downstream(op):
                    # exact_state: an effect built from the device fold's
                    # per-DC dot collapse would under-cancel at exact
                    # replicas (set_rw/flag_dw) — see DevicePlane.state_exact
                    state = pm.read_with_writeset(
                        key2, cls.name, tx.snapshot_vc, tx.txid,
                        tx.own_effects(key2), exact_state=True)
                effect = self.node.gen_downstream(
                    cls, op, state, tx.ctx, key=key2, bucket=bucket)
            except DownstreamError as e:
                self.abort_transaction(tx)
                raise TransactionAborted(f"downstream failed: {e}") from e
            if remote:
                tx.deferred_ops.setdefault(pm.partition, []).append(
                    (key2, cls.name, effect))
            else:
                pm.stage_update(tx.txid, key2, cls.name, effect)
            entry = tx.writeset.setdefault(key2, (cls.name, []))
            entry[1].append(effect)
            if pm.partition not in tx.partitions:
                tx.partitions.append(pm.partition)
            tx.client_ops.append((bucket, key2, cls.name, op))

    def _materialize_raw_ops(self, tx: Transaction, key) -> None:
        """Convert a key's pending raw ops into effects at the
        coordinator (the pre-owner-generation path): needed when THIS
        txn reads a key it updated with owner-deferred ops — the read
        must observe them (read-your-writes), and own-effect
        materialization works on effects, not ops."""
        pm = self.node.partition_of(key)
        entries = tx.deferred_ops.get(pm.partition, [])
        for i, (k, tname, eff) in enumerate(entries):
            if k != key or not _is_raw(eff):
                continue
            cls = get_type(tname)
            state = pm.read_with_writeset(
                key, tname, tx.snapshot_vc, tx.txid,
                tx.own_effects(key), exact_state=True)
            effect = self.node.gen_downstream(
                cls, eff[1], state, tx.ctx, key=key)
            entries[i] = (k, tname, effect)
            ws = tx.writeset.setdefault(key, (tname, []))
            ws[1].append(effect)
        tx.raw_keys.discard(key)

    # --------------------------------------------------------------- commit

    @traced("txn_commit", "coordinator")
    def commit_transaction(self, tx: Transaction) -> VC:
        t0 = _time.perf_counter()
        self._check_active(tx)
        node = self.node
        certify = (tx.properties.certify
                   if tx.properties.certify is not None else node.config.certify)
        if not tx.partitions:
            commit_vc = tx.snapshot_vc
        elif len(tx.partitions) == 1:
            pm = node.partitions[tx.partitions[0]]
            deferred = tx.deferred_ops.get(tx.partitions[0])
            try:
                with tracer.span("single_commit", "coordinator",
                                 txid=tx.txid,
                                 partition=tx.partitions[0]):
                    if deferred is not None:
                        ct = pm.stage_single_commit(
                            tx.txid, deferred, tx.snapshot_vc, certify)
                    else:
                        ct = pm.single_commit(tx.txid, tx.snapshot_vc,
                                              certify)
            except CertificationError as e:
                self.abort_transaction(tx)
                raise TransactionAborted(str(e)) from e
            except Exception as e:
                # single_commit is atomic at the partition: a failure
                # means nothing durable happened, so aborting is safe —
                # the reference FSM never leaves a transaction open
                # after a failed prepare (receive_prepared abort path,
                # src/clocksi_interactive_coord.erl:1078-1120)
                self.abort_transaction(tx)
                raise TransactionAborted(f"commit failed: {e}") from e
            commit_vc = tx.snapshot_vc.set_dc(node.dc_id, ct)
        else:
            pms = [node.partitions[p] for p in tx.partitions]

            def _prepare(p, pm):
                if p in tx.deferred_ops:
                    return pm.stage_prepare(tx.txid, tx.deferred_ops[p],
                                            tx.snapshot_vc, certify)
                return pm.prepare(tx.txid, tx.snapshot_vc, certify)

            def _prepare_spec(p, pm):
                if p in tx.deferred_ops:
                    return ("stage_prepare",
                            (tx.txid, [tuple(o) for o in
                                       tx.deferred_ops[p]],
                             tx.snapshot_vc, certify), {})
                return ("prepare", (tx.txid, tx.snapshot_vc, certify),
                        {})

            try:
                with tracer.span("2pc_prepare", "coordinator",
                                 txid=tx.txid,
                                 partitions=len(tx.partitions)):
                    prepare_times = _fan_out(
                        [(p, pm) for p, pm in zip(tx.partitions, pms)],
                        _prepare, spec=_prepare_spec)
            except CertificationError as e:
                self.abort_transaction(tx)
                raise TransactionAborted(str(e)) from e
            except Exception as e:
                # prepare failures are pre-decision: abort is safe
                self.abort_transaction(tx)
                raise TransactionAborted(f"prepare failed: {e}") from e
            ct = max(prepare_times)
            try:
                with tracer.span("2pc_commit", "coordinator",
                                 txid=tx.txid,
                                 partitions=len(tx.partitions)):
                    _fan_out(
                        [(p, pm) for p, pm in zip(tx.partitions, pms)],
                        lambda _p, pm: pm.commit(tx.txid, ct,
                                                 tx.snapshot_vc,
                                                 certified=certify),
                        spec=lambda _p, _pm: (
                            "commit", (tx.txid, ct, tx.snapshot_vc),
                            {"certified": certify}))
            except Exception as e:
                # post-decision failure: some partitions may hold a
                # durable commit record — reporting an abort here would
                # invite a retry and double-apply
                tx.state = TxnState.UNKNOWN
                stats.registry.open_transactions.dec()
                self._release_gate(tx)
                recorder.record("txn", "commit_unknown", txid=tx.txid,
                                error=str(e))
                recorder.dump("commit_unknown")
                raise CommitOutcomeUnknown(
                    f"commit decided at {ct} but applying it failed: {e}"
                ) from e
            commit_vc = tx.snapshot_vc.set_dc(node.dc_id, ct)
        tx.state = TxnState.COMMITTED
        tx.commit_vc = commit_vc
        stats.registry.commit_latency.observe(_time.perf_counter() - t0)
        stats.registry.open_transactions.dec()
        self._release_gate(tx)
        for bucket, key, type_name, op in tx.client_ops:
            node.hooks.run_post(bucket, key, type_name, op)
        return commit_vc

    def _release_gate(self, tx: Transaction) -> None:
        if tx.gated:
            tx.gated = False
            self.node.txn_gate.exit()

    def abort_transaction(self, tx: Transaction) -> None:
        if tx.state is not TxnState.ACTIVE:
            return
        tracer.instant("txn_abort", "coordinator", txid=tx.txid,
                       partitions=len(tx.partitions))
        recorder.record("txn", "abort", txid=tx.txid,
                        partitions=list(tx.partitions),
                        keys=list(tx.writeset))
        for p in tx.partitions:
            try:
                self.node.partitions[p].abort(tx.txid)
            except Exception:  # noqa: BLE001 — best-effort cleanup
                # an unreachable participant cannot be told to abort;
                # its in-memory staged/prepared state dies with it and
                # recovery discards commit-less records — letting this
                # escape would mask the abort CAUSE the caller reports
                import logging as _logging

                _logging.getLogger(__name__).warning(
                    "abort of %r at partition %d failed (participant "
                    "unreachable?)", tx.txid, p, exc_info=True)
        tx.state = TxnState.ABORTED
        stats.registry.open_transactions.dec()
        stats.registry.aborted_transactions.inc()
        self._release_gate(tx)
        # forensic snapshot of the window leading up to the abort —
        # AFTER partition cleanup and the gate release, so neither
        # readers blocked on this txn's prepared keys nor
        # start_transaction callers waiting on a gate slot are held out
        # for the (rate-limited, but synchronous) ring serialization +
        # disk write
        recorder.dump("txn_abort", extra={"txid": repr(tx.txid)})

from antidote_tpu.txn.clock import HybridClock  # noqa: F401
from antidote_tpu.txn.coordinator import (  # noqa: F401
    Coordinator,
    Transaction,
    TransactionAborted,
    TxnProperties,
    TxnState,
)
from antidote_tpu.txn.manager import CertificationError, PartitionManager  # noqa: F401
from antidote_tpu.txn.node import Node  # noqa: F401

"""Per-partition transaction participant — the clocksi_vnode equivalent.

Owns the partition's prepared/committed bookkeeping, write-write
certification, Clock-SI read gating, the durable log, and the host
materializer store (reference src/clocksi_vnode.erl:253-678 and
src/clocksi_readitem_server.erl:217-288).

Concurrency model: the reference uses one vnode process + 20 read
servers with shared-ETS lock-free reads; here a per-partition lock +
condition variable — reads that must wait for a conflicting prepared
transaction block on the condition until commit/abort notifies
(check_prepared_list semantics, src/clocksi_readitem_server.erl:254-264).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import replace as dc_replace
from typing import Any, Callable, Dict, List, Optional, Tuple

from antidote_tpu import stats
from antidote_tpu.clocks import VC
from antidote_tpu.mat.device_plane import DevicePlane, ReadBelowBase
from antidote_tpu.mat.host_store import HostStore
from antidote_tpu.mat.materializer import (
    MaterializedSnapshot,
    Payload,
    SnapshotGetResponse,
    materialize,
    materialize_eager,
    materialize_from_log,
)
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer
from antidote_tpu.oplog.partition import PartitionLog
from antidote_tpu.oplog.records import commit_certified
from antidote_tpu.txn.clock import HybridClock

log = logging.getLogger(__name__)


class CertificationError(Exception):
    """Write-write certification failed — transaction must abort."""


class PartitionRetired(Exception):
    """The partition's log was snapshot for a cross-node handoff; no
    further mutation may land here.  Raised under the partition lock by
    every mutating entry point once the handoff cutover set
    ``retired`` — the cluster RPC layer converts it to a typed
    wrong-owner redirect (the riak_core forwarding that follows a
    handoff, reference src/logging_vnode.erl:781-812)."""


class DeviceFlusher:
    """One background thread draining scheduled device flush/GC jobs —
    group commit for the data plane: the committing transaction only
    STAGES (list append); the XLA dispatch runs here, under the owning
    partition's lock with readers quiesced — exactly the conditions the
    inline path had, minus the committing client waiting out the
    flush.  (The reference materializer applies its op cache outside
    the commit reply path the same way,
    src/materializer_vnode.erl:620-647.)"""

    def __init__(self):
        import queue

        self._q: "queue.Queue" = queue.Queue()
        self._queued: set = set()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def schedule(self, pm: "PartitionManager", plane) -> None:
        key = (id(pm), id(plane))
        with self._lock:
            if key in self._queued:
                return
            self._queued.add(key)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="device-flusher")
                self._thread.start()
        self._q.put((key, pm, plane))

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            key, pm, plane = item
            with self._lock:
                self._queued.discard(key)
            try:
                with pm._lock:
                    pm._wait_device_quiesce()
                    plane.flush_gc_now()
            except Exception:  # noqa: BLE001 — the drain must not die
                import logging as _logging

                _logging.getLogger(__name__).exception(
                    "background device flush failed")

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            self._q.put(None)
            t.join(timeout=5.0)


#: tag marking a deferred-op entry that carries a RAW OPERATION whose
#: downstream the OWNER partition generates (reference
#: clocksi_downstream at the vnode, src/clocksi_downstream.erl:41-68)
_RAW_OP = "__raw_op__"


def _is_raw(effect) -> bool:
    return (isinstance(effect, tuple) and len(effect) == 2
            and effect[0] == _RAW_OP)


#: stable-horizon sampling throttle (seconds); see PartitionManager
_STABLE_REFRESH_S = 0.05

#: warm-apply guard: CRDT update copies containers, so a large cached
#: state would pay O(|state|) per commit under the partition lock —
#: beyond this size the entry retires and reads pay the device fold
#: instead (the cheaper side of the trade flips)
_WARM_STATE_MAX = 512

_CONTAINERS = (dict, tuple, list, set, frozenset)


def _approx_size(state, budget: int) -> int:
    """Element count including nested containers (a map field wrapping
    a huge set must count as huge), early-exiting once past ``budget``
    so the guard itself stays O(budget), not O(|state|)."""
    if not isinstance(state, _CONTAINERS):
        return 1
    total = len(state)
    for v in (state.values() if isinstance(state, dict) else state):
        if total > budget:
            break
        if isinstance(v, _CONTAINERS):
            # minus 1: the child already counted once in len(state)
            total += _approx_size(v, budget - total) - 1
    return total


def _warm_cheap(state) -> bool:
    return _approx_size(state, _WARM_STATE_MAX) <= _WARM_STATE_MAX


class PartitionManager:
    def __init__(self, partition: int, dc_id, log: PartitionLog,
                 clock: HybridClock, read_wait_timeout: float = 5.0,
                 device_plane: Optional[DevicePlane] = None):
        self.partition = partition
        self.dc_id = dc_id
        self.log = log
        log.own_dc = dc_id  # the stream the retention floor protects
        self.clock = clock
        self.store = HostStore(log_fallback=log.committed_payloads,
                               has_history=log.keys_seen.__contains__,
                               seed_source=log.seed_for)
        #: TPU data plane for supported types (None = host-only node)
        self.device = device_plane
        if device_plane is not None:
            # export_state: with enable_logging=False there is no log
            # to replay on eviction — the plane must materialize host
            # state from the device fold BEFORE dropping the lanes
            # (the PR-7-flagged silent-zeroing bug)
            device_plane.set_evict_handler(
                self._migrate_key_to_host,
                export_state=not log.enabled)
        self.read_wait_timeout = read_wait_timeout
        #: owner-side downstream generation hooks (set by the Node):
        #: gen_downstream_cb(cls, op, state, ctx, key=) and the node's
        #: dot minter — needed to resolve shipped raw ops (see
        #: _resolve_raw_ops)
        self.gen_downstream_cb = None
        self.mint_dot_cb = None
        #: GC horizon source (set by Node): a clock no FUTURE commit can
        #: fall below — the GST.  A txn's own snapshot is NOT safe here: a
        #: concurrent txn prepared earlier can still commit with a lower
        #: time, and pruning at an unstable horizon loses its op from the
        #: cached bases.  Must be called OUTSIDE self._lock (it reads
        #: min-prepared across partitions).
        self.stable_vc_source: Callable[[], VC] = VC
        #: sampled horizon cache: the source sweeps every partition, so
        #: it is refreshed at most every ``_STABLE_REFRESH_S`` (the
        #: reference's stable plane ticks at 1 s / 100 ms; an older
        #: horizon is merely conservative for GC)
        self._stable_cache = VC()
        self._stable_cached_at = 0.0
        self._lock = threading.Condition()
        #: set (under self._lock) by the handoff cutover at the moment
        #: the final log tail is snapshot: appends require self._lock,
        #: so checking this flag in the same critical section as the
        #: append makes "record lands after the tail snapshot"
        #: impossible — the in-flight mutator that raced the drain gets
        #: PartitionRetired instead of a silent ack
        self.retired = False
        #: stronger park for IN-DOUBT ownership (a handoff whose
        #: install may or may not have been applied at an unreachable
        #: receiver): READS refuse too — the receiver may have adopted
        #: and taken writes, and after a restart the local pm may sit
        #: on a rebuilt EMPTY log, so serving a read here could return
        #: stale or bottom values for committed keys.  ``retired``
        #: alone keeps reads flowing (the drain window needs them).
        self.parked = False
        #: txid -> (prepare_time, [keys])
        self.prepared: Dict[Any, Tuple[int, List[Any]]] = {}
        #: key -> last committed time at this DC
        self.committed: Dict[Any, int] = {}
        #: ops staged per txid before commit (the txn's effects on this
        #: partition, already in the durable log)
        self._staged: Dict[Any, List[Tuple[Any, str, Any]]] = {}
        #: per-key commit frontier (join of every published op's
        #: (commit_dc, commit_time)) and a latest-value cache keyed on
        #: it — the materializer snapshot cache in front of the device
        #: plane (reference materializer_vnode ETS snapshot_cache,
        #: src/materializer_vnode.erl:36-47).  A cached value is served
        #: only to reads that dominate the key's whole frontier, and a
        #: new arrival moves the frontier, so staleness is impossible.
        self.key_frontier: Dict[Any, VC] = {}
        #: key -> [frontier, state, writes_since_read, exact]: _publish
        #: applies committed effects onto the cached state (warm cache)
        #: until ``_warm_writes_cap`` commits pass with no read — then
        #: the entry retires, so write-only keys don't pay a host CRDT
        #: materialization per commit forever.  ``exact`` records whether
        #: the state's lineage is host-exact (host store / log replay /
        #: state-exact device fold) — downstream-generation reads of
        #: STATE_LOSSY device types may only use exact entries
        #: (DevicePlane.state_exact)
        self._val_cache: Dict[Any, list] = {}
        self._val_cache_cap = 65536
        self._warm_writes_cap = 32
        #: seed the cache from bottom on a key's FIRST publish (the
        #: reference materializer stores the snapshot it builds at
        #: update time, src/materializer_vnode.erl:620-647) — freshly
        #: written keys then serve reads warm instead of paying a cold
        #: device fold each.  The Node disables this when recovery is
        #: off while logging is on: there the log may hold history this
        #: process never published, and a bottom-seeded state would
        #: disagree with the log-fallback read.
        self.seed_cache_on_first_publish = True
        #: cross-transaction read-coalescing window fronting this
        #: partition's snapshot reads (antidote_tpu/mat/serve.py) —
        #: set by the Node's partition factory so the knobs route
        #: through serve_from_config; None = no serve plane (direct
        #: per-call reads, the bare-PartitionManager test tier)
        self.read_server = None
        #: device reads in flight outside the lock (see read()): the
        #: append/gc kernels DONATE their input buffers, so a device
        #: mutation while a reader still holds the captured shard state
        #: would hand the reader deleted buffers — writers wait for
        #: readers to drain (readers share; mutations exclusive)
        self._dev_readers = 0
        #: strict durability-before-visibility ordering (ISSUE 10
        #: satellite, Config.publish_after_durable): commit/apply
        #: publish their effects only after the durability ticket is
        #: covered.  Set by the Node's partition factory.
        self.publish_after_durable = False
        #: deferred publishes in flight (publish_after_durable): txns
        #: whose commit record is appended but whose effects are not
        #: yet in the store.  A checkpoint cut taken inside that window
        #: would put the commit record BELOW the cut while the seed
        #: fold misses the effect — the txn would vanish from seed AND
        #: suffix on recovery — so checkpoint_now quiesces this to 0
        #: before capturing the cut.
        self._defer_unpublished = 0
        #: keys published since the last checkpoint cut (key -> type):
        #: the incremental fold set of checkpoint_now
        self._ckpt_dirty: Dict[Any, str] = {}
        #: published-op / appended-byte counters driving the
        #: watermark-triggered checkpoint (maybe_checkpoint)
        self._ckpt_ops = 0
        self._ckpt_last_end = log.suffix_start if log.enabled else 0
        #: one checkpoint writer at a time: the persist runs outside
        #: the partition lock, and unserialized writers could land
        #: documents on disk out of cut order
        self._ckpt_inflight = False

    # ----------------------------------------------------------- log scans

    def scan_log(self, fn):
        """Run ``fn(self.log)`` serialized against this partition's
        appenders: scans share the appenders' file handle, so an unlocked
        scan could interleave seeks with a writer and corrupt the log.
        The locking discipline lives here, not at call sites."""
        with self._lock:
            return fn(self.log)

    # ------------------------------------------------------------ updates

    def _mutate_check(self) -> None:
        """Must run under self._lock, before any log append."""
        if self.retired:
            raise PartitionRetired(
                f"partition {self.partition} handed off")

    def _read_check(self) -> None:
        """Must run under self._lock, before serving a read."""
        if self.parked:
            raise PartitionRetired(
                f"partition {self.partition} ownership in doubt")

    def stage_update(self, txid, key, type_name: str, effect) -> None:
        """Log the update record and stage it for commit (the reference's
        async append + FSM ack path, src/clocksi_interactive_coord.erl:1029-1038)."""
        with self._lock:
            self._mutate_check()
            self.log.append_update(self.dc_id, txid, key, type_name, effect)
            self._staged.setdefault(txid, []).append((key, type_name, effect))

    def _resolve_raw_ops(self, txid, ops, snapshot_vc: Optional[VC]
                         ) -> List[Tuple[Any, str, Any]]:
        """Generate downstream AT THE OWNER for shipped raw operations
        (entries whose effect is ``(_RAW_OP, op)``) — the reference's
        clocksi_downstream runs next to the vnode holding the state
        (src/clocksi_downstream.erl:41-68), and shipping the op instead
        of pre-reading the state saves the coordinator one exact-state
        round trip per update.  Effects (raw or pre-generated) of the
        SAME transaction on the same key are applied progressively so
        each generation observes its predecessors.  Runs OUTSIDE
        self._lock: the snapshot read may clock-wait / block on
        prepared txns exactly like any read."""
        if not any(_is_raw(e) for _k, _t, e in ops):
            return list(ops)
        if snapshot_vc is None:
            raise ValueError("raw deferred ops need the txn snapshot")
        from antidote_tpu.crdt import DownstreamCtx, get_type

        ctx = DownstreamCtx(actor=(str(self.dc_id), txid[1]),
                            mint=self.mint_dot_cb)
        own: Dict[Any, List[Any]] = {}
        resolved = []
        for key, type_name, eff in ops:
            if _is_raw(eff):
                cls = get_type(type_name)
                state = self.read_with_writeset(
                    key, type_name, snapshot_vc, txid,
                    own.get(key, []), exact_state=True)
                effect = self.gen_downstream_cb(
                    cls, eff[1], state, ctx, key=key)
            else:
                effect = eff
            own.setdefault(key, []).append(effect)
            resolved.append((key, type_name, effect))
        return resolved

    def stage_group(self, txid, ops: List[Tuple[Any, str, Any]],
                    snapshot_vc: Optional[VC] = None) -> None:
        """Stage a transaction's whole op list for this partition in one
        lock pass (the deferred-staging form a remote coordinator ships
        with prepare — see stage_prepare).  Raw shipped operations are
        resolved to effects first (owner-side downstream generation)."""
        ops = self._resolve_raw_ops(txid, ops, snapshot_vc)
        with self._lock:
            self._mutate_check()
            staged = self._staged.setdefault(txid, [])
            for key, type_name, effect in ops:
                self.log.append_update(self.dc_id, txid, key, type_name,
                                       effect)
                staged.append((key, type_name, effect))

    def stage_prepare(self, txid, ops, snapshot_vc: VC,
                      certify: bool = True) -> int:
        """Stage + prepare in one call — one fabric round trip per
        remote 2PC participant.  The reference ships update records
        asynchronously and prepares after the log acks
        (src/clocksi_interactive_coord.erl:514-577, 1043-1075); the
        deferred coordinator buffers its remote writeset locally and
        this call preserves the same contract: everything durable at
        the owner before the prepare ack."""
        self.stage_group(txid, ops, snapshot_vc)
        return self.prepare(txid, snapshot_vc, certify)

    def stage_single_commit(self, txid, ops, snapshot_vc: VC,
                            certify: bool = True) -> int:
        """Stage + single-partition fast-path commit in one call (one
        round trip for a remote single-partition transaction)."""
        self.stage_group(txid, ops, snapshot_vc)
        return self.single_commit(txid, snapshot_vc, certify)

    # -------------------------------------------------------- 2PC on this partition

    def certify(self, txid, keys: List[Any], snapshot_vc: VC) -> None:
        """Write-write certification (reference certification_check,
        src/clocksi_vnode.erl:588-632): abort if a key was committed after
        the txn's local snapshot, or is prepared by another transaction."""
        local_start = snapshot_vc.get_dc(self.dc_id)
        for key in keys:
            if self.committed.get(key, 0) > local_start:
                raise CertificationError(f"key {key!r} committed after snapshot")
        for other_tx, (_pt, pkeys) in self.prepared.items():
            if other_tx == txid:
                continue
            if any(k in pkeys for k in keys):
                raise CertificationError("key prepared by concurrent txn")

    def prepare(self, txid, snapshot_vc: VC, certify: bool = True) -> int:
        """Certify + log a prepare record; returns the prepare time."""
        with self._lock:
            self._mutate_check()
            keys = [k for k, _t, _e in self._staged.get(txid, [])]
            if certify:
                self.certify(txid, keys, snapshot_vc)
            pt = self.clock.now_us()
            self.prepared[txid] = (pt, keys)
            self.log.append_prepare(self.dc_id, txid, pt)
            return pt

    def _stable_for_gc(self) -> VC:
        """Throttled GC horizon; call OUTSIDE self._lock."""
        now = time.monotonic()
        if now - self._stable_cached_at > _STABLE_REFRESH_S:
            self._stable_cache = self.stable_vc_source()
            self._stable_cached_at = now
        return self._stable_cache

    def _publish(self, key, type_name: str, payload: Payload,
                 stable: Optional[VC]) -> None:
        """Route one committed effect to its materializer: the device
        plane for supported types, the host store otherwise (the
        reference's update_materializer, src/clocksi_vnode.erl:634-657).
        Must run under self._lock.

        Uncertified commits (txn_cert off / DONT_CERTIFY) may mint
        concurrent same-key dots at one DC, which the device plane's
        per-DC dot collapse cannot represent — dot-bearing types from
        such commits stay on the host path (evicting the key's device
        history first if it has any).

        ORDERING (the round-5 transient-miss horizon race): any
        device-quiesce wait must happen BEFORE the op becomes visible
        in key_frontier / the value cache.  _wait_device_quiesce waits
        on the condition, RELEASING self._lock — a reader slipping in
        while the frontier already covered the unstaged op would pass
        covers_all, fold device state missing the op, and _cache_put
        would pin that stale value under the NEW frontier object (a
        poisoned hit for every read until the key's next publish).
        Waiting first keeps the invariant a reader relies on: whatever
        the frontier covers is visible to a device fold captured now."""
        if self.device is not None:
            unsound = (not payload.certified
                       and type_name in self.device.dot_collapse_types)
            device_route = (not unsound
                            and self.device.accepts(type_name, key))
            evict_route = unsound and self.device.owns(type_name, key)
            if device_route or evict_route:
                # the accepts/owns decisions are re-checked after the
                # wait (another publisher can run a whole stage-
                # overflow-EVICT cycle in the window, see below)
                self._wait_device_quiesce()
        # join the FULL commit VC (snapshot deps included): covers_all
        # must imply the read's inclusion mask admits this op, and the
        # mask tests the whole commit VC, not just the commit entry.
        # Read fr_old AFTER any wait above: a same-key publisher that
        # completed during the window moved the frontier, and the warm
        # cache update below must chain from the CURRENT entry.
        fr_old = self.key_frontier.get(key)
        fr_new = (fr_old or VC()).join(payload.commit_vc())
        self.key_frontier[key] = fr_new
        # checkpoint dirty set (ISSUE 10): this key's folded seed is
        # stale from here until the next cut re-folds it
        self._ckpt_dirty[key] = type_name
        self._ckpt_ops += 1
        # keep the commit-frontier value cache WARM instead of popping
        # it: apply the committed effect to the cached state (the
        # reference materializer applies updates onto its cached
        # snapshot rather than rematerializing, src/materializer_vnode
        # .erl:620-647).  Sound because effects commute and _publish
        # serializes per key under the lock; identity of the stored
        # frontier object is what readers re-check.
        ent = self._val_cache.get(key)
        if ent is not None and ent[0] is fr_old \
                and ent[2] < self._warm_writes_cap \
                and _warm_cheap(ent[1]):
            try:
                self._val_cache[key] = [fr_new, materialize_eager(
                    type_name, ent[1], [payload.effect]), ent[2] + 1,
                    ent[3]]
            except Exception:
                self._val_cache.pop(key, None)
        elif ent is None and fr_old is None \
                and self.seed_cache_on_first_publish \
                and len(self._val_cache) < self._val_cache_cap:
            # first committed op ever for this key: seed warm from the
            # type's bottom (exact host-oracle lineage — fr_old None
            # means nothing else has been published for it)
            from antidote_tpu.crdt import get_type

            try:
                self._val_cache[key] = [fr_new, materialize_eager(
                    type_name, get_type(type_name).new(),
                    [payload.effect]), 0, True]
            except Exception:  # noqa: BLE001 — cache stays cold
                pass
        else:
            # entry cold (stale frontier) or write-only hot (nobody has
            # read it for _warm_writes_cap commits): retire it instead
            # of paying a host materialization per commit forever
            self._val_cache.pop(key, None)
        if self.device is not None:
            if device_route:
                # the wait already ran above, with the lock held
                # continuously since: the frontier advance and the
                # stage are atomic to readers.  The re-check guards the
                # stage-overflow-EVICT cycle another publisher may have
                # run during the wait window — staging anyway would
                # re-register the evicted key with only this op's
                # history, a silently diverging replica (caught by the
                # concurrent-writers chaos test).
                if self.device.accepts(type_name, key):
                    # the plane owns the op from here — including the
                    # eviction path, where the key's whole history (this
                    # op included, it is already in the log) migrates to
                    # the host store
                    bounce = self.device.stage(key, type_name, payload,
                                               stable)
                    if bounce is not None:
                        # unlogged decode-reject eviction: the bounced
                        # effect (whole op, or a map's residual entry
                        # subset) never landed on the device and the
                        # exported state predates it — there is no log
                        # to replay it from, so fold it into the
                        # seeded snapshot (whose VC — the frontier
                        # joined above — already covers it; an
                        # ordinary insert would be replay-skipped as
                        # in-base), falling back to a plain insert
                        # when the export itself failed
                        if not self.store.apply_to_seed(
                                key, type_name, bounce):
                            self.store.insert(
                                key, type_name,
                                dc_replace(payload, effect=bounce),
                                stable_vc=stable)
                elif not self.log.enabled:
                    # evicted while we waited: with a log the migration
                    # replayed it (this op was appended first); without
                    # one, the CONCURRENT eviction's export predates
                    # this op AND its seed VC does not cover it (the
                    # evictor joined its own frontier, not ours) — an
                    # ordinary insert is correctly replay-gated
                    self.store.insert(key, type_name, payload,
                                      stable_vc=stable)
                # else: evicted while we waited — the migration replayed
                # the log, which already holds this op (every caller
                # appends before publishing), so nothing more to insert
                return
            if evict_route:
                # eviction migrates the full log history — which already
                # contains this op — so nothing more to insert (with a
                # log; unlogged, this op never staged so the export
                # cannot cover it and it must land on the host here)
                if self.device.owns(type_name, key):  # see re-check above
                    self.device.planes[type_name].evict(key)
                    if not self.log.enabled:
                        # OUR eviction: its seed VC is the frontier
                        # joined above (covers this op) — fold in
                        if not self.store.apply_to_seed(
                                key, type_name, payload.effect):
                            self.store.insert(key, type_name, payload,
                                              stable_vc=stable)
                elif not self.log.enabled:
                    # evicted during the wait by another publisher:
                    # that seed's VC predates this op — plain insert
                    self.store.insert(key, type_name, payload,
                                      stable_vc=stable)
                return
        self.store.insert(key, type_name, payload, stable_vc=stable)

    def _wait_device_quiesce(self) -> None:
        """Block (under self._lock) until no lock-free device reader is
        in flight: device mutations donate buffers a reader may still
        hold.  Must run under self._lock."""
        while self._dev_readers:
            self._lock.wait()

    def _migrate_key_to_host(self, key, type_name: str,
                             state=None) -> None:
        """Device-plane eviction handler: rebuild the key's host-store
        entry from the durable log (runs under self._lock — the lock is
        re-entrant).  Drops the key's value-cache entry: a fold-derived
        inexact state must not survive the move to the host path, where
        the cache-hit checks no longer guard exactness (the host store
        itself is exact by construction).

        With ``enable_logging=False`` the replay yields nothing — the
        pre-fix path silently ZEROED the key (PR-7 flag, reproduced on
        clean HEAD).  The plane now exports the key's device-fold
        ``state`` before dropping the lanes, and the host store is
        seeded from it at the key's commit frontier: every read whose
        snapshot covers the frontier (the overwhelmingly common shape)
        serves the true value; reads below it have no history to
        replay anywhere, exactly unlogged mode's existing contract."""
        self._val_cache.pop(key, None)
        replayed = False
        seed = self.log.seed_for(key)
        if seed is not None and seed[0] == type_name:
            # checkpoint-seeded migration (ISSUE 10): the host entry
            # starts from the folded state at the cut, and the log
            # replay below only contributes the retained suffix —
            # ops already inside the seed are replay-gated by its VC
            # (op_covered_by), so the pre-truncation full history and
            # the post-truncation suffix both reassemble exactly
            self.store.seed_state(key, type_name, seed[1], seed[2])
            replayed = True
        for _seq, p in self.log.committed_payloads(key=key):
            self.store.insert(key, type_name, p)
            replayed = True
        if not replayed and state is not None:
            self.store.seed_state(key, type_name, state,
                                  self.key_frontier.get(key))

    def _mid_batch_migrated(self, pre_hosted: Optional[set], key) -> bool:
        """True when ``key`` was evicted to the host DURING the current
        publish batch: the eviction's migration replayed the key's FULL
        log — which already contains every op of this batch (callers
        append before publishing) — so publishing the key's remaining
        batch items would double-apply them in the host store.  ``pre_
        hosted`` is the host_only snapshot taken before the batch."""
        return (pre_hosted is not None and key not in pre_hosted
                and key in self.device.host_only)

    def _note_skipped_publish(self, key, payload: Payload) -> None:
        """Bookkeeping for a batch item whose STATE application was
        covered by a mid-batch migration: the commit frontier must
        still advance (an understated frontier lets an old snapshot
        read pass covers_all, cache a stale value keyed by the stale
        frontier object, and serve it to every later read), and any
        cache entry pinned to the pre-skip frontier must drop."""
        fr_old = self.key_frontier.get(key)
        self.key_frontier[key] = (fr_old or VC()).join(
            payload.commit_vc())
        self._val_cache.pop(key, None)
        self._ckpt_dirty[key] = payload.type_name
        self._ckpt_ops += 1

    def _pre_hosted(self) -> Optional[set]:
        return set(self.device.host_only) if self.device is not None \
            else None

    def commit(self, txid, commit_time: int, snapshot_vc: VC,
               certified: bool = True) -> None:
        """Log the commit (fsync per config), publish the effects to the
        materializer store, release prepared state and wake blocked
        readers (reference commit handler src/clocksi_vnode.erl:499-531,
        update_materializer :634-657).

        GROUP COMMIT (ISSUE 9): under the group-commit log plane with
        ``sync_on_commit``, the commit record only STAGES inside the
        lock; the committer takes a durability ticket, releases the
        partition lock, and waits OUT OF LOCK for the synced watermark
        to cover it — concurrent committers share one buffered write
        and one fsync, and the partition's commit throughput stops
        degenerating to its disk's fsync rate.  The commit is acked
        (this method returns) only once the ticket is covered; the
        legacy path (``Config.log_group=False``) keeps the inline
        fsync under the lock exactly as before."""
        stable = self._stable_for_gc()  # before the lock (see __init__)
        with self._lock:
            self._mutate_check()
            self.log.append_commit(self.dc_id, txid, commit_time,
                                   snapshot_vc, certified)
            ticket = self.log.commit_ticket()
            defer = self.publish_after_durable and ticket is not None
            if defer:
                self._defer_unpublished += 1
            else:
                self._publish_commit_locked(txid, commit_time,
                                            snapshot_vc, certified,
                                            stable)
        # durability gate OUTSIDE the partition lock: readers and other
        # committers proceed while this committer waits out the shared
        # fsync (its effects are already published — group commit
        # trades the ack point, not the visibility point).  Under
        # Config.publish_after_durable the order flips: the effects
        # publish only once the ticket is covered (strict durability-
        # before-visibility; the prepared entry keeps conflicting
        # readers blocked across the wait, so no torn visibility).
        # The deferred publish runs even when the WAIT fails (wedged
        # drain leader, close race): the commit record is already in
        # the log — recovery would replay it — and leaving the
        # prepared entry behind would wedge every conflicting reader
        # forever; the error still propagates (the ack fails).
        try:
            self.log.wait_durable(ticket, txid=txid)
        finally:
            if defer:
                with self._lock:
                    try:
                        self._publish_commit_locked(txid, commit_time,
                                                    snapshot_vc,
                                                    certified, stable)
                    finally:
                        self._defer_unpublished -= 1
                        self._lock.notify_all()
        self.maybe_checkpoint()

    def _publish_commit_locked(self, txid, commit_time: int,
                               snapshot_vc: VC, certified: bool,
                               stable: Optional[VC]) -> None:
        """The visibility half of commit(): publish the staged
        effects, release the prepared entry, wake blocked readers.
        Must run under self._lock."""
        pre_hosted = self._pre_hosted()
        for key, type_name, effect in self._staged.pop(txid, []):
            payload = Payload(
                key=key, type_name=type_name, effect=effect,
                commit_dc=self.dc_id, commit_time=commit_time,
                snapshot_vc=snapshot_vc, txid=txid,
                certified=certified)
            if self._mid_batch_migrated(pre_hosted, key):
                self._note_skipped_publish(key, payload)
            else:
                self._publish(key, type_name, payload, stable)
            if commit_time > self.committed.get(key, 0):
                self.committed[key] = commit_time
        self.prepared.pop(txid, None)
        self._lock.notify_all()

    def single_commit(self, txid, snapshot_vc: VC,
                      certify: bool = True) -> int:
        """One-partition fast path: prepare + commit in one step
        (reference single_commit, src/clocksi_vnode.erl:180-190)."""
        with self._lock:
            self._mutate_check()
            keys = [k for k, _t, _e in self._staged.get(txid, [])]
            if certify:
                self.certify(txid, keys, snapshot_vc)
            ct = self.clock.now_us()
            self.prepared[txid] = (ct, keys)
        self.commit(txid, ct, snapshot_vc, certified=certify)
        return ct

    def abort(self, txid) -> None:
        with self._lock:
            self._mutate_check()
            if txid in self._staged or txid in self.prepared:
                self.log.append_abort(self.dc_id, txid)
            self._staged.pop(txid, None)
            self.prepared.pop(txid, None)
            self._lock.notify_all()

    # ------------------------------------------------------ remote apply

    def apply_remote(self, records, origin_dc, commit_time: int,
                     snapshot_vc: VC) -> None:
        """Apply a replicated transaction from another DC: append its
        records without assigning local ids, then publish the effects to
        the materializer store (reference inter_dc_dep_vnode try_store
        apply path, src/inter_dc_dep_vnode.erl:144-152).  Remote txns do
        not touch the prepared/committed certification tables — local
        certification is local-only; concurrent remote updates resolve by
        CRDT semantics, not aborts."""
        stable = self._stable_for_gc()  # before the lock (see __init__)
        certified = all(commit_certified(rec.payload) for rec in records
                        if rec.kind() == "commit")

        def publish_locked():
            pre_hosted = self._pre_hosted()
            for rec in records:
                if rec.kind() != "update":
                    continue
                _, key, type_name, effect = rec.payload
                payload = Payload(
                    key=key, type_name=type_name, effect=effect,
                    commit_dc=origin_dc, commit_time=commit_time,
                    snapshot_vc=snapshot_vc, txid=rec.txid,
                    certified=certified)
                if self._mid_batch_migrated(pre_hosted, key):
                    # eviction replayed the whole group's state; the
                    # frontier still advances
                    self._note_skipped_publish(key, payload)
                else:
                    self._publish(key, type_name, payload, stable)
            self._lock.notify_all()

        with self._lock:
            self._mutate_check()
            ticket = self.log.append_remote_group(records)
            defer = self.publish_after_durable and ticket is not None
            if defer:
                self._defer_unpublished += 1
            else:
                publish_locked()
        # remote applies ride the same group-commit durability gate as
        # local commits (out of lock; see commit()); under
        # publish_after_durable the publish follows the covered ticket
        # (the gate delivers causally-ordered batches from one thread,
        # so the flipped order cannot reorder two batches), and — like
        # commit() — still runs when the wait itself fails: the
        # records are appended and the gate already advanced past this
        # batch, so skipping the publish would silently drop it
        try:
            self.log.wait_durable(ticket)
        finally:
            if defer:
                with self._lock:
                    try:
                        publish_locked()
                    finally:
                        self._defer_unpublished -= 1
                        self._lock.notify_all()
        self.maybe_checkpoint()

    # --------------------------------------------------------------- reads

    def _blocking_prepared(self, key, snapshot_vc: VC, txid) -> bool:
        local = snapshot_vc.get_dc(self.dc_id)
        for other_tx, (pt, pkeys) in self.prepared.items():
            if other_tx != txid and pt <= local and key in pkeys:
                return True
        return False

    def read(self, key, type_name: str, snapshot_vc: Optional[VC],
             txid=None, exact_state: bool = False) -> Any:
        """Clock-SI safe read: wait until the local clock passed the
        snapshot and no conflicting prepared txn may commit below it
        (reference check_clock/check_prepared,
        src/clocksi_readitem_server.erl:236-264), then materialize.

        ``exact_state``: the caller will feed the state to downstream
        generation (require_state_downstream) — device folds of
        STATE_LOSSY types (whose reconstruction collapses per-DC dot
        sets) are refused and replaced by an exact log replay; an effect
        built from a collapsed state would under-cancel at exact
        replicas, diverging the federation permanently."""
        if snapshot_vc is not None:
            # clock wait happens outside the lock (it can be long and
            # must not stall commits on this partition)
            self.clock.wait_until(snapshot_vc.get_dc(self.dc_id))
        reader = None
        with self._lock:
            self._read_check()
            if snapshot_vc is not None:
                deadline = time.monotonic() + self.read_wait_timeout
                while self._blocking_prepared(key, snapshot_vc, txid):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._lock.wait(timeout=remaining):
                        raise TimeoutError(
                            f"read of {key!r} blocked on prepared txn")
            if self.device is not None and self.device.owns(type_name, key):
                fold_exact = self.device.state_exact(type_name, key)
                need_exact = exact_state and not fold_exact
                # the device fold runs OUTSIDE the lock on the captured
                # immutable shard state (plane.read_begin) — the
                # read-concurrency analogue of the reference's read
                # servers next to the vnode (src/clocksi_readitem_server
                # .erl:95-110).  Host-store reads stay under the lock:
                # they are dict lookups, and commit() mutates the same
                # entries.
                fr = self.key_frontier.get(key)
                covers_all = fr is not None and (
                    snapshot_vc is None or fr.le(snapshot_vc))
                if covers_all:
                    ent = self._val_cache.get(key)
                    if ent is not None and ent[0] is fr \
                            and (ent[3] or not need_exact):
                        ent[2] = 0
                        stats.registry.read_cache_hits.inc()
                        return ent[1]
                stats.registry.read_cache_misses.inc()
                if need_exact:
                    value = self._read_from_log(key, type_name,
                                                snapshot_vc, txid)
                    if covers_all:
                        self._cache_put(key, fr, value, True)
                    return value
                plane = self.device.planes[type_name]
                if key in plane.pending_keys:
                    # read_begin will flush (donating buffers): drain
                    # in-flight readers of older captures first
                    self._wait_device_quiesce()
                try:
                    reader = plane.read_begin(key, snapshot_vc)
                except ReadBelowBase:
                    reader = False  # sentinel: log replay below
                else:
                    stats.registry.read_dispatches.inc()
                    self._dev_readers += 1
            else:
                value = self._read_store(key, type_name, snapshot_vc, txid,
                                         exact_state=exact_state)
                return value
        if reader is False:
            with self._lock:  # log scans serialize with appenders
                value = self._read_from_log(key, type_name, snapshot_vc,
                                            txid)
                if covers_all and self.key_frontier.get(key) is fr:
                    self._cache_put(key, fr, value, True)
                return value
        try:
            value = reader()
        finally:
            with self._lock:
                self._dev_readers -= 1
                self._lock.notify_all()
        if covers_all:
            with self._lock:
                # re-check: a publish while we folded moved the frontier
                if self.key_frontier.get(key) is fr:
                    self._cache_put(key, fr, value, fold_exact)
        self._maybe_probe_set_aw(key, type_name, snapshot_vc, txid,
                                 value)
        return value

    def _maybe_probe_set_aw(self, key, type_name: str, snapshot_vc,
                            txid, value) -> None:
        """Sampled read-inclusion self-check for device-served set_aw
        reads (antidote_tpu/obs/probe.py): re-materialize from the log
        at the SAME snapshot and require every oracle element in the
        device fold's state.  A violation dumps the flight recorder —
        the forensic tripwire for the VERDICT round-5 transient miss."""
        from antidote_tpu.obs import probe

        if type_name != "set_aw" or not probe.should_check(snapshot_vc):
            return
        with self._lock:  # log scans serialize with appenders
            oracle = self._read_from_log(key, type_name, snapshot_vc,
                                         txid)
        probe.verify_set_aw_inclusion(self.partition, key, snapshot_vc,
                                      value, oracle)

    def _cache_put(self, key, fr, value, exact: bool) -> None:
        """Store a value-cache entry (under self._lock)."""
        if len(self._val_cache) >= self._val_cache_cap:
            self._val_cache.clear()
        self._val_cache[key] = [fr, value, 0, exact]

    def _read_store(self, key, type_name: str, read_vc: Optional[VC],
                    txid=None, exact_state: bool = False) -> Any:
        """Materialized value from whichever plane owns the key; must run
        under self._lock.  Device keys read via the batched fold; reads
        below the device base (or with clocks outside its DC domain)
        replay the log — the reference's snapshot-cache miss."""
        fr = self.key_frontier.get(key)
        covers_all = fr is not None and (read_vc is None or fr.le(read_vc))
        if covers_all:
            ent = self._val_cache.get(key)
            # frontier identity (not just dominance) guarantees no op
            # arrived since the entry was materialized
            if ent is not None and ent[0] is fr \
                    and (ent[3] or not exact_state):
                ent[2] = 0
                stats.registry.read_cache_hits.inc()
                return ent[1]
        stats.registry.read_cache_misses.inc()
        if self.device is not None and self.device.owns(type_name, key):
            exact = self.device.state_exact(type_name, key)
            try:
                if exact_state and not exact:
                    raise ReadBelowBase()  # lossy fold: exact replay
                stats.registry.read_dispatches.inc()
                value = self.device.read(key, type_name, read_vc,
                                         txid=txid)
            except ReadBelowBase:
                # log replay is host-oracle exact — cacheable like any
                # other frontier-covering read
                value = self._read_from_log(key, type_name, read_vc,
                                            txid)
                exact = True
        else:
            exact = True
            value, _vc = self.store.read(key, type_name, read_vc, txid=txid)
        if covers_all:
            self._cache_put(key, fr, value, exact)
        return value

    def _read_from_log(self, key, type_name: str, read_vc: Optional[VC],
                       txid=None) -> Any:
        """Log replay for one key (reference get_from_snapshot_log,
        src/materializer_vnode.erl:415-419).  With a checkpoint seed
        covering the read, the replay starts from the folded state at
        the cut and applies only the retained suffix (O(delta)) —
        which is also what keeps this path exact after truncation
        reclaimed the below-cut bytes."""
        seed = self.log.seed_for(key)
        if seed is not None and seed[0] == type_name:
            _tn, state, vc = seed
            if read_vc is None or vc.le(read_vc):
                payloads = self.log.committed_payloads(key=key)
                resp = SnapshotGetResponse(
                    snapshot_time=vc, ops=list(reversed(payloads)),
                    materialized=MaterializedSnapshot(0, state))
                return materialize(type_name, txid, read_vc,
                                   resp).value
            # the seed cannot base this read (below/concurrent with
            # its frontier) and the per-key index only covers the
            # suffix: the assembling whole-log scan is the exact
            # answer while the below-cut bytes remain; once truncated
            # it degrades to the retained history (the documented
            # unlogged-mode-style contract for reads below the cut)
            return materialize_from_log(
                type_name, self.log.committed_payloads(key=key,
                                                       scan=True),
                read_vc, txid).value
        return materialize_from_log(
            type_name, self.log.committed_payloads(key=key), read_vc,
            txid).value

    def read_with_writeset(self, key, type_name: str, snapshot_vc,
                           txid, own_effects: List[Any],
                           exact_state: bool = False) -> Any:
        """Read + replay the transaction's own uncommitted effects
        (read-your-writes, reference apply_tx_updates_to_snapshot,
        src/clocksi_interactive_coord.erl:880-894).  ``exact_state`` as
        in :meth:`read`; the own-effect replay preserves exactness (it
        runs the host oracle's update)."""
        value = self.read(key, type_name, snapshot_vc, txid=txid,
                          exact_state=exact_state)
        if own_effects:
            value = materialize_eager(type_name, value, own_effects)
        return value

    def read_many(self, items: List[Tuple[Any, str]], snapshot_vc,
                  txid=None) -> Dict[Tuple[Any, str], Any]:
        """Batched Clock-SI reads for THIS partition: one lock pass
        gates and splits the keys (cache / device / host), then one
        device fold PER TYPE runs outside the lock for all its keys —
        the async-batched-reads pipelining of the reference coordinator
        (src/clocksi_interactive_coord.erl:731-747) fused with the
        read-server concurrency split of :meth:`read`."""
        out, dev_batches = self.read_many_begin(items, snapshot_vc,
                                                txid)
        return self.read_many_finish(out, dev_batches, snapshot_vc,
                                     txid)

    def read_many_begin(self, items, snapshot_vc, txid=None,
                        nowait=False):
        """First half of :meth:`read_many`: gate, split, flush, and
        capture the device folds (reader counts INCREMENTED — the
        caller MUST run read_many_finish exactly once, whatever
        happens).  Split out so a multi-partition caller can fuse the
        captured folds across partitions per chip (read_many_fused).

        ``nowait=True`` returns None instead of blocking or flushing:
        no prepared-txn wait, no device flush.  The cross-GROUP fused
        drain (mat/serve.py) begins several groups before finishing
        any, so its later begins hold earlier begins' reader counts —
        a flush's quiesce wait here would deadlock on the caller's OWN
        readers.  A None defers the group to a sequential pass after
        the fused wave releases its readers."""
        if snapshot_vc is not None:
            self.clock.wait_until(snapshot_vc.get_dc(self.dc_id))
        out: Dict[Tuple[Any, str], Any] = {}
        dev_batches = []  # (type, [(key, cacheable_frontier)], closure)
        with self._lock:
            self._read_check()
            if snapshot_vc is not None:
                if nowait and any(
                        self._blocking_prepared(k, snapshot_vc, txid)
                        for k, _t in items):
                    return None
                deadline = time.monotonic() + self.read_wait_timeout
                while any(self._blocking_prepared(k, snapshot_vc, txid)
                          for k, _t in items):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._lock.wait(
                            timeout=remaining):
                        raise TimeoutError(
                            "batched read blocked on prepared txn")
            by_type: Dict[str, list] = {}
            cache_hits = dev_misses = 0
            for key, type_name in items:
                fr = self.key_frontier.get(key)
                covers = fr is not None and (
                    snapshot_vc is None or fr.le(snapshot_vc))
                if covers:
                    ent = self._val_cache.get(key)
                    if ent is not None and ent[0] is fr:
                        ent[2] = 0
                        out[(key, type_name)] = ent[1]
                        cache_hits += 1
                        continue
                if self.device is not None and self.device.owns(
                        type_name, key):
                    dev_misses += 1
                    by_type.setdefault(type_name, []).append(
                        (key, fr if covers else None,
                         self.device.state_exact(type_name, key)))
                else:
                    # _read_store counts its own cache hit/miss
                    out[(key, type_name)] = self._read_store(
                        key, type_name, snapshot_vc, txid)
            if cache_hits:
                stats.registry.read_cache_hits.inc(cache_hits)
            if dev_misses:
                stats.registry.read_cache_misses.inc(dev_misses)
            # flush EVERY type first, then create closures: a flush is
            # a buffer-donating device mutation, and quiescing for a
            # later type would deadlock on our own earlier closure's
            # reader count
            for type_name, pairs in by_type.items():
                plane = self.device.planes[type_name]
                if not plane.pending_keys.isdisjoint(
                        [k for k, _fr, _ex in pairs]):
                    if nowait:
                        return None  # no closures yet — nothing leaks
                    self._wait_device_quiesce()
                    plane.flush()
            for type_name, pairs in by_type.items():
                plane = self.device.planes[type_name]
                keys_t = [k for k, _fr, _ex in pairs]
                try:
                    closure = plane.read_many_begin(keys_t, snapshot_vc)
                except ReadBelowBase:
                    closure = None  # whole batch from the log
                else:
                    stats.registry.read_dispatches.inc()
                    self._dev_readers += 1
                dev_batches.append((type_name, pairs, closure))
        return out, dev_batches

    def read_many_finish(self, out, dev_batches, snapshot_vc,
                         txid=None, got_map=None):
        """Second half of :meth:`read_many`: run (or accept) the device
        folds, post-process, warm the cache, and RELEASE the reader
        counts taken by read_many_begin.  ``got_map`` maps a batch's
        index to its already-computed {key: value} dict (the fused
        cross-partition path ran the fold); missing entries run their
        own closure here."""
        got_map = got_map or {}
        pending_readers = sum(1 for _t, _p, c in dev_batches
                              if c is not None)
        try:
            for bi, (type_name, pairs, closure) in enumerate(
                    dev_batches):
                if closure is None:
                    with self._lock:
                        for key, _fr, _ex in pairs:
                            out[(key, type_name)] = self._read_from_log(
                                key, type_name, snapshot_vc, txid)
                    continue
                try:
                    got = got_map[bi] if bi in got_map else closure()
                finally:
                    with self._lock:
                        self._dev_readers -= 1
                        pending_readers -= 1
                        self._lock.notify_all()
                cacheable = []
                with self._lock:
                    for key, fr, exact in pairs:
                        if key in got:
                            value = got[key]
                            if fr is not None and \
                                    self.key_frontier.get(key) is fr:
                                cacheable.append((key, fr, value, exact))
                        else:
                            # evicted during the begin-flush — host path
                            value = self._read_store(
                                key, type_name, snapshot_vc, txid)
                        out[(key, type_name)] = value
                    for key, fr, value, exact in cacheable:
                        self._cache_put(key, fr, value, exact)
                if type_name == "set_aw":
                    for key, _fr, _ex in pairs:
                        if key in got:
                            self._maybe_probe_set_aw(
                                key, type_name, snapshot_vc, txid,
                                got[key])
        finally:
            # an escaping exception must not leak the not-yet-drained
            # batches' reader counts: a leak would wedge
            # _wait_device_quiesce (and every publish) forever
            if pending_readers:
                with self._lock:
                    self._dev_readers -= pending_readers
                    self._lock.notify_all()
        return out

    # --------------------------------------------------------- checkpoint

    def maybe_checkpoint(self) -> None:
        """Watermark-gated checkpoint trigger, called at the tail of
        commit/apply_remote (outside the partition lock).  Cheap when
        not due; a failing checkpoint is logged and retried at the
        next watermark — it is a cost optimization and must never fail
        the commit that happened to trip it."""
        ck = self.log.ckpt
        if ck is None or not self.log.enabled:
            return
        s = ck.settings
        if self._ckpt_ops < s.every_ops:
            try:
                end = self.log.log.end_offset()
            except OSError:
                return  # closing
            if end - self._ckpt_last_end < s.every_bytes:
                return
        try:
            self.checkpoint_now()
        except Exception:  # noqa: BLE001 — see docstring
            log.exception("checkpoint of partition %d failed; will "
                          "retry at the next watermark", self.partition)
            # reset the counters so a persistent failure does not turn
            # into a checkpoint attempt per commit; a failure BECAUSE
            # the log is closing must not escape either (the commit
            # this rode on is already durable and published)
            self._ckpt_ops = 0
            try:
                self._ckpt_last_end = self.log.log.end_offset()
            except OSError:
                pass

    def checkpoint_now(self) -> Optional[dict]:
        """Cut + fold + persist one checkpoint for this partition
        (ISSUE 10): under the partition lock (readers quiesced — the
        device folds below swap donated buffers), capture the log cut,
        fold every key published since the previous cut — device-
        resident keys via ONE batched fold per type plane (the PR-8
        export machinery's read_many path), host keys via the
        materializer, state-lossy device folds via the exact log
        replay — and hand the document to the log for the atomic write
        (+ retention-gated truncation).  Returns the document, or None
        when checkpointing is disabled."""
        if self.log.ckpt is None or not self.log.enabled:
            return None
        t0 = time.perf_counter()
        with self._lock:
            if self._ckpt_inflight:
                # another thread is mid-checkpoint (its persist runs
                # outside this lock): reuse its document rather than
                # stacking writers — the inflight guard is also what
                # keeps documents landing on disk in cut order
                return self.log.ckpt_doc
            self._ckpt_inflight = True
        dirty: Dict[Any, str] = {}
        trunc: Optional[dict] = None
        try:
            with self._lock, \
                    tracer.span("ckpt_cut", "oplog",
                                partition=self.partition):
                # the cut asserts "everything below me is in the seed
                # fold": a deferred publish in flight (commit record
                # appended, effects not yet in the store) would break
                # that — its txn would land below the cut yet in
                # neither seed nor suffix.  Wait both quiescent; the
                # condition wait releases the lock, so the deferred
                # committers' publishes (and device readers) drain.
                while self._dev_readers or self._defer_unpublished:
                    self._lock.wait()
                doc = self.log.capture_cut()
                dirty, self._ckpt_dirty = self._ckpt_dirty, {}
                self._ckpt_fold(doc, dirty)
            # make the log durable UP TO the cut before the document
            # claims it: open-time recovery resumes validation at the
            # cut precisely because bytes below it are trusted durable
            # — a cut over page-cache-only bytes would skip validating
            # data a power loss corrupted.  Out of the partition lock,
            # like the persist (one extra fsync per checkpoint).
            self.log.log.sync()
            # the persist (pickle + double fsync + rename) runs OUT of
            # the partition lock — commits and reads proceed while the
            # document lands (the PR-8 no-fsync-under-the-lock lesson)
            self.log.persist_checkpoint(doc)
            # the truncation tail copy (possibly hundreds of retained
            # MB) stages OUT here too; only the bounded catch-up +
            # atomic rename runs under the lock inside adopt (ISSUE 11
            # — the ROADMAP "stage the rewrite out of the lock" item)
            trunc = self.log.stage_truncation(doc)
            with self._lock:
                # lock-ok: adopt redeems the staged truncation — the
                # BOUNDED half (catch-up of bytes appended during the
                # copy, atomic rename, directory fsync) runs under the
                # partition lock by design; the unbounded tail copy
                # already staged out above
                self.log.adopt_checkpoint(doc, trunc)
                self._ckpt_ops = 0
                self._ckpt_last_end = doc["cut_offset"]
            recorder.record("oplog", "ckpt_cut_done",
                            partition=self.partition,
                            keys=len(doc["keys"]), dirty=len(dirty),
                            dur_s=round(time.perf_counter() - t0, 4))
            return doc
        except BaseException:
            # a failed fold/write must NOT lose the dirty set: the
            # next (successful) checkpoint would carry these keys'
            # PREVIOUS-cut seeds while its cut moved past their ops —
            # re-folding them is what keeps seed+suffix exact.
            # Publishes during the failure window merged their own
            # entries; theirs win (newer).
            with self._lock:
                merged = dict(dirty)
                merged.update(self._ckpt_dirty)
                self._ckpt_dirty = merged
            if trunc is not None:
                # a stage that will never be committed wedges every
                # future truncation behind the in-flight flag — drop
                # it (idempotent no-op if the commit did land)
                self.log.abort_truncation(trunc)
            raise
        finally:
            with self._lock:
                self._ckpt_inflight = False
                self._lock.notify_all()

    def _ckpt_fold(self, doc: dict, dirty: Dict[Any, str]) -> None:
        """Fold the dirty keys into ``doc`` (the capture half of
        :meth:`checkpoint_now`); runs under self._lock with device
        readers quiesced.  Under ``ckpt_segmented`` the freshly folded
        dirty entries ALSO land in ``doc["delta"]`` — the only part
        the segmented persist serializes (O(churn)); the carried seeds
        ride forward as shared references, never re-copied."""
        prev_doc = self.log.ckpt_doc
        segmented = (self.log.ckpt is not None
                     and self.log.ckpt.settings.segmented)
        if segmented and prev_doc is not None:
            # pointer-copy the previous merged map: entries are
            # immutable (tn, state, vc-dict) tuples, and re-copying
            # every VC per cut was itself an O(keyspace) term
            keys = dict(prev_doc["keys"])
        else:
            # carry the previous cut's seeds forward; re-fold only the
            # dirty keys (the incremental economy)
            keys = {k: (tn, state, dict(vc))
                    for k, (tn, state, vc) in self.log.ckpt_seeds.items()}
        clock = VC(prev_doc["clock"]) if prev_doc else VC()
        by_type: Dict[str, list] = {}
        host_items = []
        for key, tn in dirty.items():
            if self.device is not None \
                    and self.device.owns(tn, key) \
                    and self.device.state_exact(tn, key):
                by_type.setdefault(tn, []).append(key)
            else:
                host_items.append((key, tn))
        folded: Dict[Any, Tuple[str, Any]] = {}
        for tn, ks in by_type.items():
            got = self.device.read_many(ks, tn, None)
            for k in ks:
                if k in got:
                    folded[k] = (tn, got[k])
                else:  # evicted mid-flush: host path below
                    host_items.append((k, tn))
        for key, tn in host_items:
            if self.device is not None and self.device.owns(tn, key):
                # STATE_LOSSY fold (set_rw/flag_dw/lossy maps): a
                # collapsed state seeded into the host store would
                # feed downstream generation and under-cancel at
                # exact replicas — replay the (still complete) log
                # instead; exact by construction
                folded[key] = (tn, self._read_from_log(key, tn, None))
            else:
                folded[key] = (tn, self.store.read(key, tn, None)[0])
        delta: Dict[Any, tuple] = {}
        for key, (tn, state) in folded.items():
            fr = self.key_frontier.get(key) or VC()
            ent = (tn, state, dict(fr))
            keys[key] = ent
            delta[key] = ent
            clock = clock.join(fr)
        doc["keys"] = keys
        if segmented:
            # a previous MONOLITHIC document's carried seeds live in
            # no segment — the first segmented cut after a knob flip
            # must persist the full set or they would silently vanish
            # from the manifest's merge
            doc["delta"] = keys if (prev_doc is not None
                                    and "segments" not in prev_doc) \
                else delta
        doc["clock"] = dict(clock)

    def install_ckpt_seeds(self) -> set:
        """Boot-time half of checkpoint recovery: install every seed
        into its materializer plane BEFORE the suffix replay applies
        the ops past the cut on top; must run under self._lock.
        Returns the keys whose seeding EVICTED to the host mid-install
        — their migration already replayed seed + suffix, so the
        caller's suffix replay must skip (not re-publish) them.

        ISSUE 13: seeds of types the device plane can re-ingest
        (DevicePlane.seed_state — the folded state decoded back into
        plane rows, uploaded through the packed ingest path) go back
        DEVICE-resident, then fold into the device base at the
        checkpoint clock, so a restarted node re-earns its device
        economy instead of serving every previously device-resident
        key host-path forever (the PR-9 remainder).  Types with no
        state→effect decoding (maps, RGA, the STATE_LOSSY collapses)
        keep the host seeding exactly as before; so does a key a
        capacity miss evicts mid-seed (its eviction already migrated
        the checkpoint seed to the host store)."""
        if not self.log.ckpt_seeds:
            return set()
        pre_hosted = set(self.device.host_only) \
            if self.device is not None else set()
        host_seeded: set = set()
        dev_clocks: Dict[str, VC] = {}
        for key, (tn, state, vc) in self.log.ckpt_seeds.items():
            if self.device is not None \
                    and self.device.seed_state(key, tn, state, vc):
                dev_clocks[tn] = dev_clocks.get(tn, VC()).join(vc)
            elif not (self.device is not None
                      and key in self.device.host_only):
                # host path; mid-seed evictions (host_only) already
                # seeded via their migration's checkpoint replay
                self.store.seed_state(key, tn, state, vc)
                host_seeded.add(key)
                if self.device is not None:
                    self.device.host_only.add(key)
            self.key_frontier[key] = (
                self.key_frontier.get(key) or VC()).join(vc)
        # fold the staged seed rows into each plane's device base at
        # that plane's seed-clock join: the base VC then gates reads
        # below a seed's frontier to the exact log-replay path — the
        # device twin of HostStore seed replay-gating.  Per PLANE, not
        # the document clock: seed_state interns every accepted
        # frontier's DC columns up front (bottom-state seeds
        # included), so the fold can never miss on a column-capacity
        # check and leave seeds un-gated.
        for tn, ck in dev_clocks.items():
            self.device.planes[tn].gc(ck)
        # keys a capacity/overflow eviction migrated DURING seeding:
        # their migration replayed checkpoint seed + retained suffix
        # into the host store, so the caller's suffix replay must SKIP
        # their payloads (publishing them again would double-apply) —
        # exactly the live _mid_batch_migrated contract
        migrated = set()
        if self.device is not None:
            migrated = (set(self.device.host_only) - pre_hosted
                        - host_seeded)
        return migrated

    def ckpt_bootstrap_answer(self, own_dc) -> Optional[dict]:
        """Server side of the CKPT_READ inter-DC query (a remote
        SubBuf whose gap repair hit BELOW_FLOOR): cut a FRESH
        checkpoint — the freshest cut both maximizes the watermark the
        requester jumps to and is exactly as cheap as the dirty set —
        and answer with the seeds + clocks.  None when checkpointing
        is off (the requester keeps buffering and retries)."""
        doc = self.checkpoint_now()
        if doc is None:
            return None
        return {
            "keys": dict(doc["keys"]),
            "clock": dict(doc["clock"]),
            "commit_opid": doc["commit_watermarks"].get(own_dc, 0),
            "op_counter": doc["op_counters"].get(own_dc, 0),
        }

    def bootstrap_seed(self, items, origin_dc=None, op_counter=0
                       ) -> None:
        """Receiver side of a checkpoint bootstrap: install the
        origin's seed states as MERGE bases.  A key the device plane
        owns evicts to the host first (migrating its local history),
        then the seed lands with ``base_op_id=0`` so every local op
        NOT covered by the seed's VC re-applies on top — local
        concurrent writes survive, ops the origin had already folded
        are replay-gated by the VC.  ``items``: iterable of
        (key, type_name, state, VC)."""
        with self._lock:
            self._wait_device_quiesce()
            for key, tn, state, vc in items:
                if self.device is not None and self.device.owns(tn, key):
                    self.device.planes[tn].evict(key)
                self.store.seed_state(key, tn, state, vc, base_op_id=0)
                self.key_frontier[key] = (
                    self.key_frontier.get(key) or VC()).join(vc)
                self._val_cache.pop(key, None)
                self._ckpt_dirty[key] = tn
            if origin_dc is not None:
                self.log.op_counters[origin_dc] = max(
                    self.log.op_counters.get(origin_dc, 0),
                    int(op_counter))
            self._lock.notify_all()

    # ------------------------------------------------------- stable plane

    def has_prepared(self) -> bool:
        """True while any transaction holds a prepare on this partition
        (the cross-node handoff drain waits for this to clear)."""
        with self._lock:
            return bool(self.prepared)

    def min_prepared(self) -> int:
        """Min prepare time of in-flight txns (caps the stable time so a
        snapshot never passes a pending commit; reference get_min_prep,
        src/clocksi_vnode.erl:671-678)."""
        with self._lock:
            if self.prepared:
                return min(pt for pt, _ in self.prepared.values())
            return self.clock.now_us()

    def value_snapshot(self, key, type_name: str,
                       clock: Optional[VC] = None) -> Any:
        """Committed value at ``clock`` (None = latest) without Clock-SI
        gating (get_objects path); store access under the partition lock."""
        with self._lock:
            self._read_check()
            return self._read_store(key, type_name, clock)


def read_many_fused(groups, snapshot_vc, txid=None
                    ) -> Dict[Tuple[Any, str], Any]:
    """Multi-partition batched read with per-CHIP device dispatch:
    ``groups`` is [(pm, items)] over LOCAL partitions; every captured
    device fold landing on the same chip runs in ONE XLA program
    (mat/device_plane.fused_read), so a read spanning P ring-placed
    partitions issues at most n_devices * n_types programs instead of
    P * n_types (round-4 verdict item 4: per-partition dispatch won't
    scale to the 256-partition configs).  On a single-device node this
    degenerates to one program for the whole read — strictly fewer
    dispatches than the per-partition loop it replaces.

    Begin/run/finish are split so reader counts stay balanced on every
    path: each partition's read_many_begin increments its counts, and
    read_many_finish (which always runs, fused result or not) releases
    them."""
    from antidote_tpu.mat.device_plane import (collective_guard,
                                               fused_read)

    begun = []  # (pm, out, dev_batches)
    try:
        for pm, items in groups:
            out, dev_batches = pm.read_many_begin(items, snapshot_vc,
                                                  txid)
            begun.append((pm, out, dev_batches))
    except BaseException:
        # release the already-begun partitions' reader counts (their
        # closures run un-fused; results discarded)
        for pm, out, dev_batches in begun:
            try:
                pm.read_many_finish(out, dev_batches, snapshot_vc, txid)
            except Exception:  # noqa: BLE001 — original error wins
                pass
        raise
    # group fusible captures by chip.  BaseException here (interrupt
    # mid-fuse) must still fall through to the finish loop below —
    # every begun partition's reader counts are released there.
    results: Dict[Tuple[int, int], dict] = {}
    err = None
    try:
        by_dev: Dict[Any, list] = {}
        for gi, (_pm, _out, batches) in enumerate(begun):
            for bi, (_t, _pairs, closure) in enumerate(batches):
                split = getattr(closure, "split", None) \
                    if closure is not None else None
                if split is not None:
                    by_dev.setdefault(
                        getattr(closure, "device", None), []).append(
                            (gi, bi, split))
        for dev, entries in by_dev.items():
            if len(entries) < 2 or dev is None:
                continue  # a lone fold dispatches itself in finish
            try:
                # ``dev`` is the Mesh handle when the partitions are
                # pod-sharded (every sharded plane reports the same
                # mesh, so the whole read is ONE multi-chip program)
                # — which must serialize on COLLECTIVE_LOCK
                with collective_guard(dev):
                    outs = fused_read([s for _gi, _bi, s in entries])
            except Exception:  # noqa: BLE001 — per-fold fallback
                log.exception("fused cross-partition read failed; "
                              "falling back to per-partition folds")
                continue
            for (gi, bi, _s), got in zip(entries, outs):
                results[(gi, bi)] = got
    except BaseException as e:  # noqa: BLE001 — re-raised below
        err = e
    merged: Dict[Tuple[Any, str], Any] = {}
    for gi, (pm, out, batches) in enumerate(begun):
        got_map = {bi: results[(gi, bi)]
                   for bi in range(len(batches))
                   if (gi, bi) in results}
        # EVERY begun partition's finish must run (it releases the
        # reader counts begin took) — a failing partition must not
        # leak its successors' counts; first error re-raises after
        try:
            merged.update(pm.read_many_finish(
                out, batches, snapshot_vc, txid, got_map))
        except BaseException as e:  # noqa: BLE001 — re-raised below
            if err is None:
                err = e
    if err is not None:
        raise err
    return merged

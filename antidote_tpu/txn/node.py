"""A DC node: partitions + clocks + coordinator wiring.

The single-node assembly of what the reference spreads over riak_core
vnodes and supervisors (reference src/antidote_app.erl:42-59,
src/antidote_sup.erl:136-158): N partition managers (each owning a
durable log + materializer store), a node clock, the hook registry, and
the stable-snapshot source.  Key placement mirrors
log_utilities:get_key_partition (reference src/log_utilities.erl:75-118):
integer keys map by modulo, everything else by hash.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Any, Callable, List, Optional, Tuple

from antidote_tpu.clocks import VC
from antidote_tpu.config import Config
from antidote_tpu.hooks import HookRegistry
from antidote_tpu.oplog.log import _fsync_dir
from antidote_tpu.oplog.partition import PartitionLog
from antidote_tpu.oplog.records import LogRecord, commit_certified
from antidote_tpu.txn.clock import HybridClock
from antidote_tpu.txn.coordinator import Coordinator
from antidote_tpu.txn.manager import PartitionManager


class TxnGate:
    """Node-level shared/exclusive gate for live handoff.

    Transactions hold the gate SHARED from their first mutation (or for
    the span of a read batch) to commit/abort; a live repartition's
    cutover takes it EXCLUSIVE, which drains every in-flight
    transaction and briefly blocks new ones.  Reader-preference while
    no exclusive is pending; once one is pending, only transactions
    that already hold the gate proceed (a blocked new transaction can
    retry) — holders must be able to finish or the drain deadlocks."""

    def __init__(self):
        self._cond = threading.Condition()
        self._active = 0
        self._blocking = False
        #: cluster-resize freeze — its OWN flag, not _blocking: a
        #: handoff cutover's exclusive() releases _blocking on exit,
        #: and that must never reopen a gate the resize froze (the
        #: member would admit transactions at the old partition width
        #: through the resize barrier)
        self._frozen = False

    def enter(self, timeout: float = 30.0) -> None:
        with self._cond:
            deadline = time.monotonic() + timeout
            while self._blocking or self._frozen:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    raise TimeoutError(
                        "transaction admission blocked by a cutover")
            self._active += 1

    def exit(self) -> None:
        with self._cond:
            self._active -= 1
            if self._active <= 0:
                self._cond.notify_all()

    def freeze(self) -> None:
        """Close the gate to NEW transactions WITHOUT draining — the
        cluster-resize barrier's first half: every member freezes, the
        in-flight transactions (including their remote 2PC legs, which
        the members still serve) run to completion, then wait_idle
        confirms the global drain.  Stays frozen until unfreeze()
        (persisted across a crash by the caller's resize marker);
        composes with exclusive() — a cutover finishing during the
        freeze must not reopen the gate."""
        with self._cond:
            self._frozen = True

    def unfreeze(self) -> None:
        with self._cond:
            self._frozen = False
            self._cond.notify_all()

    def wait_idle(self, timeout: float = 60.0) -> None:
        """Block until no transaction holds the gate (call after
        freeze(); a frozen gate admits nobody new, so idle is a
        barrier, not a race)."""
        with self._cond:
            deadline = time.monotonic() + timeout
            while self._active:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    raise TimeoutError(
                        "in-flight transactions never drained")

    def exclusive(self, drain_timeout: float = 60.0):
        gate = self

        class _Exclusive:
            def __enter__(self):
                with gate._cond:
                    if gate._blocking:
                        raise RuntimeError("cutover already in progress")
                    if gate._frozen:
                        raise RuntimeError(
                            "gate frozen by a cluster resize; no "
                            "cutover may start until it finishes")
                    gate._blocking = True
                    deadline = time.monotonic() + drain_timeout
                    while gate._active:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0 or not gate._cond.wait(
                                remaining):
                            gate._blocking = False
                            gate._cond.notify_all()
                            raise TimeoutError(
                                "in-flight transactions never drained")
                return self

            def __exit__(self, *exc):
                with gate._cond:
                    gate._blocking = False
                    gate._cond.notify_all()
                return False

        return _Exclusive()


def resize_journal_path(data_dir: str, dc_id) -> str:
    """The ring-resize journal's location — ONE owner for the name:
    Node's crash recovery (_resume_interrupted_resize) and the cluster
    restart reconciliation (cluster/node.py _reconcile_resized_plan)
    must read the same file or a mid-resize crash recovers a width
    the persisted plan disagrees with."""
    return os.path.join(data_dir, f"{dc_id}_resize.journal")


def read_resize_journal(path: str):
    """(old_n, new_n) from a resize journal, or None if absent."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        old_n, new_n = (int(x) for x in f.read().split())
    return old_n, new_n


class LiveFold:
    """Incremental committed-group fold from live partition logs into
    staged resize logs — the riak_core handoff fold running while the
    vnode keeps serving (reference src/logging_vnode.erl:781-812),
    shared by the single-node live resize (Node.repartition_live) and
    the cluster-wide resize (each member folds its LOCAL slice,
    cluster/node.py resize_cluster).

    Emission safety: a transaction's update records always precede its
    FIRST commit copy in wall order (stage -> prepare -> commit), so
    any commit seen by pass k has all its updates below pass k+1's
    cursors — groups emit one pass after their commit is first seen,
    and the quiesced final pass emits the rest.

    ISSUE 19 (checkpoint-seeded fold): a source partition carrying a
    checkpoint starts its cursor AT THE CUT instead of 0 — the
    below-cut history rides as routed seed states in the staged re-cut
    checkpoints (Node.build_resize_fold), and ``prefeed`` injects the
    cut-crossing pending update records so suffix commits reassemble.
    ``post_fold`` runs inside final_pass after the staged logs close
    (where the re-cut checkpoints stage); ``on_done`` runs exactly
    once on final_pass OR discard (truncation-hold release)."""

    def __init__(self, parts, new_logs, route, cursors=None,
                 prefeed=None, post_fold=None, on_done=None):
        #: [(global index, PartitionManager)] — the logs folded FROM
        self.parts = list(parts)
        #: {global new index: PartitionLog} — the staged logs folded TO
        self.new_logs = dict(new_logs)
        #: key -> global new partition index
        self.route = route
        self.cursors = {p: 0 for p, _pm in self.parts}
        if cursors:
            self.cursors.update(cursors)
        self._updates: dict = {}   # txid -> [update records]
        self._commits: dict = {}   # txid -> commit record (first wins)
        self._ready: list = []     # commit order, not yet emitted
        self._emitted: set = set()
        for rec in (prefeed or ()):
            # cut-crossing pending updates: staged below a seeded
            # source's cut, commit lands in the suffix the cursors scan
            if rec.kind() == "update":
                self._updates.setdefault(rec.txid, []).append(rec)
        self.post_fold = post_fold
        self.on_done = on_done
        self._done = False

    def _release(self) -> None:
        if not self._done and self.on_done is not None:
            self.on_done()
        self._done = True

    def scan_pass(self) -> int:
        """One cursor pass over every live log; returns the number of
        new records seen."""
        seen = 0
        for p, pm in self.parts:
            def scan(log, _p=p):
                # byte cursors: records(offset) scans from a FILE
                # offset, and under the partition lock nothing appends
                # between the iteration and end_offset()
                new = list(log.records(offset=self.cursors[_p]))
                self.cursors[_p] = log.log.end_offset()
                return new
            for rec in pm.scan_log(scan):
                seen += 1
                kind = rec.kind()
                if kind == "update":
                    self._updates.setdefault(rec.txid, []).append(rec)
                elif kind == "commit" and rec.txid not in self._commits \
                        and rec.txid not in self._emitted:
                    self._commits[rec.txid] = rec
                    self._ready.append(rec.txid)
        return seen

    def _emit(self, txids) -> None:
        for txid in txids:
            rec = self._commits.pop(txid)
            dests: dict = {}
            for u in self._updates.pop(txid, ()):
                dests.setdefault(self.route(u.payload[1]), []).append(u)
            (dc, ct) = rec.payload[1]
            svc = rec.payload[2]
            cert = commit_certified(rec.payload)
            for q, ups in dests.items():
                lg = self.new_logs[q]
                for u in ups:
                    lg.append_update(dc, txid, u.payload[1],
                                     u.payload[2], u.payload[3])
                lg.append_commit(dc, txid, ct, svc, certified=cert)
            self._emitted.add(txid)

    def serve_passes(self, max_passes: int, delta_threshold: int
                     ) -> None:
        """Phase 1 — fold toward the live frontier while serving:
        passes shrink as clients keep committing; stop once a pass
        sees at most ``delta_threshold`` new records."""
        self.scan_pass()
        for _ in range(max_passes):
            emittable, self._ready = self._ready, []
            seen = self.scan_pass()
            # commits collected before this pass now have every update
            # below the cursors — safe to emit
            self._emit(emittable)
            if seen <= delta_threshold:
                break

    def final_pass(self) -> None:
        """Phase 2 — with the gate held (no appenders), fold the
        remainder and close the staged logs.  Dangling updates without
        commits are aborted/in-doubt transactions — they do not
        survive the resize."""
        self.scan_pass()
        self._emit(self._ready)
        self._ready = []
        for lg in self.new_logs.values():
            lg.close()
        if self.post_fold is not None:
            self.post_fold(self)
        self._release()

    def discard(self) -> None:
        """Abort-before-swap: close and DELETE the staged child logs.
        An aborted resize must not leave half-folded files on disk —
        a re-driven prepare rebuilds them from scratch anyway."""
        for lg in self.new_logs.values():
            try:
                lg.close()
            except Exception:  # noqa: BLE001 — already closed
                pass
            try:
                os.remove(lg.path)
            except OSError:
                pass
        self.new_logs.clear()
        self._release()


class Node:
    def __init__(self, dc_id="dc1", config: Optional[Config] = None,
                 data_dir: Optional[str] = None,
                 on_log_append: Optional[Callable] = None):
        self.dc_id = dc_id
        self.config = config or Config()
        self.clock = HybridClock()
        self.hooks = HookRegistry()
        # push only explicitly-set observability knobs into the
        # process-global tracer/recorder/probe (shared by every DC in
        # the process, like stats.registry): a later Node built with a
        # default Config must not silently revert the sample rate or
        # disarm the probe another DC configured.  The globals START
        # from the same Config defaults (obs/spans.py, obs/probe.py),
        # so skipping the push is lossless; the one blind spot is a
        # Node explicitly setting a knob BACK to the default after
        # another DC changed it — use obs.configure() directly for that
        from antidote_tpu import obs

        _obs_defaults = Config()
        obs.configure(**{kw: v for kw, v, d in (
            ("sample_rate", self.config.trace_sample_rate,
             _obs_defaults.trace_sample_rate),
            ("capacity", self.config.trace_capacity,
             _obs_defaults.trace_capacity),
            ("dump_dir", self.config.flight_recorder_dir,
             _obs_defaults.flight_recorder_dir),
            ("selfcheck_set_aw", self.config.obs_selfcheck_set_aw,
             _obs_defaults.obs_selfcheck_set_aw),
            ("kernel_profile", self.config.kernel_profile,
             _obs_defaults.kernel_profile),
        ) if v != d})
        from antidote_tpu.txn.manager import DeviceFlusher

        #: background group-commit flusher shared by this node's
        #: partitions (see Config.device_async_flush)
        self._flusher = DeviceFlusher()
        base = data_dir or self.config.data_dir
        os.makedirs(base, exist_ok=True)
        self.data_dir = base
        self._on_log_append = on_log_append
        self._resume_interrupted_resize()
        self.partitions: List[PartitionManager] = [
            self._build_partition(p)
            for p in range(self.config.n_partitions)
        ]
        #: provider of the gossiped stable snapshot (set by the meta
        #: plane / inter-DC layer).  The single-DC default is the node's
        #: own min-prepared time: no future local commit can fall below
        #: it, so it is a safe GC horizon and a valid (own-entry-only)
        #: stable snapshot.
        self.stable_vc_provider: Callable[[], VC] = (
            lambda: VC({dc_id: self.min_prepared_vc()}))
        #: ring-placed node over a real mesh: the stable fold itself is
        #: a device collective (rows co-located with the partitions'
        #: planes, GST = cross-chip pmin — meta/device_stable.py; the
        #: reference's gossip fold, src/meta_data_sender.erl:224-255).
        #: Higher layers (DataCenter, NodeServer) install richer
        #: trackers over the same mechanism via make_stable_tracker.
        self.stable_tracker = None
        self._install_device_stable()
        #: (monotonic time, VC) pair backing stable_vc()'s TTL cache
        self._stable_read_cache = (0.0, None)
        #: called inside causal clock-wait spins; the inter-DC layer
        #: points this at its inbound pump so waiting makes progress
        self.wait_hook: Callable[[], None] = lambda: time.sleep(0.002)
        self.coordinator = Coordinator(self)
        #: optional detour for bounded-counter downstream generation
        #: (reference clocksi_downstream's bcounter_mgr hop)
        self.bcounter_mgr = None
        #: shared/exclusive gate live handoff cuts over under
        self.txn_gate = TxnGate()
        if self.config.recover_from_log:
            self._recover_stores()

    def _install_device_stable(self) -> None:
        """Serve this node's OWN stable fold from the device mesh when
        the data plane is ring-placed over multiple chips: each local
        partition's row (own min-prepared — the single-node default
        provider's quantity) lives on the partition's chip and the GST
        is a cross-chip pmin (meta/device_stable.py).  Skipped when a
        higher layer will install its own provider anyway for slices
        this process doesn't own (ClusterNode), or with <2 devices."""
        if not (self.config.device_store
                and self.config.device_placement == "ring"):
            return
        if any(not isinstance(pm, PartitionManager)
               for pm in self.partitions):
            return  # cluster member: NodeServer wires the plane
        import jax

        devs = jax.devices()
        if len(devs) < 2:
            return
        from antidote_tpu.meta.device_stable import (
            DeviceStableTimeTracker,
        )

        trk = DeviceStableTimeTracker(
            self.dc_id, self.config.n_partitions, devs)
        dc_id = self.dc_id
        trk.sources = [
            (lambda _pm=pm: VC({dc_id: _pm.min_prepared()}))
            for pm in self.partitions
        ]
        self.stable_tracker = trk
        self.stable_vc_provider = trk.get_stable_snapshot

    # ------------------------------------------------------------ elasticity

    def repartition(self, new_n: int) -> None:
        """Ring resize: redistribute every committed transaction across
        ``new_n`` partitions and rebuild the materializer planes — the
        riak_core handoff fold duty (reference logging_vnode.erl:781-812
        folds the log, materializer_vnode.erl:221-246 folds the cache
        across a vnode move), generalized to a resize the reference's
        fixed ring cannot do.

        Requires a quiesced node (no in-flight transactions).  The fold
        collects every committed transaction across ALL old logs (a txn
        that spanned old partitions reassembles into one group), then
        replays each group once: updates route to their key's new
        owner, each participating new partition gets its own commit
        copy — the same per-participant commit layout the live protocol
        writes — and EVERY origin's stream is renumbered densely on its
        new partitions.  Dense renumbering is what keeps inter-DC
        watermarks meaningful after a whole-federation resize: two DCs
        folding the same replicated history produce the same per-origin
        record multiset per new partition, hence identical stream
        counts, so reseeded sub/sender watermarks agree (tested by the
        resize-rejoin case in tests/multidc/test_elasticity.py).
        Materializer state (host + device planes) is rebuilt by the
        standard recovery replay — handoff IS recovery from a
        redistributed log.

        ISSUE 19: partitions carrying a checkpoint fold SEEDED instead
        (seeds route to the new slots, only the suffix past the cut
        replays — O(delta), truncated logs accepted); their streams
        renumber from the checkpoint bases and the new slots are marked
        ``renumbered``, which the inter-DC layer re-bases through a
        checkpoint bootstrap at the next federation handshake.  The
        fold itself is the shared LiveFold machinery — on a quiesced
        node the single final pass IS the whole fold, emitting exactly
        the record sequence the pre-ISSUE-19 in-line fold wrote."""
        if new_n < 1:
            raise ValueError(f"new_n must be >= 1, got {new_n}")
        old_parts = self.partitions
        for pm in old_parts:
            with pm._lock:
                if pm.prepared or pm._staged:
                    raise RuntimeError(
                        "repartition requires a quiesced node "
                        "(in-flight transactions present)")
        old_n = self.config.n_partitions
        if new_n == old_n:
            return
        if not self.config.enable_logging:
            raise RuntimeError(
                "repartition folds the durable logs; enable_logging=False "
                "leaves nothing to redistribute")

        fold = self.build_resize_fold(new_n)
        fold.final_pass()

        # 3. journaled swap: the per-file renames are not atomic as a
        #    group, so a journal marks the transition — a crash mid-swap
        #    resumes it at the next boot (_complete_resize_swap) instead
        #    of silently booting with empty/mixed logs
        for pm in old_parts:
            pm.log.close()
        journal = self._resize_journal_path()
        tmp = journal + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{old_n} {new_n}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, journal)
        # the journal IS the commit point of the whole swap: pin its
        # rename before acting on it (ISSUE 15 — a resurrected
        # pre-journal dir after a power cut would boot the old width
        # over already-swapped logs)
        _fsync_dir(self.data_dir, instant="resize_journal_fsync")
        self._complete_resize_swap(old_n, new_n)

        # 4. rebuild partitions + materializer via standard recovery
        self.config.n_partitions = new_n
        self.partitions = [self._build_partition(p)
                           for p in range(new_n)]
        self._recover_stores()
        if self.stable_tracker is not None:
            self._install_device_stable()  # re-aim rows at the new ring

    def sweep_staged_resize(self) -> None:
        """Delete every staged ``.resize`` child log in this node's
        data dir — the abort-path sweep for attempts that died before
        the current process held a fold object.  Lives here so the
        staged-log naming (``_log_path(p) + ".resize"``, also used by
        build_resize_fold and _complete_resize_swap) has ONE owner."""
        import glob as _glob

        for f in (_glob.glob(os.path.join(self.data_dir, "*.resize"))
                  + _glob.glob(os.path.join(self.data_dir,
                                            "*.resize.seg-*"))):
            try:
                os.remove(f)
            except OSError:
                pass

    def _refuse_truncated_resize(self) -> None:
        """Legacy guard name (PR 9) kept for its callers/tests: now
        delegates to the fold-source decision — a truncated log only
        refuses when the checkpoint-seeded path (ISSUE 19) cannot
        serve it."""
        self._fold_sources()

    def _fold_sources(self) -> dict:
        """Per local old partition index: the checkpoint document a
        SEEDED fold starts from, or None for the legacy full-history
        fold from offset 0 (ISSUE 19).  The seeded path engages
        whenever the partition carries a live checkpoint and
        ``Config.resize_from_ckpt`` allows it — which is also what
        makes a TRUNCATED log resizable: its reclaimed prefix lives in
        the seeds.  A truncated partition with no usable checkpoint
        refuses loudly (the pre-ISSUE-19 behavior): a full-history
        fold would silently lose the reclaimed records."""
        seeded_ok = getattr(self.config, "resize_from_ckpt", True)
        out: dict = {}
        for p, pm in enumerate(self.partitions):
            if not isinstance(pm, PartitionManager) \
                    or not pm.log.enabled:
                continue
            doc = pm.log.ckpt_doc \
                if (seeded_ok and pm.log.ckpt is not None) else None
            if doc is None and pm.log.log.truncated_base > 0:
                raise RuntimeError(
                    f"partition {pm.partition}'s log is truncated "
                    "below its checkpoint cut and no checkpoint-"
                    "seeded fold is available (Config.resize_from_"
                    "ckpt off, or the checkpoint is missing/torn); "
                    "a full-history fold would lose the reclaimed "
                    "records — refusing the resize")
            out[p] = doc
        return out

    def build_resize_fold(self, new_n: int, own_slot=None) -> LiveFold:
        """LiveFold from this process's partitions toward width
        ``new_n``.  ``own_slot(q) -> bool`` restricts the staged logs
        to the slots this process will own — a single-process node
        stages all of them; ClusterNode passes its ring-slice filter
        (cluster/node.py).

        ISSUE 19 — the seeded/legacy routing's ONE home: partitions
        with a checkpoint fold from its seeds + suffix (cursor starts
        at the cut, truncated logs accepted); the rest fold the full
        history bit-for-bit.  When any source folds seeded, the fold's
        final pass also stages one re-cut checkpoint per staged slot
        (seeds routed by the new ring, counters/floors at the joined
        checkpoint base, ``renumbered`` set) — nothing is live until
        the resize journal commits and _complete_resize_swap renames
        the staged manifest in, so a crash mid-resize leaves the old
        ring's checkpoints authoritative."""
        from antidote_tpu.oplog.checkpoint import (
            ckpt_from_config,
            discard_staged_resize_checkpoint,
            empty_doc,
            stage_resize_checkpoint,
        )

        parts = [(p, pm) for p, pm in enumerate(self.partitions)
                 if isinstance(pm, PartitionManager)]
        by_p = dict(parts)
        held: list = []
        # pin EVERY source's log before classifying seeded/full: an
        # auto-checkpoint adopted mid-fold (live resizes serve while
        # folding) must not truncate records a cursor has not scanned
        # yet — for a FULL-fold source the reclaimed prefix lives only
        # in a checkpoint this fold ignores and the swap deletes, so
        # an unheld mid-fold cut is silent data loss.  Held for the
        # fold's whole life; released via on_done (final_pass OR
        # discard, whichever happens)
        for _p, pm in parts:
            with pm._lock:
                pm.log.hold_truncation()
                held.append(pm.log)
        try:
            sources = self._fold_sources()
        except BaseException:
            for lg in held:
                lg.release_truncation()
            raise
        new_logs = {}
        for q in range(new_n):
            if own_slot is not None and not own_slot(q):
                continue
            path = self._log_path(q) + ".resize"
            if os.path.exists(path):
                os.remove(path)
            # a staged re-cut checkpoint from an earlier attempt that
            # died pre-journal must not survive into this fold: the
            # eventual swap would install it over logs it never
            # described
            discard_staged_resize_checkpoint(
                self._log_path(q) + ".ckpt")
            new_logs[q] = PartitionLog(path, partition=q,
                                       sync_on_commit=False,
                                       enabled=True)
        seeded = {p: doc for p, doc in sources.items()
                  if doc is not None}
        cursors: dict = {}
        prefeed: list = []
        base: dict = {}
        clock: dict = {}
        max_vc: dict = {}
        seeds_by_slot: dict = {}
        moved = 0
        for p in sorted(seeded):
            pm = by_p[p]
            # the cut is pinned (truncation held above); re-read the
            # doc under the partition lock so the cursor below starts
            # at the SAME cut the seeds came from, even if a fresh
            # checkpoint was adopted since _fold_sources looked
            with pm._lock:
                doc = pm.log.ckpt_doc
            seeded[p] = doc
            cursors[p] = doc["cut_offset"]
            prefeed.extend(LogRecord.from_bytes(rb)
                           for _txid, _off, rb in doc["pending"])
        if seeded:
            # per-origin numbering base for every staged slot: the
            # join of the contributing cuts' op counters.  The suffix
            # replay renumbers densely from base+1, and base itself
            # fences the seed-covered history behind BELOW_FLOOR
            # (re-cut repair_floors below) — a repair request under it
            # has no bytes to answer from in the new numbering
            for doc in seeded.values():
                for o, n in doc["op_counters"].items():
                    base[o] = max(base.get(o, 0), n)
                for o, t in doc.get("clock", {}).items():
                    clock[o] = max(clock.get(o, 0), t)
                for o, t in doc["max_commit_vc"].items():
                    max_vc[o] = max(max_vc.get(o, 0), t)
            seeds_by_slot = {q: {} for q in new_logs}
            for p, doc in seeded.items():
                for key, entry in doc["keys"].items():
                    q = self.partition_index(key, new_n)
                    if q not in seeds_by_slot:
                        raise RuntimeError(
                            f"seed key {key!r} of partition {p} "
                            f"routes to slot {q}, which this fold "
                            "does not stage — sliced-fold ownership "
                            "mismatch")
                    seeds_by_slot[q][key] = entry
                    moved += 1
            for lg in new_logs.values():
                # appended suffix records number densely from base+1
                lg.op_counters.update(base)
        t0 = time.perf_counter()

        def release():
            for lg in held:
                lg.release_truncation()

        def post_fold(fold: LiveFold) -> None:
            from antidote_tpu import stats as _stats

            reg = _stats.registry
            reg.reshard_resizes.inc()
            reg.reshard_duration.observe(time.perf_counter() - t0)
            reg.reshard_replayed_txns.inc(len(fold._emitted))
            reg.reshard_full_fold_slots.inc(len(sources) - len(seeded))
            if not seeded:
                return
            reg.reshard_seeded_slots.inc(len(seeded))
            reg.reshard_moved_keys.inc(moved)
            cks = ckpt_from_config(self.config)
            for q in fold.new_logs:
                doc_q = empty_doc(q)
                doc_q["op_counters"] = dict(base)
                doc_q["max_commit_vc"] = dict(max_vc)
                doc_q["commit_watermarks"] = dict(base)
                doc_q["repair_floors"] = dict(base)
                doc_q["op_floors"] = dict(base)
                doc_q["keys"] = seeds_by_slot[q]
                doc_q["clock"] = dict(clock)
                # this slot's stream numbering diverged from any
                # peer's independent fold of the same history: the
                # inter-DC layer must re-base through a checkpoint
                # bootstrap, never trust local counters as watermarks
                doc_q["renumbered"] = True
                stage_resize_checkpoint(
                    self._log_path(q) + ".ckpt", doc_q, cks)

        # a key routed outside new_logs KeyErrors in the emit — a
        # correctness assert for sliced folds, not a silent drop
        return LiveFold(parts, new_logs,
                        lambda k: self.partition_index(k, new_n),
                        cursors=cursors, prefeed=prefeed,
                        post_fold=post_fold, on_done=release)

    def repartition_live(self, new_n: int, max_passes: int = 6,
                         delta_threshold: int = 256) -> None:
        """Ring resize WHILE SERVING — riak_core's handoff-under-traffic
        duty (reference logging_vnode handoff folds run while the vnode
        keeps serving, src/logging_vnode.erl:781-812).

        Phases:
        1. *Incremental fold (serving)*: repeated passes copy committed
           transaction groups from the live logs into staged new logs;
           each pass only scans the records appended since the last
           (per-partition cursors), so passes shrink toward the live
           frontier while clients keep committing.
        2. *Cutover (short exclusive window)*: the node's TxnGate
           drains in-flight transactions and briefly blocks new ones;
           the final delta folds (bounded by ``delta_threshold``-ish),
           the logs swap under the existing crash-safe journal, and
           partitions + materializer rebuild by standard recovery.

        Emission safety: a transaction's update records always precede
        its FIRST commit copy in wall order (stage -> prepare ->
        commit), so any commit seen by pass k has all its updates below
        pass k+1's cursors — groups emit one pass after their commit is
        first seen, and the quiesced final pass emits the rest.

        Like Node.repartition, this resizes a DC that is not currently
        federated (partition counts are part of the inter-DC contract);
        unlike it, the node stays open for business throughout."""
        if new_n < 1:
            raise ValueError(f"new_n must be >= 1, got {new_n}")
        old_n = self.config.n_partitions
        if new_n == old_n:
            return
        if not self.config.enable_logging:
            raise RuntimeError(
                "repartition folds the durable logs; enable_logging="
                "False leaves nothing to redistribute")

        fold = self.build_resize_fold(new_n)

        # phase 1: fold toward the live frontier while serving
        fold.serve_passes(max_passes, delta_threshold)

        # phase 2: cutover — drain in-flight txns, fold the remainder,
        # swap under the journal, rebuild via recovery
        with self.txn_gate.exclusive():
            fold.final_pass()
            for pm in self.partitions:
                pm.log.close()
            journal = self._resize_journal_path()
            tmp = journal + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{old_n} {new_n}\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, journal)
            # pin the journal rename before acting on it (ISSUE 15 —
            # same discipline as the quiesced repartition above)
            _fsync_dir(self.data_dir, instant="resize_journal_fsync")
            self._complete_resize_swap(old_n, new_n)
            self.config.n_partitions = new_n
            self.partitions = [self._build_partition(p)
                               for p in range(new_n)]
            self._recover_stores()
            if self.stable_tracker is not None:
                self._install_device_stable()

    def _resize_journal_path(self) -> str:
        return resize_journal_path(self.data_dir, self.dc_id)

    def _complete_resize_swap(self, old_n: int, new_n: int) -> None:
        """Idempotently finish a journaled log swap: every remaining
        ``.resize`` file moves into place (displacing the old log to
        ``.pre-resize``), then the journal clears.  Called by
        repartition and by boot-time crash recovery."""
        for p in range(new_n):
            live = self._log_path(p)
            staged = live + ".resize"
            if not os.path.exists(staged):
                continue  # this slot's swap already completed
            # the staged fold never fsynced per commit (it is garbage
            # until the journal lands); pin its bytes BEFORE the
            # rename publishes them — without this, a power cut after
            # the swap could install a page-cache-torn log whose
            # recovery silently truncates at the seam (ISSUE 15)
            with open(staged, "rb") as f:
                os.fsync(f.fileno())
            if os.path.exists(live):
                os.replace(live, live + ".pre-resize")
            os.replace(staged, live)
        for p in range(new_n, old_n):  # shrink: retire extra old logs
            live = self._log_path(p)
            if os.path.exists(live):
                os.replace(live, live + ".pre-resize")
        # the swap's renames must be durable BEFORE the journal
        # clears: unordered metadata could persist the journal unlink
        # but lose the renames — a boot with no journal over
        # half-swapped logs
        _fsync_dir(self.data_dir, instant="resize_swap_fsync")
        # stale checkpoints must not survive the swap: a doc captured
        # against the pre-resize layout would otherwise be adopted by
        # the re-cut log (its cut is just a byte offset) and recovery
        # would seed old-routing state + skip the new log's prefix —
        # segments included, or the next segmented cut at this path
        # could stack fresh deltas onto pre-resize seed files
        from antidote_tpu.oplog.checkpoint import (
            commit_staged_resize_checkpoint,
            delete_checkpoint_files,
            discard_staged_resize_checkpoint,
        )

        for p in range(max(new_n, old_n)):
            cp = self._log_path(p) + ".ckpt"
            # a slot with a staged re-cut checkpoint retires its old
            # one inside commit_staged_resize_checkpoint below — the
            # unconditional delete here would, on a crash re-run,
            # destroy a re-cut checkpoint the previous run already
            # committed (its seeds are the only copy of the pre-cut
            # state; the re-cut log alone is just the suffix)
            if not os.path.exists(cp + ".resize"):
                delete_checkpoint_files(cp)
        # seeded resize (ISSUE 19): each new slot's staged re-cut
        # checkpoint links into place — idempotent (re-runs from the
        # still-present staged files after any crash; returns False
        # when nothing is staged), so the boot-time crash resume is
        # safe; on a legacy fold no slot staged anything → no-op
        for p in range(new_n):
            commit_staged_resize_checkpoint(self._log_path(p) + ".ckpt")
        os.remove(self._resize_journal_path())
        # past the journal removal no re-run can happen — the staged
        # files served their purpose as the re-run marker; sweep them
        # (a crash here just leaves strays the next resize's build
        # discards before staging its own)
        for p in range(new_n):
            discard_staged_resize_checkpoint(self._log_path(p) + ".ckpt")

    def _resume_interrupted_resize(self) -> None:
        """Boot-time check: a journal on disk means a crash interrupted
        a repartition after its staged logs were complete — finish the
        swap and adopt the journal's partition count (the caller's
        config may still carry the old one)."""
        parsed = read_resize_journal(self._resize_journal_path())
        if parsed is None:
            return
        old_n, new_n = parsed
        self._complete_resize_swap(old_n, new_n)
        self.config.n_partitions = new_n

    def _log_path(self, p: int) -> str:
        return os.path.join(self.data_dir, f"{self.dc_id}_p{p}.log")

    def _build_partition(self, p: int) -> PartitionManager:
        # the ONE construction path for the group-commit AND checkpoint
        # knobs (oplog/log.py log_group_from_config + oplog/checkpoint
        # ckpt_from_config — the gate_from_config lesson): boot,
        # repartition, and adopt_partition all come through here, so no
        # assembly can honor different settings
        from antidote_tpu.oplog.checkpoint import (
            CheckpointStore,
            ckpt_from_config,
        )
        from antidote_tpu.oplog.log import log_group_from_config

        cks = ckpt_from_config(self.config)
        # the plane needs BOTH logging and boot-time recovery: with
        # recover_from_log=False nothing ever replays (there is no
        # recovery cost to cut), the seed/dirty sets never cover keys
        # whose history predates this process — and a truncation would
        # then reclaim the ONLY copy of their state
        ckpt = CheckpointStore(self._log_path(p) + ".ckpt", cks) \
            if (cks.enabled and self.config.enable_logging
                and self.config.recover_from_log) else None
        log = PartitionLog(
            self._log_path(p), partition=p,
            sync_on_commit=self.config.sync_log,
            backend=self.config.extra.get("oplog_backend", "auto"),
            enabled=self.config.enable_logging,
            on_append=(lambda rec, _p=p: self._on_log_append(_p, rec))
            if self._on_log_append else None,
            group=log_group_from_config(self.config),
            checkpoint=ckpt)
        plane = None
        if self.config.device_store:
            from antidote_tpu.mat.device_plane import DevicePlane
            from antidote_tpu.mat.sharded import sharded_from_config

            plane = DevicePlane(config=self.config)
            shard = sharded_from_config(self.config)
            if shard.enabled:
                # pod-scale materializer (ISSUE 20): the live keyspace
                # shards ACROSS the mesh's chips — every partition's
                # plane states split on the key axis per the named
                # partition rules, with per-shard adaptive residency.
                # Mutually exclusive with ring placement (a plane is
                # sharded over all chips or pinned to one, never both);
                # the one factory resolves the knob, so mat_sharded=
                # False routes the legacy path bit-for-bit.
                plane.place_sharded(shard.mesh)
            elif self.config.device_placement == "ring":
                import jax

                devs = jax.devices()
                if len(devs) > 1:
                    plane.place_on(devs[p % len(devs)])
        pm = PartitionManager(p, self.dc_id, log, self.clock,
                              device_plane=plane)
        # cross-transaction read coalescing (mat/serve.py): the ONE
        # construction path routes the Config knobs, so every local
        # partition — boot, repartition, adopt_partition — gets the
        # same window (the gate_from_config lesson)
        from antidote_tpu.mat.serve import ReadServer, serve_from_config

        pm.read_server = ReadServer(pm, serve_from_config(self.config))
        if plane is not None and self.config.device_async_flush:
            plane.flush_scheduler = (
                lambda pl, _pm=pm: self._flusher.schedule(_pm, pl))
        pm.stable_vc_source = self.stable_vc
        # owner-side downstream generation (shipped raw ops resolve at
        # the partition that holds the state — manager._resolve_raw_ops)
        pm.gen_downstream_cb = self.gen_downstream
        pm.mint_dot_cb = self.mint_dot
        pm.publish_after_durable = self.config.publish_after_durable
        # recovery-off + logging-on: the log may hold history this
        # process never published — a bottom-seeded warm cache would
        # disagree with log-fallback reads (see PartitionManager)
        pm.seed_cache_on_first_publish = (
            self.config.recover_from_log or not self.config.enable_logging)
        return pm

    # ---------------------------------------------------------- node scope

    def _local_partitions(self) -> List[PartitionManager]:
        """The partitions THIS process owns.  A single-process node owns
        all of them; a ClusterNode (antidote_tpu/cluster/node.py)
        narrows this to its ring slice — everything that folds over
        \"my\" partitions (recovery, min-prepared, flags, close) goes
        through here."""
        return self.partitions

    # ------------------------------------------------------- runtime flags

    #: flags togglable at runtime (the reference replicates these
    #: DC-wide through its stable metadata and every vnode re-reads
    #: them, reference src/logging_vnode.erl:247-258,
    #: src/dc_meta_data_utilities.erl:79-104; this node is a whole DC,
    #: so "DC-wide" is the node plus the durable meta store — see
    #: DataCenter.set_flag for the persisted layer)
    RUNTIME_FLAGS = ("sync_log", "certify", "txn_prot")

    def set_flag(self, name: str, value) -> None:
        if name not in self.RUNTIME_FLAGS:
            raise KeyError(f"unknown runtime flag {name!r}; "
                           f"togglable: {self.RUNTIME_FLAGS}")
        if name == "sync_log":
            value = bool(value)
            self.config.sync_log = value
            for pm in self._local_partitions():
                pm.log.sync_on_commit = value
        elif name == "certify":
            self.config.certify = bool(value)
        elif name == "txn_prot":
            if value not in ("clocksi", "gr"):
                raise ValueError(f"txn_prot must be clocksi|gr, got {value!r}")
            self.config.txn_prot = value

    def get_flag(self, name: str):
        if name not in self.RUNTIME_FLAGS:
            raise KeyError(f"unknown runtime flag {name!r}")
        return getattr(self.config, name)

    # ----------------------------------------------------------- placement

    def partition_index(self, key, n: Optional[int] = None) -> int:
        n = n if n is not None else self.config.n_partitions
        if isinstance(key, int):
            return key % n
        # stable across restarts (Python's hash() is salted per process,
        # which would orphan logged history on recovery)
        if isinstance(key, bytes):
            raw = key
        elif isinstance(key, str):
            raw = key.encode()
        else:
            raw = repr(key).encode()
        return zlib.crc32(raw) % n

    def partition_of(self, key) -> PartitionManager:
        return self.partitions[self.partition_index(key)]

    # --------------------------------------------------------------- clocks

    def stable_vc(self) -> VC:
        """The provider's stable snapshot behind a short TTL cache (see
        Config.stable_ttl_s; benign data race — both racers store a
        freshly computed value)."""
        ttl = self.config.stable_ttl_s
        if ttl <= 0:
            return self.stable_vc_provider()
        t, v = self._stable_read_cache
        now = time.monotonic()
        if v is None or now - t > ttl:
            v = self.stable_vc_provider()
            self._stable_read_cache = (now, v)
        return v

    def min_prepared_vc(self) -> int:
        """Node-wide min prepared time (feeds the stable-time gossip);
        folds this process's own partitions."""
        return min(pm.min_prepared() for pm in self._local_partitions())

    def mint_dot(self) -> Tuple[Any, int]:
        """Unique dot for CRDT downstream generation: ``(dc_id, µs)``
        with the µs sequence strictly monotone node-wide.  One actor per
        DC (not per transaction) is what lets the device data plane
        collapse dot sets into dense per-DC-column tables
        (antidote_tpu/mat/device_plane.py): write-write certification
        serializes same-key commits at a DC, so per-DC max-seq collapse
        preserves ORSWOT semantics."""
        return (self.dc_id, self.clock.now_us())

    # ------------------------------------------------------------ normalize

    @staticmethod
    def normalize_bound(bo) -> Tuple[Any, str, Any]:
        """Bound object: (key, type) or (key, type, bucket)."""
        if len(bo) == 2:
            key, type_name = bo
            return key, _type_name(type_name), None
        key, type_name, bucket = bo
        return key, _type_name(type_name), bucket

    @staticmethod
    def normalize_update(upd) -> Tuple[Tuple, str, Any]:
        """Update: (bound_object, op_name, op_param)."""
        bo, op_name, op_param = upd
        return bo, op_name, op_param

    # ----------------------------------------------------------- downstream

    def gen_downstream(self, cls, op, state, ctx, key=None, bucket=None):
        """Downstream generation with the bounded-counter detour
        (reference src/clocksi_downstream.erl:41-68)."""
        if cls.name == "counter_b" and self.bcounter_mgr is not None:
            return self.bcounter_mgr.generate_downstream(
                op, state, ctx, key=key, bucket=bucket)
        return cls.gen_downstream(op, state, ctx)

    # ------------------------------------------------------------- recovery

    def _recover_stores(self) -> None:
        """Rebuild materializer caches from the durable logs at boot
        (reference materializer_vnode load_from_log,
        src/materializer_vnode.erl:123-131, 288-319).

        ISSUE 10: per partition this is now checkpoint-seeded —
        install the cut's folded key states, then replay ONLY the log
        suffix past the cut (O(delta) however long the log grew) —
        and partitions recover IN PARALLEL: their locks, logs, and
        stores are disjoint, so a restart's wall time is the slowest
        partition, not the sum."""
        from antidote_tpu import stats as _stats

        def recover_one(pm: PartitionManager) -> VC:
            t0 = time.perf_counter()
            with pm._lock:
                seed_migrated = pm.install_ckpt_seeds()
            pre_hosted = pm._pre_hosted()
            # the recovered commit join is a safe fold horizon for
            # replay-time device flushes: every replayed op lies at or
            # below it and nothing else is in flight (it is the same
            # horizon the post-replay gc folds at).  Without one, a
            # replay whose ingest window expires mid-stream (the
            # parallel-recovery interleaving makes that routine) hits
            # the ring-overflow retry with NO gc horizon and evicts
            # hot keys to the host path — values stay correct, the
            # device economy silently vanishes.
            stable = pm.log.max_commit_vc
            stable = stable if stable else None
            for _seq, payload in pm.log.suffix_payloads():
                with pm._lock:
                    # a key whose device seeding evicted mid-install
                    # already replayed seed + suffix via its migration
                    # — publishing again would double-apply (ISSUE 13)
                    if payload.key in seed_migrated or \
                            pm._mid_batch_migrated(pre_hosted,
                                                   payload.key):
                        pm._note_skipped_publish(payload.key, payload)
                    else:
                        pm._publish(payload.key, payload.type_name,
                                    payload, stable)
                if payload.commit_dc != self.dc_id:
                    # replicated records are durable too, but the
                    # certification tables are local-only — exactly as
                    # on the live apply_remote path; loading remote
                    # commit times here would make certify() compare
                    # local snapshot times against another DC's clock
                    continue
                if payload.commit_time > pm.committed.get(payload.key, 0):
                    pm.committed[payload.key] = payload.commit_time
            _stats.registry.ckpt_recovery.observe(
                time.perf_counter() - t0)
            return pm.log.max_commit_vc

        pms = self._local_partitions()
        recovered_vc = VC()
        if len(pms) > 1:
            from concurrent.futures import ThreadPoolExecutor

            workers = min(len(pms), max(2, os.cpu_count() or 2))
            with ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="recover") as ex:
                for vc in ex.map(recover_one, pms):
                    recovered_vc = recovered_vc.join(vc)
        else:
            for pm in pms:
                recovered_vc = recovered_vc.join(recover_one(pm))
        # keep commit timestamps monotone across the restart
        self.clock.advance_to(recovered_vc.get_dc(self.dc_id))
        if recovered_vc:
            # the recovered join is a safe fold horizon: every future
            # op's origin column exceeds its origin's recovered
            # watermark (FIFO opid continuity / local clock), so nothing
            # can still commit at/below it.  Folding leaves the device
            # rings empty — recovery = batch append + one fold.
            for pm in self._local_partitions():
                if pm.device is not None:
                    with pm._lock:
                        pm.device.gc(recovered_vc)

    def adopt_partition(self, p: int):
        """Build + recover ONE partition from its (just-installed) log
        — the receiving half of a cross-node handoff: the transferred
        log replays into the materializer exactly like a boot-time
        recovery, and the clock advances past every adopted commit so
        this node's future commit times stay monotone for the moved
        keys."""
        pm = self._build_partition(p)
        with pm._lock:
            seed_migrated = pm.install_ckpt_seeds()
        pre_hosted = pm._pre_hosted()
        # same safe replay-time fold horizon as _recover_stores
        stable = pm.log.max_commit_vc
        stable = stable if stable else None
        for _seq, payload in pm.log.suffix_payloads():
            with pm._lock:
                if payload.key in seed_migrated or \
                        pm._mid_batch_migrated(pre_hosted, payload.key):
                    pm._note_skipped_publish(payload.key, payload)
                else:
                    pm._publish(payload.key, payload.type_name,
                                payload, stable)
            if payload.commit_dc != self.dc_id:
                continue
            if payload.commit_time > pm.committed.get(payload.key, 0):
                pm.committed[payload.key] = payload.commit_time
        recovered = pm.log.max_commit_vc
        self.clock.advance_to(recovered.get_dc(self.dc_id))
        if recovered and pm.device is not None:
            with pm._lock:
                pm.device.gc(recovered)
        self.partitions[p] = pm
        return pm

    def close(self) -> None:
        self._flusher.stop()
        for pm in self._local_partitions():
            pm.log.close()


def _type_name(t) -> str:
    from antidote_tpu.crdt import get_type

    return get_type(t).name

"""Node-local hybrid clock.

Commit/prepare timestamps must be strictly monotone per node and close
to wall time (Clock-SI correctness depends on waits, not sync).  The
reference uses Erlang µs timestamps with `+C no_time_warp`
(reference config/vm.args:29-31); here: wall µs bumped to stay monotone.
"""

from __future__ import annotations

import threading
import time


class HybridClock:
    def __init__(self):
        self._last = 0
        self._lock = threading.Lock()

    def now_us(self) -> int:
        with self._lock:
            t = time.time_ns() // 1000
            if t <= self._last:
                t = self._last + 1
            self._last = t
            return t

    def advance_to(self, ts_us: int) -> None:
        """Never issue a timestamp at/below ``ts_us`` again — used at
        recovery so commit times stay monotone across restarts even if
        the wall clock regressed (the reference relies on BEAM's
        no_time_warp, config/vm.args:29-31)."""
        with self._lock:
            self._last = max(self._last, int(ts_us))

    def wait_until(self, ts_us: int) -> None:
        """Block until the local clock passes ``ts_us`` (the reference's
        wait_for_clock spin, src/clocksi_interactive_coord.erl:915-926) —
        needed when a client clock from another node runs ahead.

        Consults the HYBRID clock, not raw wall time: after a recovery
        ``advance_to`` (or any wall regression) ``_last`` runs ahead of
        the wall, and timestamps it issued are already safe to read at —
        waiting for the wall to catch up would stall every read for the
        regression span."""
        while True:
            with self._lock:
                now = max(time.time_ns() // 1000, self._last)
            if now >= ts_us:
                return
            time.sleep(min((ts_us - now) / 1e6, 0.01))

"""Stable-snapshot (GST) computation — the stable-time instance of the
generic metadata merge plane.

The reference gossips each partition's vector clock once a second and
publishes the column-wise min, monotonically (reference
src/meta_data_sender.erl:224-356, merge policy
src/stable_time_functions.erl:39-85: a partition missing a DC's entry
pins that column to zero).  In one process the gossip network collapses
to a dense ``int64[P, D]`` matrix and the GST is a single min-reduce —
the dense kernel path (antidote_tpu/clocks/dense.min_merge) that scales
the same computation to 256 simulated DCs on device (BASELINE config 5).
The fold + monotone publish run through the generic
:class:`antidote_tpu.meta.sender.MetaDataSender` framework, exactly as
the reference registers `stable` with stable_time_functions callbacks.

The node dimension of the reference's gossip (partitions live on many
BEAM nodes per DC) maps to the device mesh in this rebuild: sharded
partitions each hold their row, and the min-reduce over the mesh axis is
an XLA collective — see bench_gst for the sharded form.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

import numpy as np

from antidote_tpu.clocks import VC, ClockDomain
from antidote_tpu.meta.sender import MetaDataSender


class StableTimeTracker:
    """Per-partition VC rows -> monotone published GST for one DC."""

    def __init__(self, dc_id, n_partitions: int,
                 domain: Optional[ClockDomain] = None,
                 sender: Optional[MetaDataSender] = None):
        self.dc_id = dc_id
        self.n_partitions = n_partitions
        self.domain = domain or ClockDomain(8)
        self.sender = sender or MetaDataSender()
        # RLock: DeviceStableTimeTracker.put wraps super().put plus its
        # dirty-mark in one outer hold so a snapshot can never observe
        # the row updated but the device mirror not yet marked stale
        self._lock = threading.RLock()
        self.sender.register(
            "stable", n_partitions,
            initial=lambda: np.zeros(self.domain.d, dtype=np.int64),
            merge=self._merge_rows,
            publish=self._publish_monotone,
        )
        # restart-recovery floor (see seed_floor): a single-slot entry
        # whose publish is the same monotone join
        self.sender.register(
            "stable_floor", 1, initial=lambda: None,
            merge=lambda vs: vs[0],
            publish=lambda prev, new:
                new if prev is None
                else (prev if new is None else prev.join(new)),
        )
        #: pull sources: partition -> () -> VC; set by the DC assembly
        #: (dep-gate applied watermarks + own min-prepared)
        self.sources: List[Callable[[], VC]] = []

    # -- merge callbacks (the stable_time_functions role) ----------------

    def _merge_rows(self, rows: List[np.ndarray]) -> VC:
        if not rows or len(self.domain) == 0:
            # zero partitions: a coordinator-only cluster member has no
            # rows to fold; its stable view comes from peer gossip
            return VC()
        gst = np.stack(rows).min(axis=0)
        return self.domain.from_dense(gst)

    @staticmethod
    def _publish_monotone(prev: Optional[VC], new: VC) -> VC:
        return new if prev is None else prev.join(new)

    # -- per-partition inputs --------------------------------------------

    def _grow_if_needed(self, vc: VC) -> None:
        unseen = [dc for dc, t in vc.items()
                  if t and not self.domain.contains(dc)]
        if len(self.domain) + len(unseen) > self.domain.d:
            new_d = max(self.domain.d * 2, len(self.domain) + len(unseen))
            self.domain = self.domain.grow(new_d)
            pad = lambda row: np.pad(row, (0, new_d - len(row)))
            for p in range(self.n_partitions):
                self.sender.update("stable", p, pad)

    def put(self, partition: int, vc: VC) -> None:
        """Advance one partition's row (entries never regress — gossip
        merges are monotone per source, reference update_stable
        src/meta_data_sender.erl:341-356)."""
        with self._lock:
            self._grow_if_needed(vc)
            row = self.domain.to_dense(vc)
            self.sender.update(
                "stable", partition, lambda cur: np.maximum(cur, row))

    def refresh(self) -> None:
        """Pull every partition's current VC from its source."""
        for p, src in enumerate(self.sources):
            self.put(p, src())

    def seed_floor(self, vc: VC) -> None:
        """Restore a previously-published stable snapshot (restart
        recovery): stability is permanent, so a time once published as
        stable may floor the published clock forever — without this the
        GST regresses across a restart to whatever the logs alone can
        prove, hiding committed-but-remote-dependent history until the
        peers gossip again (the reference persists its stable meta for
        the same reason, recover_meta_data_on_start)."""
        with self._lock:
            self._grow_if_needed(vc)
        self.sender.put("stable_floor", 0, vc)
        self.sender.merged("stable_floor")

    def get_stable_snapshot(self) -> VC:
        """Column-wise min over partitions, published monotonically
        (reference dc_utilities:get_stable_snapshot,
        src/dc_utilities.erl:246-279)."""
        if self.sources:
            self.refresh()
        with self._lock:
            stable = self.sender.merged("stable")
            floor = self.sender.peek("stable_floor")
            return VC(stable if floor is None else stable.join(floor))

    def get_scalar_stable_time(self):
        """GentleRain form: (GST scalar, vector stable time) — the min
        entry over known DCs (reference dc_utilities:get_scalar_stable_time,
        src/dc_utilities.erl:294-317)."""
        vst = self.get_stable_snapshot()
        known = [vst.get_dc(dc) for dc in self.domain.dc_ids]
        gst = min(known) if known else 0
        return gst, vst

"""Device-resident stable clock plane — the GST as a mesh collective.

Under ring placement (Config.device_placement="ring") partition p's
data plane lives on chip p % n_devices.  This module puts the stable
METADATA there too: each partition's stable VC row (the quantity the
reference gossips once a second, src/meta_data_sender.erl:224-255) is
mirrored onto the partition's own chip, and the DC's stable snapshot —
the column-wise min over partitions (src/stable_time_functions.erl:
39-85) — is ONE sharded XLA program whose min-reduce is a cross-device
``pmin`` riding ICI (the ShardedOrsetStore.gc_collective pattern,
antidote_tpu/mat/sharded.py; SURVEY §7.7).

The host fold (StableTimeTracker, meta/gossip.py) stays fully wired as
the ORACLE: every row mirrored to the device is also folded on host,
and tests assert the two snapshots are identical.  In a multi-node DC
this plane replaces the LOCAL (per-node) fold; the cross-node level
remains gossip (cluster/node.py ClusterStablePlane) — on a multi-host
TPU pod the mesh spans the hosts and this same program spans the DC.

Row layout: device-major blocks.  Device k holds the rows of the
partitions ring-placed on it ({p : p % n == k}), padded to a common
row count with +inf rows (min-neutral).  A row update touches only its
device's small block; the fold builds one global array from the
per-device blocks (no host gather) and runs the collective.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from antidote_tpu.clocks import VC
from antidote_tpu.meta.gossip import StableTimeTracker

_I64_MAX = np.iinfo(np.int64).max

from antidote_tpu.runtime import COLLECTIVE_LOCK as _COLLECTIVE_LOCK


def _pow2(n: int, floor: int = 8) -> int:
    return max(floor, 1 << (max(n, 1) - 1).bit_length())


class DeviceStableTimeTracker(StableTimeTracker):
    """StableTimeTracker whose published fold runs on the device mesh.

    ``put`` updates the host row (the oracle path, unchanged) and marks
    the partition's device row dirty; ``get_stable_snapshot`` flushes
    dirty rows to their chips and serves the min from the collective.
    ``oracle_snapshot`` serves the host fold for equality checks."""

    def __init__(self, dc_id, n_partitions: int, devices: List,
                 placement: Optional[List[int]] = None,
                 domain=None, sender=None):
        super().__init__(dc_id, n_partitions, domain=domain,
                         sender=sender)
        if not devices:
            raise ValueError("device plane needs at least one device")
        self.devices = list(devices)
        n = len(self.devices)
        #: row -> device index.  Default mirrors the data-plane ring
        #: (txn/node.py places partition p's plane on devices[p % n]);
        #: a cluster member passes its local slice's GLOBAL ring slots
        #: so each row still sits beside its partition's plane.
        if placement is None:
            placement = [p % n for p in range(n_partitions)]
        if len(placement) != n_partitions or any(
                not 0 <= k < n for k in placement):
            raise ValueError("placement must map every row to a device")
        self.placement = list(placement)
        #: row -> (device index, slot within that device's block)
        self._slots = {}
        per_dev = [0] * n
        for p, k in enumerate(self.placement):
            self._slots[p] = (k, per_dev[k])
            per_dev[k] += 1
        self._rpd = max(1, max(per_dev, default=0))
        self._dev_lock = threading.Lock()
        #: serializes device folds + the monotone publish.  The fold
        #: itself (device transfers + the collective + the D2H fetch)
        #: runs under THIS mutex only — holding self._lock/_dev_lock
        #: across it stalled every delivery/heartbeat put() for the
        #: fold duration (round-5 advisor finding); those locks now
        #: cover just the host-side row copy.
        self._fold_lock = threading.Lock()
        self._d_pad = _pow2(self.domain.d)
        #: host mirror of the device rows, device-major (+inf pads are
        #: min-neutral)
        self._blocks_host = [
            np.full((self._rpd, self._d_pad), _I64_MAX, np.int64)
            for _ in range(n)
        ]
        self._blocks_dev = [None] * n  # lazily device_put per block
        self._dirty = set(range(n_partitions))
        self._published_dev: Optional[VC] = None
        self._fold_fn = None
        self._mesh = None

    # -- row ingestion ----------------------------------------------------

    def put(self, partition: int, vc: VC) -> None:
        # one critical section for the host-row update AND the
        # dirty-mark (the tracker lock is an RLock for exactly this):
        # released in between, a snapshot holding both locks could fold
        # the NEW row on host but skip flushing the device mirror
        # (partition not yet dirty) — dev lagging host by one put
        with self._lock:
            super().put(partition, vc)  # the host oracle row
            with self._dev_lock:
                self._dirty.add(partition)

    # -- device plumbing --------------------------------------------------

    def _slot(self, p: int):
        return self._slots[p]

    def _ensure_width(self) -> None:
        """Domain growth (host side pads rows in put) must widen the
        device blocks too; a width change invalidates every block and
        the compiled fold."""
        want = _pow2(self.domain.d)
        if want == self._d_pad:
            return
        self._d_pad = want
        n = len(self.devices)
        self._blocks_host = [
            np.full((self._rpd, self._d_pad), _I64_MAX, np.int64)
            for _ in range(n)
        ]
        self._blocks_dev = [None] * n
        self._dirty = set(range(self.n_partitions))
        self._fold_fn = None

    def _build_fold(self):
        import jax
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        n = len(self.devices)
        self._mesh = Mesh(np.array(self.devices), ("parts",))
        sharding = NamedSharding(self._mesh, P("parts", None))

        if n == 1:
            # degenerate mesh: a plain jitted min (no collective axis)
            self._fold_fn = (jax.jit(lambda m: m.min(axis=0)), sharding)
            return

        def local_min(blk):
            import jax.numpy as jnp

            m = jnp.min(blk, axis=0, keepdims=True)  # (1, D) this chip
            # the cross-device column min — XLA lowers this to an ICI
            # all-reduce(min) on TPU (the gossip fold as a collective)
            return jax.lax.pmin(m, "parts")

        from antidote_tpu.runtime import shard_map_compat

        fn = jax.jit(shard_map_compat(
            local_min, mesh=self._mesh,
            in_specs=P("parts", None), out_specs=P(None, None)))
        self._fold_fn = (lambda m: fn(m)[0], sharding)

    def _copy_dirty_locked(self):
        """Copy every dirty partition's row into the host-side device
        blocks.  Caller holds self._lock, self._dev_lock AND
        self._fold_lock; this is pure host-array work (the EXACT rows
        the host oracle folds — _grow_if_needed keeps them current),
        so the row locks are held only for the memcpy, not the device
        round trip.  Returns (touched device indices, domain snapshot)
        for the fold that runs after the locks drop."""
        self._ensure_width()
        touched = set()
        for p in self._dirty:
            k, j = self._slot(p)
            row = np.asarray(self.sender.peek_value("stable", p))
            blk = self._blocks_host[k]
            blk[j, :] = _I64_MAX
            blk[j, :len(row)] = row
            touched.add(k)
        self._dirty.clear()
        return touched, self.domain

    # -- snapshots --------------------------------------------------------

    def oracle_snapshot(self) -> VC:
        """The host fold — identical inputs, host min (for tests)."""
        return super().get_stable_snapshot()

    def snapshot_pair(self):
        """(device snapshot, host snapshot) folded from ONE source
        refresh — the oracle-equality form: time-dependent sources
        (min-prepared reads the clock) make two separately-refreshed
        snapshots incomparable.  Both folds read their inputs under
        ONE row-lock hold (a concurrent put() between them would feed
        the later fold newer rows and make the pair transiently
        unequal — observed live with background heartbeats); the
        device round trip itself then runs outside the row locks."""
        if self.sources:
            self.refresh()
        with self._fold_lock:
            with self._lock, self._dev_lock:
                # ONE floor peek shared by both folds: a concurrent
                # seed_floor between two peeks would skew only the
                # later fold
                floor = self.sender.peek("stable_floor")
                touched, domain = self._copy_dirty_locked()
                stable = self.sender.merged("stable")
                host = VC(stable if floor is None
                          else stable.join(floor))
            dev = self._fold_device(touched, domain, floor)
        return dev, host

    def get_stable_snapshot(self) -> VC:
        if self.sources:
            self.refresh()
        if self.n_partitions == 0:
            return super().get_stable_snapshot()
        with self._fold_lock:
            with self._lock, self._dev_lock:
                floor = self.sender.peek("stable_floor")
                touched, domain = self._copy_dirty_locked()
            return self._fold_device(touched, domain, floor)

    def _fold_device(self, touched, domain, floor) -> VC:
        """The device fold: flush touched blocks, run the collective,
        publish monotonically.  Runs under self._fold_lock ONLY (plus
        COLLECTIVE_LOCK around the launch) — delivery/heartbeat put()
        calls proceed concurrently instead of stalling for the whole
        device round trip (round-5 advisor finding); they mark rows
        dirty for the NEXT fold, which the monotone publish orders.
        ``domain`` is the width snapshot taken with the rows — a
        concurrent grow must not skew the dense decode."""
        import jax

        if self._fold_fn is None:
            self._build_fold()
        fold, sharding = self._fold_fn
        n = len(self.devices)
        for k in range(n):
            if k in touched or self._blocks_dev[k] is None:
                self._blocks_dev[k] = jax.device_put(
                    self._blocks_host[k], self.devices[k])
        with _COLLECTIVE_LOCK:
            global_mat = jax.make_array_from_single_device_arrays(
                (n * self._rpd, self._d_pad), sharding,
                self._blocks_dev)
            row = np.asarray(fold(global_mat))
        # +inf pad rows survive the min only when a column is
        # beyond every real row's width — those columns are absent
        # from the domain anyway; mask for safety
        row = np.where(row == _I64_MAX, 0, row)
        gst = domain.from_dense(row[:domain.d])
        if floor is not None:
            gst = gst.join(floor)
        # monotone publish, the device path's own lineage (serialized
        # by self._fold_lock)
        self._published_dev = (
            gst if self._published_dev is None
            else self._published_dev.join(gst))
        return VC(self._published_dev)


def make_stable_tracker(config, dc_id, n_partitions: int,
                        placement: Optional[List[int]] = None,
                        **kw) -> StableTimeTracker:
    """Tracker factory honoring the node's placement policy: the
    device-collective plane when the data plane is ring-placed over a
    real multi-device mesh, the host fold otherwise.  ``placement``
    maps row index -> device index for callers whose rows are a slice
    of a larger ring (cluster members); default is the full ring
    (txn/node.py places partition p's plane on devices()[p % n])."""
    if (config is not None and config.device_store
            and config.device_placement == "ring"):
        import jax

        devs = jax.devices()
        if len(devs) > 1:
            return DeviceStableTimeTracker(dc_id, n_partitions, devs,
                                           placement=placement, **kw)
    return StableTimeTracker(dc_id, n_partitions, **kw)

"""Generic named-metadata merge framework — the meta_data_sender /
meta_data_manager duty (reference src/meta_data_sender.erl:60-220:
arbitrary named metadata, per-partition values, a registered merge
function folding them into one published view, update callbacks on
change).

The reference gossips these tables across the DC's BEAM nodes; this
rebuild's DC is one process scaling through partitions and device
shards, so the node-gossip hop collapses and the framework is the
per-partition fold + monotone publish.  The stable-time plane
(antidote_tpu/meta/gossip.py StableTimeTracker) is the flagship
instance — registered here with a dense-tensor merge, exactly as the
reference registers `stable` with `stable_time_functions` merge
callbacks (reference src/stable_time_functions.erl:24-37).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class _Entry:
    __slots__ = ("values", "merge", "publish", "merged", "on_update")

    def __init__(self, n_partitions: int, initial: Callable[[], Any],
                 merge: Callable[[List[Any]], Any],
                 publish: Callable[[Any, Any], Any],
                 on_update: Optional[Callable[[Any], None]]):
        self.values = [initial() for _ in range(n_partitions)]
        self.merge = merge
        self.publish = publish
        self.merged: Any = None
        self.on_update = on_update


class MetaDataSender:
    """Named metadata tables with per-partition values and fold-merge.

    - ``register(name, n_partitions, initial, merge, publish)``:
      ``merge([v_0..v_P-1])`` folds the partition values;
      ``publish(prev_merged, new)`` reconciles with the previously
      published view (e.g. monotone join — the reference's
      should-update check, src/meta_data_sender.erl:341-356).
    - ``put(name, partition, value)`` stores one partition's datum.
    - ``merged(name)`` folds + publishes, invoking the update callback
      when the published view changed.
    """

    def __init__(self):
        self._entries: Dict[str, _Entry] = {}
        self._lock = threading.Lock()

    def register(self, name: str, n_partitions: int,
                 initial: Callable[[], Any],
                 merge: Callable[[List[Any]], Any],
                 publish: Callable[[Any, Any], Any] = lambda _p, n: n,
                 on_update: Optional[Callable[[Any], None]] = None) -> None:
        with self._lock:
            if name in self._entries:
                raise KeyError(f"metadata {name!r} already registered")
            self._entries[name] = _Entry(n_partitions, initial, merge,
                                         publish, on_update)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def put(self, name: str, partition: int, value: Any) -> None:
        with self._lock:
            self._entries[name].values[partition] = value

    def update(self, name: str, partition: int,
               fn: Callable[[Any], Any]) -> None:
        """Read-modify-write one partition's datum under the lock."""
        with self._lock:
            e = self._entries[name]
            e.values[partition] = fn(e.values[partition])

    def merged(self, name: str) -> Any:
        cb = None
        with self._lock:
            e = self._entries[name]
            new = e.publish(e.merged, e.merge(list(e.values)))
            if new != e.merged:
                e.merged = new
                cb = e.on_update
            out = e.merged
        if cb is not None:
            cb(out)
        return out

    def peek(self, name: str) -> Any:
        """Last published view without re-folding."""
        with self._lock:
            return self._entries[name].merged

    def peek_value(self, name: str, partition: int) -> Any:
        """One partition's raw datum (no fold) — the device stable
        plane mirrors these rows onto the mesh."""
        with self._lock:
            return self._entries[name].values[partition]

"""Clock / metadata plane (reference §2.4: meta_data_sender,
stable_meta_data_server, dc_utilities stable-snapshot accessors)."""

from antidote_tpu.meta.gossip import StableTimeTracker  # noqa: F401
from antidote_tpu.meta.stable_store import StableMetaData  # noqa: F401

"""DC-wide durable configuration store — the stable_meta_data_server
equivalent (reference src/stable_meta_data_server.erl): a small KV map
holding DC descriptors, connected-DC lists, env flags, and the
``has_started`` restart flag, persisted to disk (the reference uses
dets) and reloaded at boot so a restarted node can re-join its DCs
(reference check_node_restart, src/inter_dc_manager.erl:156-201).
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Dict, Optional

from antidote_tpu.oplog.log import _fsync_dir


class StableMetaData:
    def __init__(self, path: Optional[str], recover: bool = True):
        self.path = path
        self._lock = threading.Lock()
        self._kv: Dict[Any, Any] = {}
        if recover and path and os.path.exists(path):
            with open(path, "rb") as f:
                data = pickle.load(f)
            if isinstance(data, dict):
                self._kv = data

    def get(self, key, default=None):
        with self._lock:
            return self._kv.get(key, default)

    def put(self, key, value) -> None:
        with self._lock:
            self._kv[key] = value
            # lock-ok: persist-under-lock is this store's design — a
            # tiny KV on the 1 s gossip cadence, and the lock is what
            # keeps each on-disk snapshot a consistent cut
            self._persist()

    def merge_update(self, key, value, merge) -> None:
        """Update ``key`` through a merge function (reference
        broadcast_meta_data_merge, src/stable_meta_data_server.erl:180-190)."""
        with self._lock:
            self._kv[key] = merge(self._kv.get(key), value)
            # lock-ok: same audit as put — consistent-cut persist on
            # the gossip cadence
            self._persist()

    def delete(self, key) -> None:
        with self._lock:
            self._kv.pop(key, None)
            # lock-ok: same audit as put — consistent-cut persist on
            # the gossip cadence
            self._persist()

    def keys(self):
        with self._lock:
            return list(self._kv.keys())

    def _persist(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            # lock-ok: the stable-meta KV is tiny (a handful of VCs)
            # and writes ride the 1 s gossip cadence; persisting under
            # the lock is what keeps the file a consistent snapshot
            pickle.dump(self._kv, f, protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            # lock-ok: same audit — without the fsync the rename
            # below publishes page-cache bytes, and a power cut
            # could lose the has_started flag an acked restart
            # contract depends on (the ISSUE-15 sweep found this
            # write was never pinned at all)
            os.fsync(f.fileno())
        # lock-ok: same audit — an atomic rename of a tiny file on the
        # gossip cadence, ordered with the update it persists
        os.replace(tmp, self.path)
        # lock-ok: same audit — the directory fsync pins the rename
        # (a lost rename re-reads the previous consistent KV, but the
        # durable-publish protocol is one discipline, not a menu)
        _fsync_dir(os.path.dirname(self.path), instant="meta_dir_fsync")

    # ------------------------------------------------- well-known entries

    def mark_started(self) -> None:
        self.put("has_started", True)

    def has_started(self) -> bool:
        return bool(self.get("has_started", False))

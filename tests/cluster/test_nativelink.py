"""Native node fabric (cluster/nativelink.py + native/nodelink.cpp):
the NodeLink protocol contract over the C++ IO plane.

What must hold (same contract as the Python NodeLink, judged by the
same rules as tests/cluster/test_cluster.py's fabric expectations):
typed errors cross the wire, a transport failure retries ONCE with the
same rid and the peer's at-most-once cache keeps non-idempotent
handlers exactly-once, a restarted server rebinds its advertised port,
and pipelined fan-out preserves per-call results and errors.
"""

import threading
import time

import pytest

from antidote_tpu.cluster.nativelink import (
    NativeNodeLink,
    native_available,
)
from antidote_tpu.interdc.transport import LinkDown
from antidote_tpu.txn.manager import CertificationError

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain")


def _pair(handler, **kw):
    a = NativeNodeLink("a", **kw)
    b = NativeNodeLink("b", **kw)
    addr = b.serve(handler)
    a.serve(lambda *x: None)
    a.connect("b", addr)
    return a, b


def test_roundtrip_and_typed_errors():
    def handler(origin, kind, payload):
        if kind == "cert":
            raise CertificationError("ww conflict")
        if kind == "timeout":
            raise TimeoutError("clock wait")
        return (origin, kind, payload)

    a, b = _pair(handler)
    try:
        assert a.request("b", "echo", {"k": [1, b"x", None]}) == \
            ("a", "echo", {"k": [1, b"x", None]})
        with pytest.raises(CertificationError):
            a.request("b", "cert", None)
        with pytest.raises(TimeoutError):
            a.request("b", "timeout", None)
    finally:
        a.close()
        b.close()


def test_pipelined_fanout_mixed_results():
    def handler(origin, kind, payload):
        if payload == 3:
            raise CertificationError("no")
        return payload * 10

    a, b = _pair(handler)
    try:
        out = a.request_many([("b", "q", i) for i in range(6)])
        for i, (ok, val) in enumerate(out):
            if i == 3:
                assert not ok and isinstance(val, CertificationError)
            else:
                assert ok and val == i * 10
    finally:
        a.close()
        b.close()


def test_big_frames_grow_buffers_both_directions():
    blob = b"z" * (3 << 20)

    a, b = _pair(lambda o, k, p: p)
    try:
        assert a.request("b", "echo", blob) == blob
    finally:
        a.close()
        b.close()


def test_linkdown_on_unreachable_peer():
    a = NativeNodeLink("a")
    a.serve(lambda *x: None)
    a.connect("ghost", ("127.0.0.1", 1))
    try:
        with pytest.raises(LinkDown):
            a.request("ghost", "q", None)
    finally:
        a.close()


def test_retry_after_drop_is_at_most_once():
    """A client whose link dies mid-request re-sends the SAME rid; the
    server must answer from its at-most-once cache (or park the
    duplicate on the first execution), never run the handler twice."""
    calls = []
    started = threading.Event()

    def handler(origin, kind, payload):
        calls.append(payload)
        started.set()
        time.sleep(0.3)  # reply lands after the client dropped the link
        return len(calls)

    a, b = _pair(handler)
    try:
        h = a.start_request("b", "bump", 1)
        assert started.wait(5.0)  # first execution is in flight
        # sever the link under the in-flight request: its reply is lost
        a._lib.nl_drop_peer(a._h, h.idx)
        # finish retries once with the same rid on a fresh dial; the
        # duplicate parks on the in-flight marker and gets execution
        # #1's reply
        assert a.finish_request(h) == 1
        assert calls == [1]
    finally:
        a.close()
        b.close()


def test_server_restart_rebinds_advertised_port():
    a, b = _pair(lambda o, k, p: ("v1", p))
    addr = b.local_addr()
    try:
        assert a.request("b", "q", 7) == ("v1", 7)
        b.close()
        b2 = NativeNodeLink("b", host=addr[0], port=addr[1])
        b2.serve(lambda o, k, p: ("v2", p))
        try:
            # the client's first attempt may ride the dead connection;
            # the built-in single retry dials the rebound listener
            assert a.request("b", "q", 8) == ("v2", 8)
        finally:
            b2.close()
    finally:
        a.close()


def test_concurrent_clients_share_one_connection():
    seen = []
    lock = threading.Lock()

    def handler(origin, kind, payload):
        with lock:
            seen.append(payload)
        return payload

    a, b = _pair(handler)
    errs = []

    def worker(t):
        try:
            for i in range(50):
                assert a.request("b", "q", (t, i)) == (t, i)
        except Exception as e:  # noqa: BLE001 — collected for assert
            errs.append(e)

    try:
        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        assert len(seen) == 400
    finally:
        a.close()
        b.close()

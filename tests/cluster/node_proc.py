"""Subprocess node harness for the multi-process-DC tests.

Runs one NodeServer and obeys a line-oriented stdio protocol so the
pytest parent can drive a DC whose partitions live in several OS
processes — the analogue of the reference's ct_slave BEAM peers
(reference test/utils/test_utils.erl:110-165).

Commands (JSON per line on stdin; one JSON reply per line on stdout):
  {"cmd": "addr"}
  {"cmd": "join", "dc": d, "ring": {"0": nid, ...},
   "members": {nid: [host, port], ...}}
  {"cmd": "update", "key": k, "type": t, "op": o, "arg": a,
   "clock": vc|null}
  {"cmd": "read", "key": k, "type": t, "clock": vc|null}
  {"cmd": "stable"}
  {"cmd": "kill"}     — hard-exit without cleanup (crash injection)
  {"cmd": "exit"}     — graceful close
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from antidote_tpu.clocks import VC  # noqa: E402
from antidote_tpu.cluster import NodeServer  # noqa: E402
from antidote_tpu.config import Config  # noqa: E402


def main():
    node_id = sys.argv[1]
    data_dir = sys.argv[2]
    port = int(sys.argv[3])
    faults = sys.argv[4] if len(sys.argv) > 4 else ""
    if "die_in_resize_swap" in faults:
        # crash injection: kill -9 semantics at the nastiest resize
        # point — journal + new plan persisted, staged logs complete,
        # live logs NOT yet swapped (restart must resume via journal)
        from antidote_tpu.txn.node import Node

        def dying(self, old_n, new_n):
            os._exit(9)

        Node._complete_resize_swap = dying
    srv = NodeServer(node_id, port=port, data_dir=data_dir,
                     config=Config(heartbeat_s=0.02, sync_log=True,
                                   clock_wait_timeout_s=20.0))

    def out(obj):
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    out({"ready": True, "addr": list(srv.addr),
         "assembled": srv.node is not None})
    for line in sys.stdin:
        try:
            req = json.loads(line)
            cmd = req["cmd"]
            if cmd == "addr":
                out({"addr": list(srv.addr)})
            elif cmd == "join":
                srv.install_cluster(
                    req["dc"],
                    {int(p): nid for p, nid in req["ring"].items()},
                    {nid: tuple(a) for nid, a in req["members"].items()})
                out({"ok": True})
            elif cmd == "update":
                clock = VC(req["clock"]) if req.get("clock") else None
                ct = srv.api.update_objects_static(
                    clock,
                    [((req["key"], req["type"], "b"), req["op"],
                      req["arg"])])
                out({"clock": dict(ct)})
            elif cmd == "read":
                clock = VC(req["clock"]) if req.get("clock") else None
                vals, cvc = srv.api.read_objects_static(
                    clock, [(req["key"], req["type"], "b")])
                out({"value": vals[0], "clock": dict(cvc)})
            elif cmd == "stable":
                out({"stable": dict(
                    srv.plane.get_stable_snapshot())})
            elif cmd == "resize":
                ring = srv.resize_cluster(int(req["n"]))
                out({"ring": {str(p): o for p, o in ring.items()}})
            elif cmd == "width":
                out({"n": srv.node.config.n_partitions,
                     "parked": srv._resize_parking})
            elif cmd == "kill":
                os._exit(9)
            elif cmd == "exit":
                srv.close()
                out({"ok": True})
                return
            else:
                out({"error": f"unknown cmd {cmd!r}"})
        except Exception as e:  # noqa: BLE001 — report, keep serving
            out({"error": f"{type(e).__name__}: {e}"})


if __name__ == "__main__":
    main()

"""Multi-process DC: partitions spread over node processes, cross-node
transactions, one merged stable snapshot, kill/restart recovery.

The reference's analogue is a riak_core cluster of ct_slave BEAM nodes
in one DC (reference test/utils/test_utils.erl:110-165, staged join
src/antidote_dc_manager.erl:53-81, cross-node gossip
src/meta_data_sender.erl:224-255).  Tier 1 forms the cluster inside one
process over real TCP; tier 2 spawns separate OS processes
(node_proc.py) and kills/restarts one mid-run.
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from antidote_tpu.clocks import VC
from antidote_tpu.cluster import (
    NodeServer,
    create_dc_cluster,
    plan_ring,
)
from antidote_tpu.config import Config


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def cluster2(tmp_path):
    servers = [
        NodeServer(f"n{i + 1}", data_dir=str(tmp_path / f"n{i + 1}"),
                   config=Config(heartbeat_s=0.02,
                                 clock_wait_timeout_s=10.0))
        for i in range(2)
    ]
    create_dc_cluster("dc1", 4, servers)
    yield servers
    for s in servers:
        s.close()


class TestRingPlacement:
    def test_plan_covers_all_partitions(self):
        ring = plan_ring(5, ["b", "a"])
        assert sorted(ring) == [0, 1, 2, 3, 4]
        assert set(ring.values()) == {"a", "b"}

    def test_partitions_split_between_nodes(self, cluster2):
        n1, n2 = cluster2
        own1 = n1.node.local_partition_indices()
        own2 = n2.node.local_partition_indices()
        assert sorted(own1 + own2) == [0, 1, 2, 3]
        assert own1 and own2
        # both nodes agree on the ring
        assert n1.node.ring == n2.node.ring


class TestCrossNodeTransactions:
    def test_writes_on_both_nodes_one_view(self, cluster2):
        n1, n2 = cluster2
        # integer keys map to partitions by modulo: key 0 lives on n1's
        # slice, key 1 on n2's (round-robin ring over sorted node ids)
        ct = n1.api.update_objects_static(
            None, [((0, "counter_pn", "b"), "increment", 1)])
        ct = n2.api.update_objects_static(
            ct, [((1, "counter_pn", "b"), "increment", 2)])
        # each node reads BOTH keys — one local, one via the proxy
        for srv in cluster2:
            vals, _ = srv.api.read_objects_static(
                ct, [(0, "counter_pn", "b"), (1, "counter_pn", "b")])
            assert vals == [1, 2], srv.node_id

    def test_remote_coordinator_writes_remote_partition(self, cluster2):
        n1, n2 = cluster2
        remote_key = n2.node.local_partition_indices()[0]
        # n1 coordinates a txn whose only partition is owned by n2
        ct = n1.api.update_objects_static(
            None, [((remote_key, "set_aw", "b"), "add", "x")])
        vals, _ = n2.api.read_objects_static(
            ct, [(remote_key, "set_aw", "b")])
        assert vals[0] == ["x"]
        # the durable record lives at the owner
        pm = n2.node.partitions[remote_key]
        assert remote_key in pm.log.keys_seen

    def test_cross_node_multipartition_2pc(self, cluster2):
        n1, n2 = cluster2
        k1 = n1.node.local_partition_indices()[0]
        k2 = n2.node.local_partition_indices()[0]
        tx = n1.api.start_transaction()
        n1.api.update_objects(
            [((k1, "counter_pn", "b"), "increment", 10),
             ((k2, "counter_pn", "b"), "increment", 20)], tx)
        ct = n1.api.commit_transaction(tx)
        for srv in cluster2:
            vals, _ = srv.api.read_objects_static(
                ct, [(k1, "counter_pn", "b"), (k2, "counter_pn", "b")])
            assert vals == [10, 20]

    def test_remote_certification_aborts(self, cluster2):
        from antidote_tpu.txn.coordinator import TransactionAborted

        n1, n2 = cluster2
        key = n2.node.local_partition_indices()[0]
        tx1 = n1.api.start_transaction()
        tx2 = n1.api.start_transaction()
        n1.api.update_objects(
            [((key, "counter_pn", "b"), "increment", 1)], tx1)
        n1.api.update_objects(
            [((key, "counter_pn", "b"), "increment", 1)], tx2)
        n1.api.commit_transaction(tx1)
        with pytest.raises(TransactionAborted):
            n1.api.commit_transaction(tx2)

    def test_exact_downstream_state_crosses_nodes(self, cluster2):
        """The exact-state rule must survive the RPC hop: remove,
        remove, add on a remote set_rw with cold caches."""
        n1, n2 = cluster2
        key = n2.node.local_partition_indices()[0]
        bo = (key, "set_rw", "b")
        ct = n1.api.update_objects_static(None, [(bo, "remove", "x")])
        for pm in n2.node._local_partitions():
            with pm._lock:
                pm._val_cache.clear()
        ct = n1.api.update_objects_static(ct, [(bo, "remove", "x")])
        for pm in n2.node._local_partitions():
            with pm._lock:
                pm._val_cache.clear()
        ct = n1.api.update_objects_static(ct, [(bo, "add", "x")])
        v1, _ = n1.api.read_objects_static(ct, [bo])
        v2, _ = n2.api.read_objects_static(ct, [bo])
        assert v1[0] == v2[0] == ["x"]


class TestClusterStablePlane:
    def test_one_stable_snapshot_covers_both_nodes(self, cluster2):
        n1, n2 = cluster2
        ct1 = n1.api.update_objects_static(
            None, [((0, "counter_pn", "b"), "increment", 1)])
        ct2 = n2.api.update_objects_static(
            None, [((1, "counter_pn", "b"), "increment", 1)])
        want = max(ct1.get_dc("dc1"), ct2.get_dc("dc1"))
        deadline = time.monotonic() + 10.0
        while True:
            st1 = n1.plane.get_stable_snapshot().get_dc("dc1")
            st2 = n2.plane.get_stable_snapshot().get_dc("dc1")
            if st1 >= want and st2 >= want:
                break
            assert time.monotonic() < deadline, (st1, st2, want)
            time.sleep(0.01)

    def test_snapshot_zero_until_peer_reports(self, tmp_path):
        """A member that never gossiped pins the snapshot to zero
        (reference stable_time_functions:78-85)."""
        srv = NodeServer("n1", data_dir=str(tmp_path / "solo"),
                         config=Config(heartbeat_s=0.02))
        try:
            # plan includes an unreachable ghost member
            ring = plan_ring(2, ["n1", "ghost"])
            srv.install_cluster(
                "dc1", ring,
                {"n1": srv.addr, "ghost": ("127.0.0.1", free_port())})
            assert srv.plane.get_stable_snapshot().get_dc("dc1") == 0
        finally:
            srv.close()


# --------------------------------------------------------------- tier 2


class NodeProc:
    def __init__(self, node_id, data_dir, port):
        self.proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "node_proc.py"),
             node_id, data_dir, str(port)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        self.node_id = node_id
        ready = json.loads(self.proc.stdout.readline())
        assert ready.get("ready"), ready
        self.addr = ready["addr"]
        self.assembled = ready.get("assembled", False)

    def cmd(self, **req):
        self.proc.stdin.write(json.dumps(req) + "\n")
        self.proc.stdin.flush()
        resp = json.loads(self.proc.stdout.readline())
        assert "error" not in resp, resp
        return resp

    def kill(self):
        try:
            self.proc.stdin.write(json.dumps({"cmd": "kill"}) + "\n")
            self.proc.stdin.flush()
        except OSError:
            pass
        self.proc.wait(timeout=10)

    def stop(self):
        if self.proc.poll() is not None:
            return
        try:
            self.cmd(cmd="exit")
        except Exception:  # noqa: BLE001
            pass
        self.proc.wait(timeout=10)


class TestCrossProcessDC:
    def test_two_process_dc_kill_restart(self, tmp_path):
        ports = [free_port(), free_port()]
        dirs = [str(tmp_path / "n1"), str(tmp_path / "n2")]
        procs = [NodeProc(f"n{i + 1}", dirs[i], ports[i])
                 for i in range(2)]
        try:
            members = {p.node_id: p.addr for p in procs}
            ring = {str(i): f"n{(i % 2) + 1}" for i in range(4)}
            for p in procs:
                p.cmd(cmd="join", dc="dc1", ring=ring, members=members)

            # writes on both nodes; cross-process reads see both
            ct = procs[0].cmd(cmd="update", key=0, type="counter_pn",
                              op="increment", arg=1)["clock"]
            ct = procs[1].cmd(cmd="update", key=1, type="counter_pn",
                              op="increment", arg=2, clock=ct)["clock"]
            r = procs[0].cmd(cmd="read", key=1, type="counter_pn",
                             clock=ct)
            assert r["value"] == 2
            r = procs[1].cmd(cmd="read", key=0, type="counter_pn",
                             clock=ct)
            assert r["value"] == 1

            # ONE stable snapshot: both processes converge past the
            # writes' commit point
            want = ct["dc1"]
            deadline = time.monotonic() + 15.0
            while True:
                st = [p.cmd(cmd="stable")["stable"].get("dc1", 0)
                      for p in procs]
                if all(s >= want for s in st):
                    break
                assert time.monotonic() < deadline, (st, want)
                time.sleep(0.05)

            # kill node 2 hard; node 1's snapshot holds (stability is
            # permanent) and its own partitions keep serving
            procs[1].kill()
            r = procs[0].cmd(cmd="read", key=0, type="counter_pn")
            assert r["value"] == 1
            st1 = procs[0].cmd(cmd="stable")["stable"].get("dc1", 0)
            assert st1 >= want

            # restart node 2 from its data dir: it reloads the
            # persisted plan, recovers partitions from its logs, and
            # re-joins the gossip
            procs[1] = NodeProc("n2", dirs[1], ports[1])
            assert procs[1].assembled
            r = procs[1].cmd(cmd="read", key=1, type="counter_pn",
                             clock=ct)
            assert r["value"] == 2

            # the DC keeps accepting cross-node transactions
            ct = procs[1].cmd(cmd="update", key=0, type="counter_pn",
                              op="increment", arg=5, clock=ct)["clock"]
            r = procs[0].cmd(cmd="read", key=0, type="counter_pn",
                             clock=ct)
            assert r["value"] == 6
        finally:
            for p in procs:
                p.stop()

"""Federation of multi-node DCs: two DCs, each spanning two node
servers, replicating over the inter-DC fabric — the reference's full
topology (many BEAM nodes per DC x many DCs; multi-DC suites run
against exactly this shape, reference test/utils/test_utils.erl:428-450
[dev1,dev2] + [dev3] + [dev4])."""

import time

import pytest

from antidote_tpu.clocks import vc_max
from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.cluster.federation import (
    NodeInterDc,
    connect_federation,
    dc_descriptor,
)
from antidote_tpu.config import Config
from antidote_tpu.interdc import InProcBus


def make_dc(bus, tmp_path, dc_id, n_nodes=2, n_partitions=4):
    servers = [
        NodeServer(f"{dc_id}_n{i + 1}",
                   data_dir=str(tmp_path / f"{dc_id}_n{i + 1}"),
                   config=Config(n_partitions=n_partitions,
                                 heartbeat_s=0.02,
                                 clock_wait_timeout_s=10.0))
        for i in range(n_nodes)
    ]
    create_dc_cluster(dc_id, n_partitions, servers)
    nids = [NodeInterDc(s, bus) for s in servers]
    return servers, nids


@pytest.fixture
def federation2x2(tmp_path):
    bus = InProcBus()
    servers_a, nids_a = make_dc(bus, tmp_path, "dcA")
    servers_b, nids_b = make_dc(bus, tmp_path, "dcB")
    connect_federation([nids_a, nids_b])
    yield (servers_a, nids_a), (servers_b, nids_b)
    for nid in nids_a + nids_b:
        nid.close()
    for s in servers_a + servers_b:
        s.close()


def pump_all(nids_groups):
    for nids in nids_groups:
        for nid in nids:
            nid.tick_heartbeats()
            nid.pump()
            nid.srv.gossip_tick()


class TestFederatedReplication:
    def test_descriptor_carries_ring_and_members(self, federation2x2):
        (sa, na), _b = federation2x2
        d = dc_descriptor(na)
        assert d.n_members == 2
        assert len(d.ring) == 4
        assert set(d.ring) == {0, 1}

    def test_write_on_each_node_reads_everywhere(self, federation2x2):
        (sa, na), (sb, nb) = federation2x2
        # writes land on BOTH nodes of dcA (keys 0 and 1 live on
        # different members)
        ct = sa[0].api.update_objects_static(
            None, [((0, "counter_pn", "b"), "increment", 5)])
        ct = sa[1].api.update_objects_static(
            ct, [((1, "counter_pn", "b"), "increment", 7)])
        # every node of BOTH DCs converges at the causal clock
        deadline = time.monotonic() + 15.0
        for srv in sa + sb:
            while True:
                try:
                    vals, _ = srv.api.read_objects_static(
                        ct, [(0, "counter_pn", "b"),
                             (1, "counter_pn", "b")])
                    assert vals == [5, 7], srv.node_id
                    break
                except TimeoutError:
                    assert time.monotonic() < deadline, srv.node_id
                    pump_all([na, nb])

    def test_cross_dc_causal_chain(self, federation2x2):
        (sa, na), (sb, nb) = federation2x2
        ct = sa[0].api.update_objects_static(
            None, [((2, "set_aw", "b"), "add", "x")])
        # dcB extends causally after seeing dcA's write
        deadline = time.monotonic() + 15.0
        while True:
            try:
                ct2 = sb[0].api.update_objects_static(
                    ct, [((2, "set_aw", "b"), "add", "y")])
                break
            except TimeoutError:
                assert time.monotonic() < deadline
                pump_all([na, nb])
        while True:
            try:
                vals, _ = sa[1].api.read_objects_static(
                    ct2, [(2, "set_aw", "b")])
                assert vals[0] == ["x", "y"]
                break
            except TimeoutError:
                assert time.monotonic() < deadline
                pump_all([na, nb])

    def test_concurrent_writes_converge(self, federation2x2):
        (sa, na), (sb, nb) = federation2x2
        base = sa[0].api.update_objects_static(
            None, [((3, "set_aw", "b"), "add", "s")])
        ct1 = sa[1].api.update_objects_static(
            base, [((3, "set_aw", "b"), "add", "a")])
        deadline = time.monotonic() + 15.0
        while True:
            try:
                ct2 = sb[1].api.update_objects_static(
                    base, [((3, "set_aw", "b"), "add", "b")])
                break
            except TimeoutError:
                assert time.monotonic() < deadline
                pump_all([na, nb])
        merged = vc_max([ct1, ct2])
        views = []
        for srv in sa + sb:
            while True:
                try:
                    vals, _ = srv.api.read_objects_static(
                        merged, [(3, "set_aw", "b")])
                    views.append(vals[0])
                    break
                except TimeoutError:
                    assert time.monotonic() < deadline
                    pump_all([na, nb])
        assert all(v == ["a", "b", "s"] for v in views), views

    def test_stable_snapshot_covers_both_dcs_on_every_node(
            self, federation2x2):
        (sa, na), (sb, nb) = federation2x2
        for nid in na + nb:
            st = nid.srv.plane.get_stable_snapshot()
            assert st.get_dc("dcA") > 0 and st.get_dc("dcB") > 0, (
                nid.srv.node_id, dict(st))


class TestFederatedGapRepair:
    def test_lost_frames_repair_from_owning_node(self, tmp_path):
        """Frames dropped inbound to dcB: the opid gap triggers a log
        read routed to the REMOTE NODE owning the partition (the
        descriptor ring), not a random member."""
        bus = InProcBus()
        sa, na = make_dc(bus, tmp_path, "dcA")
        sb, nb = make_dc(bus, tmp_path, "dcB")
        connect_federation([na, nb])
        try:
            ct = sa[0].api.update_objects_static(
                None, [((0, "counter_pn", "b"), "increment", 1)])
            # silently drop everything inbound to both dcB members
            for nid in nb:
                bus.set_drop_rx((nid.dc_id, nid.member_index), True)
            for i in range(4):
                ct = sa[0].api.update_objects_static(
                    ct, [((0, "counter_pn", "b"), "increment", 1)])
            for nid in nb:
                bus.set_drop_rx((nid.dc_id, nid.member_index), False)
            # the next frame exposes the gap; repair refetches 2..5
            ct = sa[0].api.update_objects_static(
                ct, [((0, "counter_pn", "b"), "increment", 1)])
            deadline = time.monotonic() + 15.0
            while True:
                try:
                    vals, _ = sb[0].api.read_objects_static(
                        ct, [(0, "counter_pn", "b")])
                    assert vals[0] == 6
                    break
                except TimeoutError:
                    assert time.monotonic() < deadline
                    for group in (na, nb):
                        for nid in group:
                            nid.tick_heartbeats()
                            nid.pump()
                            nid.srv.gossip_tick()
        finally:
            for nid in na + nb:
                nid.close()
            for s in sa + sb:
                s.close()


class TestFederatedMemberRestart:
    def test_member_restart_reobserves_and_catches_up(self, tmp_path):
        """One member of dcB restarts mid-federation: it reloads its
        cluster plan from disk, re-observes the federation, and its
        slice catches up on everything committed while it was down
        (watermark-seeded resume + gap repair, reference
        check_node_restart src/inter_dc_manager.erl:156-201)."""
        bus = InProcBus()
        sa, na = make_dc(bus, tmp_path, "dcA")
        sb, nb = make_dc(bus, tmp_path, "dcB")
        connect_federation([na, nb])
        try:
            ct = sa[0].api.update_objects_static(
                None, [((0, "counter_pn", "b"), "increment", 1)])
            # kill dcB's member 0 (owner of partitions 0 and 2)
            victim_srv, victim_nid = sb[0], nb[0]
            victim_nid.close()
            victim_srv.close()
            # dcA keeps committing while the member is down
            for _ in range(5):
                ct = sa[0].api.update_objects_static(
                    ct, [((0, "counter_pn", "b"), "increment", 1)])
            # restart from the same data dir: the persisted plan
            # re-assembles the cluster node; the harness re-attaches
            # the inter-DC plane and re-observes the federation
            sb0 = NodeServer("dcB_n1",
                             data_dir=str(tmp_path / "dcB_n1"),
                             config=Config(n_partitions=4,
                                           heartbeat_s=0.02,
                                           clock_wait_timeout_s=10.0))
            assert sb0.node is not None  # plan reloaded from disk
            # NodeInterDc auto-re-observes the persisted federation
            # descriptors (reference check_node_restart reconnects DCs)
            nb0 = NodeInterDc(sb0, bus)
            assert "dcA" in nb0.remote
            nb0.start()
            sb[0], nb[0] = sb0, nb0
            # the restarted member serves its slice at the causal clock
            deadline = time.monotonic() + 20.0
            while True:
                try:
                    vals, _ = sb0.api.read_objects_static(
                        ct, [(0, "counter_pn", "b")])
                    assert vals[0] == 6
                    break
                except TimeoutError:
                    assert time.monotonic() < deadline
                    pump_all([na, nb])
        finally:
            for nid in na + nb:
                nid.close()
            for s in sa + sb:
                s.close()

"""Owner-side downstream generation (manager._resolve_raw_ops): a
remote coordinator ships RAW operations of state-requiring types; the
owner partition generates the effect against its local materialized
state — the reference's clocksi_downstream runs at the vnode
(src/clocksi_downstream.erl:41-68).

What must hold: the generated effects are semantically identical to
coordinator-side generation (add-wins supersession, observed-remove
cancellation), reads inside the same transaction still observe the
txn's own raw updates (read-your-writes degrades them on demand), and
multi-op transactions generate in program order at the owner.
"""

import pytest

from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.config import Config


@pytest.fixture
def duo(tmp_path):
    servers = [
        NodeServer(f"n{i}", data_dir=str(tmp_path / f"n{i}"),
                   config=Config(n_partitions=4, heartbeat_s=0.05))
        for i in range(2)
    ]
    create_dc_cluster("dc1", 4, servers)
    yield servers
    for s in servers:
        s.close()


def _owner_of(servers, key):
    ring = servers[0].node.ring
    return ring[key % len(ring)]


def _remote_key(servers, coordinator_idx, base=0):
    """A key whose partition is owned by the OTHER node."""
    me = servers[coordinator_idx].node_id
    k = base
    while _owner_of(servers, k) == me:
        k += 1
    return k


def test_remote_set_add_remove_generates_at_owner(duo):
    api = duo[0].api
    k = _remote_key(duo, 0)
    bo = (k, "set_aw", "b")

    tx = api.start_transaction()
    api.update_objects([(bo, "add", b"x"), (bo, "add", b"y")], tx)
    # the raw ops are pending at the coordinator, not yet effects
    assert k in tx.raw_keys
    cvc = api.commit_transaction(tx)

    # observed-remove must cancel the add it SAW (generated at the
    # owner against the committed state)
    tx = api.start_transaction(clock=cvc)
    api.update_objects([(bo, "remove", b"x")], tx)
    cvc = api.commit_transaction(tx)

    tx = api.start_transaction(clock=cvc)
    assert api.read_objects([bo], tx) == [[b"y"]]
    api.commit_transaction(tx)

    # and the OWNER node agrees (same effects applied everywhere)
    api1 = duo[1].api
    tx = api1.start_transaction(clock=cvc)
    assert api1.read_objects([bo], tx) == [[b"y"]]
    api1.commit_transaction(tx)


def test_read_your_raw_writes_in_same_txn(duo):
    api = duo[0].api
    k = _remote_key(duo, 0, base=100)
    bo = (k, "set_aw", "b")

    tx = api.start_transaction()
    api.update_objects([(bo, "add", b"a")], tx)
    assert k in tx.raw_keys
    # the read degrades the raw op into an effect and observes it
    assert api.read_objects([bo], tx) == [[b"a"]]
    assert k not in tx.raw_keys
    # a later update in the same txn must see the degraded effect too
    api.update_objects([(bo, "remove", b"a")], tx)
    assert api.read_objects([bo], tx) == [[]]
    cvc = api.commit_transaction(tx)

    tx = api.start_transaction(clock=cvc)
    assert api.read_objects([bo], tx) == [[]]
    api.commit_transaction(tx)


def test_mvreg_assign_remote_owner_generated(duo):
    api = duo[0].api
    k = _remote_key(duo, 0, base=200)
    bo = (k, "register_mv", "b")

    tx = api.start_transaction()
    api.update_objects([(bo, "assign", b"v1")], tx)
    cvc = api.commit_transaction(tx)

    # a second assign must supersede v1 (it observed v1's dot at the
    # owner): exactly one live value remains
    tx = api.start_transaction(clock=cvc)
    api.update_objects([(bo, "assign", b"v2")], tx)
    cvc = api.commit_transaction(tx)

    for srv in duo:
        tx = srv.api.start_transaction(clock=cvc)
        assert srv.api.read_objects([bo], tx) == [[b"v2"]]
        srv.api.commit_transaction(tx)


def test_mixed_local_remote_txn_converges(duo):
    """One txn spanning a local and a remote state-requiring update:
    2PC with one raw participant; both nodes read the same values."""
    api = duo[0].api
    k_remote = _remote_key(duo, 0, base=300)
    k_local = k_remote + 1
    while _owner_of(duo, k_local) != duo[0].node_id:
        k_local += 1
    tx = api.start_transaction()
    api.update_objects([((k_remote, "set_aw", "b"), "add", b"r"),
                        ((k_local, "set_aw", "b"), "add", b"l")], tx)
    cvc = api.commit_transaction(tx)
    for srv in duo:
        tx = srv.api.start_transaction(clock=cvc)
        got = srv.api.read_objects(
            [(k_remote, "set_aw", "b"), (k_local, "set_aw", "b")], tx)
        assert got == [[b"r"], [b"l"]]
        srv.api.commit_transaction(tx)

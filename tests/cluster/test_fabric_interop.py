"""Native node fabric — answer-plane differentials, exactly-once
across the native/Python boundary, and the fabric_native knob routing
(ISSUE 12).

The native answer plane serves registered read-only RPCs from C++
event threads against published reply bytes; everything here pins its
contract: a native-answered read is BYTE-IDENTICAL to the Python
handler's answer (the published bytes ARE its reply — asserted by
repeating a request and proving the handler never ran the second
time), retries re-send the same rid and stay exactly-once whether the
at-most-once cache or the answer table replies, invalidation events
(truncation, ring moves) re-route repeats through Python, and
``Config.fabric_native=False`` routes every call site through the
exact legacy plane."""

import pytest

from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.cluster.link import NodeLink
from antidote_tpu.cluster.node import build_link
from antidote_tpu.cluster import nativelink
from antidote_tpu.config import Config
from antidote_tpu.txn.manager import PartitionManager

pytestmark = pytest.mark.skipif(
    not nativelink.native_available(),
    reason="no C++ toolchain: the native fabric cannot build")


def _cfg(**kw):
    kw.setdefault("n_partitions", 4)
    kw.setdefault("heartbeat_s", 0.05)
    return Config(**kw)


@pytest.fixture
def native2(tmp_path):
    servers = [
        NodeServer(f"nv{i}", data_dir=str(tmp_path / f"nv{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    create_dc_cluster("dc1", 4, servers)
    yield servers
    for s in servers:
        s.close()


def _owner_of(servers, p):
    for s in servers:
        if isinstance(s.node.partitions[p], PartitionManager):
            return s
    raise AssertionError(f"no local owner for partition {p}")


def _other(servers, srv):
    return next(s for s in servers if s is not srv)


def _commit(srv, key, n=3):
    api = srv.api
    clock = None
    for _ in range(n):
        tx = api.start_transaction(clock)
        api.update_objects([((key, "counter_pn", "b"), "increment", 1)],
                           tx)
        clock = api.commit_transaction(tx)
    return clock


# ---------------------------------------------------- knob routing

class TestFabricRouting:
    def test_false_routes_to_python_nodelink(self):
        link = build_link("r1", config=Config(fabric_native=False))
        try:
            assert type(link) is NodeLink
        finally:
            link.close()

    def test_auto_routes_to_native(self):
        link = build_link("r2", config=Config())
        try:
            assert type(link) is nativelink.NativeNodeLink
        finally:
            link.close()

    def test_true_requires_native(self, monkeypatch):
        monkeypatch.setattr(nativelink, "native_available",
                            lambda: False)
        with pytest.raises(RuntimeError, match="fabric_native"):
            build_link("r3", config=Config(fabric_native=True))

    def test_true_without_compiler_falls_back_under_auto(
            self, monkeypatch):
        monkeypatch.setattr(nativelink, "native_available",
                            lambda: False)
        link = build_link("r4", config=Config(fabric_native="auto"))
        try:
            assert type(link) is NodeLink
        finally:
            link.close()

    def test_unknown_knob_value_refused(self):
        """fabric_native="python" (a plausible guess at a legacy knob
        value — and a valid DIRECT TcpTransport mode) must fail
        loudly: treated as "auto" it would route the node fabric
        NATIVE, the opposite of the request."""
        from antidote_tpu.interdc.tcp import transport_from_config

        for bad in ("python", "native", None):
            with pytest.raises(ValueError, match="fabric_native"):
                build_link("bx", config=_cfg(fabric_native=bad))
            with pytest.raises(ValueError, match="fabric_native"):
                transport_from_config(_cfg(fabric_native=bad))

    def test_transport_factory_routes_fabric_native(self):
        from antidote_tpu.interdc.tcp import transport_from_config

        legacy = transport_from_config(Config(fabric_native=False))
        assert legacy._native_pub is False and not legacy._staged
        auto = transport_from_config(Config())
        assert auto._native_pub == "auto" and auto._staged

    def test_mixed_fabric_cluster_refused(self, tmp_path):
        """The framings do not interoperate: assembling a cluster
        whose members disagree on the fabric fails loudly instead of
        half-connecting (the documented align-Config contract)."""
        a = NodeServer("mx0", data_dir=str(tmp_path / "mx0"),
                       config=_cfg())
        b = NodeServer("mx1", data_dir=str(tmp_path / "mx1"),
                       config=_cfg(fabric_native=False))
        try:
            with pytest.raises(RuntimeError, match="fabric"):
                create_dc_cluster("dcx", 4, [a, b])
        finally:
            a.close()
            b.close()

    def test_python_cluster_answer_plane_stays_cold(self, tmp_path):
        """fabric_native=False: the legacy NodeLink has no answer
        plane to arm — _refresh_fabric_plane is a structural no-op and
        the FABRIC_* counters have nothing to pull."""
        servers = [
            NodeServer(f"pc{i}", data_dir=str(tmp_path / f"pc{i}"),
                       config=_cfg(fabric_native=False))
            for i in range(2)
        ]
        create_dc_cluster("dcp", 4, servers)
        try:
            for s in servers:
                assert type(s.link) is NodeLink
                assert not hasattr(s.link, "fabric_counters")
            _commit(servers[0], "cold", n=2)
        finally:
            for s in servers:
                s.close()


# ------------------------------------- answer-plane differentials

class TestAnswerPlaneDifferential:
    """For every registered read-only RPC: ask twice with fresh rids.
    The first answer comes from the Python handler (and publishes);
    the second must come from the C++ event thread — the endpoint's
    native_answered counter moves and the answer is IDENTICAL (the
    published bytes are the handler's own reply, so equality here is
    byte-identity of the reply frames)."""

    def _ask_twice(self, asker, owner, kind, payload):
        c0 = owner.link.fabric_counters()["native_answered"]
        r1 = asker.link.request(owner.node_id, kind, payload)
        mid = owner.link.fabric_counters()["native_answered"]
        r2 = asker.link.request(owner.node_id, kind, payload)
        c1 = owner.link.fabric_counters()["native_answered"]
        assert mid == c0, f"{kind}: first ask must take the Python path"
        assert c1 == mid + 1, f"{kind}: repeat was not answered natively"
        return r1, r2

    def test_snap_read_at_clock(self, native2):
        ct = _commit(native2[0], "sk", n=3)
        p = native2[0].node.partition_index("sk")
        owner = _owner_of(native2, p)
        asker = _other(native2, owner)
        payload = ([("sk", "counter_pn", "b")], dict(ct))
        r1, r2 = self._ask_twice(asker, owner, "snap_read", payload)
        assert r1 == r2
        values, vc = r1
        assert values[0] == 3

    def test_snap_read_clockless_never_published(self, native2):
        """A clockless read serves the MOVING stable snapshot — the
        answer policy refuses it, so repeats keep entering Python."""
        _commit(native2[0], "mk", n=1)
        p = native2[0].node.partition_index("mk")
        owner = _owner_of(native2, p)
        asker = _other(native2, owner)
        payload = ([("mk", "counter_pn", "b")], None)
        c0 = owner.link.fabric_counters()["native_answered"]
        asker.link.request(owner.node_id, "snap_read", payload)
        asker.link.request(owner.node_id, "snap_read", payload)
        assert owner.link.fabric_counters()["native_answered"] == c0

    def test_gap_repair_range_read(self, native2):
        _commit(native2[0], "gk", n=4)
        for p in range(4):
            owner = _owner_of(native2, p)
            pm = owner.node.partitions[p]
            last = pm.log.op_counters.get(owner.node.dc_id, 0)
            if last == 0:
                continue
            asker = _other(native2, owner)
            r1, r2 = self._ask_twice(asker, owner, "idc_log_read",
                                     (p, 1, last))
            assert r1 == r2
            assert isinstance(r1, list) and r1
            return
        raise AssertionError("no partition carried committed records")

    def test_handoff_byte_read(self, native2):
        _commit(native2[0], "hk", n=2)
        for p in range(4):
            owner = _owner_of(native2, p)
            pm = owner.node.partitions[p]
            if not pm.log.op_counters.get(owner.node.dc_id, 0):
                continue
            asker = _other(native2, owner)
            r1, r2 = self._ask_twice(asker, owner, "handoff_fetch",
                                     (p, 0, 1 << 16))
            assert r1 == r2
            data, end, base = r1
            assert data and end > 0
            return
        raise AssertionError("no partition carried log bytes")

    def test_ring_change_invalidates_published_answers(self, native2):
        """The wholesale invalidation: after a ring re-plan every
        published answer is dropped — the next identical request
        re-enters Python (and re-publishes against the new state)."""
        ct = _commit(native2[0], "ik", n=2)
        p = native2[0].node.partition_index("ik")
        owner = _owner_of(native2, p)
        asker = _other(native2, owner)
        payload = ([("ik", "counter_pn", "b")], dict(ct))
        r1, r2 = self._ask_twice(asker, owner, "snap_read", payload)
        owner._refresh_fabric_plane()  # what every ring-change path calls
        assert owner.link.fabric_counters()["published"] == 0
        c0 = owner.link.fabric_counters()["native_answered"]
        r3 = asker.link.request(owner.node_id, "snap_read", payload)
        assert owner.link.fabric_counters()["native_answered"] == c0
        # the VALUES at an explicit covered clock are fixed forever;
        # the fresh Python answer mints a fresh covering snapshot VC,
        # so only the value set is compared
        assert r3[0] == r1[0]

    def test_truncation_hook_is_wired(self, native2):
        """Every local partition log's on_truncate clears the answer
        table — reclaimed bytes may back published idc_log_read /
        handoff_fetch answers."""
        for srv in native2:
            for pm in srv.node._local_partitions():
                assert pm.log.on_truncate is not None
            ct = _commit(srv, "tk", n=1)
            p = srv.node.partition_index("tk")
            owner = _owner_of(native2, p)
            asker = _other(native2, owner)
            r1, r2 = TestAnswerPlaneDifferential._ask_twice(
                self, asker, owner, "snap_read",
                ([("tk", "counter_pn", "b")], dict(ct)))
            assert owner.link.fabric_counters()["published"] > 0
            # fire the hook exactly as a checkpoint truncation would
            next(iter(owner.node._local_partitions())).log.on_truncate()
            assert owner.link.fabric_counters()["published"] == 0
            return

    def test_stale_generation_cannot_republish(self, native2):
        """The publish-after-invalidate race, pinned at the C ABI: a
        worker that read the invalidation generation BEFORE computing
        its answer cannot install it after a clear bumped the
        generation — the stale answer would otherwise resurrect into
        the freshly-cleared table and serve old-layout bytes natively
        until the NEXT invalidation."""
        link = native2[0].link
        lib, h = link._lib, link._h
        key, reply = b"fab-gen-key", b"fab-gen-reply"
        gen = lib.nl_pub_gen(h)
        # the clear lands between the worker's gen capture and its
        # publish (exactly the truncation-mid-handler interleaving)
        lib.nl_publish_clear(h)
        lib.nl_publish(h, key, len(key), reply, len(reply), gen, 0)
        assert link.fabric_counters()["published"] == 0
        # the same publish at the CURRENT generation installs fine
        lib.nl_publish(h, key, len(key), reply, len(reply),
                       lib.nl_pub_gen(h), 0)
        assert link.fabric_counters()["published"] == 1
        link.invalidate_answers()
        assert link.fabric_counters()["published"] == 0


# --------------------------- exactly-once across the boundary

class TestExactlyOnceAcrossBoundary:
    def test_same_rid_retry_of_published_read(self, native2):
        """A transport-level retry re-sends the SAME encoded request
        bytes.  After the first answer published, the duplicate is
        answered by the event thread — same reply, handler untouched;
        the at-most-once guarantee holds with the cache never
        consulted because the published bytes ARE the cached reply."""
        from antidote_tpu.cluster.nativelink import _Handle

        ct = _commit(native2[0], "rk", n=2)
        p = native2[0].node.partition_index("rk")
        owner = _owner_of(native2, p)
        asker = _other(native2, owner)
        payload = ([("rk", "counter_pn", "b")], dict(ct))
        h = asker.link.start_request(owner.node_id, "snap_read",
                                     payload)
        r1 = asker.link.finish_request(h)
        c0 = owner.link.fabric_counters()["native_answered"]
        # replay the identical request bytes — the rid is the same,
        # exactly what the one-retry path does after a transport error
        corr = asker.link._lib.nl_send(asker.link._h, h.idx, h.data,
                                       len(h.data))
        h2 = _Handle(h.peer_id, h.idx, h.data, corr)
        r2 = asker.link.finish_request(h2)
        assert r2 == r1
        assert owner.link.fabric_counters()["native_answered"] == c0 + 1

    def test_same_rid_retry_of_unpublished_rpc_hits_amo(self, native2):
        """Non-publishable RPCs keep the at-most-once discipline: the
        duplicate rid is answered from the server's AMO cache without
        re-executing the handler (gossip mutates peer state — run-once
        matters), never from the answer table."""
        from antidote_tpu.cluster.nativelink import _Handle

        owner, asker = native2[0], native2[1]
        summary = asker.plane.local_summary()
        h = asker.link.start_request(owner.node_id, "gossip",
                                     (asker.node_id, summary))
        r1 = asker.link.finish_request(h)
        c0 = owner.link.fabric_counters()["native_answered"]
        corr = asker.link._lib.nl_send(asker.link._h, h.idx, h.data,
                                       len(h.data))
        r2 = asker.link.finish_request(
            _Handle(h.peer_id, h.idx, h.data, corr))
        assert r2 == r1
        # answered from the AMO cache (Python), not the native table
        assert owner.link.fabric_counters()["native_answered"] == c0

"""Causal-consistency checker at federation scale: two DCs x two node
servers each, writers on one member and reader sessions on the OTHER
member of each DC — every visibility set crosses the intra-DC node
fabric AND the inter-DC stream before validation (rules and trace
generator: tests/causal_core.py; the two-DC variant documents them,
tests/multidc/test_causal_checker.py)."""

import causal_core as cc
from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.cluster.federation import (
    NodeInterDc,
    connect_federation,
)
from antidote_tpu.config import Config
from antidote_tpu.interdc import InProcBus


def _make_dc(bus, tmp_path, dc_id, n_nodes=2, n_partitions=4, **kw):
    servers = [
        NodeServer(f"{dc_id}_n{i + 1}",
                   data_dir=str(tmp_path / f"{dc_id}_n{i + 1}"),
                   config=Config(n_partitions=n_partitions,
                                 heartbeat_s=0.005,
                                 clock_wait_timeout_s=10.0, **kw))
        for i in range(n_nodes)
    ]
    create_dc_cluster(dc_id, n_partitions, servers)
    nids = [NodeInterDc(s, bus) for s in servers]
    return servers, nids


import pytest


@pytest.mark.parametrize("placement", ["none", "ring"])
def test_causal_visibility_federation(tmp_path, placement):
    kw = {"device_placement": "ring", "device_flush_ops": 8} \
        if placement == "ring" else {}
    bus = InProcBus()
    servers_a, nids_a = _make_dc(bus, tmp_path, "dcA", **kw)
    servers_b, nids_b = _make_dc(bus, tmp_path, "dcB", **kw)
    try:
        connect_federation([nids_a, nids_b])
        # writers on member 1, reader sessions on member 2: every
        # cross-DC write is served to the reader via handoff through
        # the OTHER node's ring slice as well
        writes, reads, abandoned = cc.run_trace(
            [servers_a[0].api, servers_b[0].api],
            [servers_a[1].api, servers_b[1].api])
        assert len(writes) >= 2 * cc.N_WRITES
        cc.validate(writes, reads)
    finally:
        for nid in nids_a + nids_b:
            nid.close()
        for s in servers_a + servers_b:
            s.close()


def test_causal_visibility_across_member_restart(tmp_path):
    """The checker's rules must hold across a crash/restart of a
    reader-side member mid-trace: recovery (journaled plan, stable
    floor, re-observed federation) may make reads time out while the
    member is down — an availability gap — but every read that
    SUCCEEDS, before, during, or after the restart, must still satisfy
    the causal floor and snapshot closure (restart recovery that
    forgot the stable floor or replayed the log short would fail
    here)."""
    import threading
    import time as _t

    bus = InProcBus()
    servers_a, nids_a = _make_dc(bus, tmp_path, "dcA")
    servers_b, nids_b = _make_dc(bus, tmp_path, "dcB")
    stop = threading.Event()
    restarted = []

    def chaos():
        # one crash/restart of dcB's second member (a reader endpoint)
        _t.sleep(0.4)
        victim_nid, victim_srv = nids_b[1], servers_b[1]
        victim_nid.close()
        victim_srv.close()
        _t.sleep(0.2)
        srv = NodeServer("dcB_n2",
                         data_dir=str(tmp_path / "dcB_n2"),
                         config=Config(n_partitions=4,
                                       heartbeat_s=0.005,
                                       clock_wait_timeout_s=10.0))
        nid = NodeInterDc(srv, bus)
        nid.start()
        servers_b[1], nids_b[1] = srv, nid
        restarted.append(srv)

    class RestartTolerantReader:
        """Endpoint proxy following the CURRENT incarnation of the
        member; reads hitting the down-window raise and are retried
        (only successful reads enter the validated trace)."""

        def __init__(self, servers, idx):
            self.servers, self.idx = servers, idx

        def read_objects_static(self, clock, objs):
            deadline = _t.monotonic() + 30.0
            while True:
                try:
                    return self.servers[self.idx].api \
                        .read_objects_static(clock, objs)
                except Exception:
                    if _t.monotonic() > deadline:
                        raise
                    _t.sleep(0.05)

    try:
        connect_federation([nids_a, nids_b])
        t = threading.Thread(target=chaos)
        t.start()
        writes, reads, abandoned = cc.run_trace(
            [servers_a[0].api, servers_b[0].api],
            [servers_a[1].api, RestartTolerantReader(servers_b, 1)])
        t.join()
        stop.set()
        assert restarted, "chaos thread never restarted the member"
        assert len(writes) >= 2 * cc.N_WRITES
        cc.validate(writes, reads)
    finally:
        for nid in nids_a + nids_b:
            nid.close()
        for s in servers_a + servers_b:
            s.close()

"""Causal-consistency checker at federation scale: two DCs x two node
servers each, writers on one member and reader sessions on the OTHER
member of each DC — every visibility set crosses the intra-DC node
fabric AND the inter-DC stream before validation (rules and trace
generator: tests/causal_core.py; the two-DC variant documents them,
tests/multidc/test_causal_checker.py)."""

import causal_core as cc
from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.cluster.federation import (
    NodeInterDc,
    connect_federation,
)
from antidote_tpu.config import Config
from antidote_tpu.interdc import InProcBus


def _make_dc(bus, tmp_path, dc_id, n_nodes=2, n_partitions=4):
    servers = [
        NodeServer(f"{dc_id}_n{i + 1}",
                   data_dir=str(tmp_path / f"{dc_id}_n{i + 1}"),
                   config=Config(n_partitions=n_partitions,
                                 heartbeat_s=0.005,
                                 clock_wait_timeout_s=10.0))
        for i in range(n_nodes)
    ]
    create_dc_cluster(dc_id, n_partitions, servers)
    nids = [NodeInterDc(s, bus) for s in servers]
    return servers, nids


def test_causal_visibility_federation(tmp_path):
    bus = InProcBus()
    servers_a, nids_a = _make_dc(bus, tmp_path, "dcA")
    servers_b, nids_b = _make_dc(bus, tmp_path, "dcB")
    try:
        connect_federation([nids_a, nids_b])
        # writers on member 1, reader sessions on member 2: every
        # cross-DC write is served to the reader via handoff through
        # the OTHER node's ring slice as well
        writes, reads = cc.run_trace(
            [servers_a[0].api, servers_b[0].api],
            [servers_a[1].api, servers_b[1].api])
        assert len(writes) >= 2 * cc.N_WRITES
        cc.validate(writes, reads)
    finally:
        for nid in nids_a + nids_b:
            nid.close()
        for s in servers_a + servers_b:
            s.close()

"""Cross-node handoff: re-planning a LIVE multi-node DC's ring.

The reference's riak_core transfers partition ownership between live
nodes with handoff folds that run while the vnode keeps serving
(reference src/logging_vnode.erl:781-812, claim/plan staged join
src/antidote_dc_manager.erl:53-81).  Here: the new owner pulls the
partition's CRC-framed log in chunks over the node fabric, the old
owner drains (prepared transactions resolve, new mutating work parks),
pushes the final tail, retires behind a typed wrong-owner redirect,
and the driver commits the new plan on every member.

What must hold: a cluster GROWS while writers commit continuously and
no committed transaction is lost; proxies self-heal across the move;
the stable snapshot never regresses; a restarted former owner honors
the transfer.
"""

import threading
import time

import pytest

from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.cluster.remote import RemotePartition
from antidote_tpu.config import Config
from antidote_tpu.txn.coordinator import TransactionAborted
from antidote_tpu.txn.manager import PartitionManager


def _cfg():
    return Config(n_partitions=8, heartbeat_s=0.05)


def _counter_total(api, keys):
    tx = api.start_transaction()
    vals = api.read_objects([(k, "counter_pn", "b") for k in keys], tx)
    api.commit_transaction(tx)
    return sum(vals)


def test_grow_cluster_under_continuous_writes(tmp_path):
    """2-node DC grows to 3 while 3 writer threads commit without
    pause; every committed increment survives the move."""
    servers = [
        NodeServer(f"n{i}", data_dir=str(tmp_path / f"n{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    create_dc_cluster("dc1", 8, servers)
    s3 = NodeServer("n2", data_dir=str(tmp_path / "n2x"), config=_cfg())
    try:
        servers[0].add_member("n2", s3.addr)
        assert s3.node is not None
        assert s3.node.local_partition_indices() == []

        stop = threading.Event()
        committed = [0, 0, 0]
        aborted = [0, 0, 0]
        errs = []

        def writer(slot, api, seed):
            k = 0
            try:
                while not stop.is_set():
                    key = (seed * 97 + k) % 64
                    k += 1
                    try:
                        tx = api.start_transaction()
                        api.update_objects(
                            [((key, "counter_pn", "b"), "increment", 1),
                             ((100 + key, "set_aw", "b"), "add",
                              f"w{slot}")], tx)
                        api.commit_transaction(tx)
                        committed[slot] += 1
                    except TransactionAborted:
                        aborted[slot] += 1
            except BaseException as e:  # noqa: BLE001 — asserted below
                errs.append(e)

        apis = [servers[0].api, servers[1].api, s3.api]
        threads = [threading.Thread(target=writer, args=(i, a, i))
                   for i, a in enumerate(apis)]
        for t in threads:
            t.start()
        time.sleep(0.5)

        # the re-plan: n2 takes partitions 2 and 5 (one from each)
        new_ring = dict(servers[0].node.ring)
        assert new_ring[2] == "n0" and new_ring[5] == "n1"
        new_ring[2] = "n2"
        new_ring[5] = "n2"
        servers[0].rebalance(new_ring)

        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        total = sum(committed)
        assert total > 50  # writers really ran through the move

        # ownership moved everywhere
        for srv in servers + [s3]:
            assert srv.node.ring[2] == "n2"
            assert srv.node.ring[5] == "n2"
        assert isinstance(s3.node.partitions[2], PartitionManager)
        assert isinstance(s3.node.partitions[5], PartitionManager)
        assert isinstance(servers[0].node.partitions[2], RemotePartition)

        # nothing lost: the counters' grand total equals the number of
        # committed increment transactions, read from EVERY member
        for srv in servers + [s3]:
            assert _counter_total(srv.api, range(64)) == total
    finally:
        for srv in servers + [s3]:
            srv.close()


def test_moved_partition_serves_history_and_new_writes(tmp_path):
    servers = [
        NodeServer(f"m{i}", data_dir=str(tmp_path / f"m{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    extra = NodeServer("m2", data_dir=str(tmp_path / "m2"),
                       config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[extra])
        api = servers[0].api
        # history on partition 3 (owned by m1) before the move
        tx = api.start_transaction()
        api.update_objects(
            [((3 + 8 * i, "counter_pn", "b"), "increment", i + 1)
             for i in range(4)], tx)
        cvc = api.commit_transaction(tx)

        new_ring = dict(servers[0].node.ring)
        old_owner = new_ring[3]
        new_ring[3] = "m2"
        servers[0].rebalance(new_ring)

        # history is served by the new owner
        tx = extra.api.start_transaction(clock=cvc)
        vals = extra.api.read_objects(
            [((3 + 8 * i), "counter_pn", "b") for i in range(4)], tx)
        extra.api.commit_transaction(tx)
        assert vals == [1, 2, 3, 4]

        # new writes through a STALE proxy self-heal onto the new owner
        stale_api = servers[0 if old_owner != "m0" else 1].api
        tx = stale_api.start_transaction()
        stale_api.update_objects([((3, "counter_pn", "b"),
                                   "increment", 10)], tx)
        cvc = stale_api.commit_transaction(tx)
        tx = extra.api.start_transaction(clock=cvc)
        assert extra.api.read_objects([(3, "counter_pn", "b")], tx) \
            == [11]
        extra.api.commit_transaction(tx)

        # stable snapshot still advances after the move (pins cleared)
        s0 = servers[0].plane.get_stable_snapshot().get_dc("dc1")
        time.sleep(0.3)
        s1 = servers[0].plane.get_stable_snapshot().get_dc("dc1")
        assert s1 >= s0
    finally:
        for srv in servers + [extra]:
            srv.close()


def test_crash_between_cutover_and_replan_resolves_via_journal(tmp_path):
    """The old owner dies AFTER pushing the partition to the new owner
    but BEFORE the global re-plan: its restart finds the handoff-out
    journal, asks the new owner, and retires behind a redirect instead
    of serving a log it no longer has (split-brain guard)."""
    servers = [
        NodeServer(f"j{i}", data_dir=str(tmp_path / f"j{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    extra = NodeServer("j2", data_dir=str(tmp_path / "j2"),
                       config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[extra])
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects([((0, "counter_pn", "b"), "increment", 5)],
                           tx)
        api.commit_transaction(tx)
        assert servers[0].node.ring[0] == "j0"

        # transfer partition 0 to j2 WITHOUT the ring_update step (the
        # driver "crashed" right after the cutover)
        cursor = servers[0]._rpc("j2", "handoff_begin", (0, "j0"))
        servers[0]._rpc("j0", "handoff_cutover", (0, "j2", cursor))
        assert servers[0].meta.get("handoff_out") == {0: "j2"}

        servers[0].close()
        j0b = NodeServer("j0", data_dir=str(tmp_path / "j0"),
                         config=_cfg())
        try:
            # the journal + peer query retired the moved partition
            assert isinstance(j0b.node.partitions[0], RemotePartition)
            assert j0b.node.ring[0] == "j2"
            tx = j0b.api.start_transaction()
            assert j0b.api.read_objects([(0, "counter_pn", "b")], tx) \
                == [5]
            j0b.api.commit_transaction(tx)
        finally:
            j0b.close()
        servers = servers[1:]
    finally:
        for srv in servers + [extra]:
            srv.close()


def test_former_owner_restart_honors_transfer(tmp_path):
    """The old owner crashes right after the transfer (before/without
    anything else happening) and restarts from its persisted plan: the
    handoff journal + peer query must keep it from serving the moved
    partition."""
    servers = [
        NodeServer(f"r{i}", data_dir=str(tmp_path / f"r{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    extra = NodeServer("r2", data_dir=str(tmp_path / "r2"),
                       config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[extra])
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects([((0, "counter_pn", "b"), "increment", 7)],
                           tx)
        api.commit_transaction(tx)

        new_ring = dict(servers[0].node.ring)
        assert new_ring[0] == "r0"
        new_ring[0] = "r2"
        servers[0].rebalance(new_ring)

        # "crash" r0 and restart it from disk
        servers[0].close()
        r0b = NodeServer("r0", data_dir=str(tmp_path / "r0"),
                         config=_cfg())
        try:
            assert r0b.node.ring[0] == "r2"
            assert isinstance(r0b.node.partitions[0], RemotePartition)
            tx = r0b.api.start_transaction()
            assert r0b.api.read_objects([(0, "counter_pn", "b")], tx) \
                == [7]
            r0b.api.commit_transaction(tx)
        finally:
            r0b.close()
        servers = servers[1:]
    finally:
        for srv in servers + [extra]:
            srv.close()

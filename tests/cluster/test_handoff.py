"""Cross-node handoff: re-planning a LIVE multi-node DC's ring.

The reference's riak_core transfers partition ownership between live
nodes with handoff folds that run while the vnode keeps serving
(reference src/logging_vnode.erl:781-812, claim/plan staged join
src/antidote_dc_manager.erl:53-81).  Here: the new owner pulls the
partition's CRC-framed log in chunks over the node fabric, the old
owner drains (prepared transactions resolve, new mutating work parks),
pushes the final tail, retires behind a typed wrong-owner redirect,
and the driver commits the new plan on every member.

What must hold: a cluster GROWS while writers commit continuously and
no committed transaction is lost; proxies self-heal across the move;
the stable snapshot never regresses; a restarted former owner honors
the transfer.
"""

import threading
import time

import pytest

from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.cluster.remote import RemotePartition
from antidote_tpu.config import Config
from antidote_tpu.txn.coordinator import TransactionAborted
from antidote_tpu.txn.manager import PartitionManager


def _cfg():
    return Config(n_partitions=8, heartbeat_s=0.05)


def _counter_total(api, keys):
    tx = api.start_transaction()
    vals = api.read_objects([(k, "counter_pn", "b") for k in keys], tx)
    api.commit_transaction(tx)
    return sum(vals)


def test_grow_cluster_under_continuous_writes(tmp_path):
    """2-node DC grows to 3 while 3 writer threads commit without
    pause; every committed increment survives the move."""
    servers = [
        NodeServer(f"n{i}", data_dir=str(tmp_path / f"n{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    create_dc_cluster("dc1", 8, servers)
    s3 = NodeServer("n2", data_dir=str(tmp_path / "n2x"), config=_cfg())
    try:
        servers[0].add_member("n2", s3.addr)
        assert s3.node is not None
        assert s3.node.local_partition_indices() == []

        stop = threading.Event()
        committed = [0, 0, 0]
        aborted = [0, 0, 0]
        errs = []

        def writer(slot, api, seed):
            k = 0
            try:
                while not stop.is_set():
                    key = (seed * 97 + k) % 64
                    k += 1
                    try:
                        tx = api.start_transaction()
                        api.update_objects(
                            [((key, "counter_pn", "b"), "increment", 1),
                             ((100 + key, "set_aw", "b"), "add",
                              f"w{slot}")], tx)
                        api.commit_transaction(tx)
                        committed[slot] += 1
                    except TransactionAborted:
                        aborted[slot] += 1
            except BaseException as e:  # noqa: BLE001 — asserted below
                errs.append(e)

        apis = [servers[0].api, servers[1].api, s3.api]
        threads = [threading.Thread(target=writer, args=(i, a, i))
                   for i, a in enumerate(apis)]
        for t in threads:
            t.start()
        time.sleep(0.5)

        # the re-plan: n2 takes partitions 2 and 5 (one from each)
        new_ring = dict(servers[0].node.ring)
        assert new_ring[2] == "n0" and new_ring[5] == "n1"
        new_ring[2] = "n2"
        new_ring[5] = "n2"
        servers[0].rebalance(new_ring)

        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        total = sum(committed)
        assert total > 50  # writers really ran through the move

        # ownership moved everywhere
        for srv in servers + [s3]:
            assert srv.node.ring[2] == "n2"
            assert srv.node.ring[5] == "n2"
        assert isinstance(s3.node.partitions[2], PartitionManager)
        assert isinstance(s3.node.partitions[5], PartitionManager)
        assert isinstance(servers[0].node.partitions[2], RemotePartition)

        # nothing lost: the counters' grand total equals the number of
        # committed increment transactions, read from EVERY member
        for srv in servers + [s3]:
            assert _counter_total(srv.api, range(64)) == total
    finally:
        for srv in servers + [s3]:
            srv.close()


def test_moved_partition_serves_history_and_new_writes(tmp_path):
    servers = [
        NodeServer(f"m{i}", data_dir=str(tmp_path / f"m{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    extra = NodeServer("m2", data_dir=str(tmp_path / "m2"),
                       config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[extra])
        api = servers[0].api
        # history on partition 3 (owned by m1) before the move
        tx = api.start_transaction()
        api.update_objects(
            [((3 + 8 * i, "counter_pn", "b"), "increment", i + 1)
             for i in range(4)], tx)
        cvc = api.commit_transaction(tx)

        new_ring = dict(servers[0].node.ring)
        old_owner = new_ring[3]
        new_ring[3] = "m2"
        servers[0].rebalance(new_ring)

        # history is served by the new owner
        tx = extra.api.start_transaction(clock=cvc)
        vals = extra.api.read_objects(
            [((3 + 8 * i), "counter_pn", "b") for i in range(4)], tx)
        extra.api.commit_transaction(tx)
        assert vals == [1, 2, 3, 4]

        # new writes through a STALE proxy self-heal onto the new owner
        stale_api = servers[0 if old_owner != "m0" else 1].api
        tx = stale_api.start_transaction()
        stale_api.update_objects([((3, "counter_pn", "b"),
                                   "increment", 10)], tx)
        cvc = stale_api.commit_transaction(tx)
        tx = extra.api.start_transaction(clock=cvc)
        assert extra.api.read_objects([(3, "counter_pn", "b")], tx) \
            == [11]
        extra.api.commit_transaction(tx)

        # stable snapshot still advances after the move (pins cleared)
        s0 = servers[0].plane.get_stable_snapshot().get_dc("dc1")
        time.sleep(0.3)
        s1 = servers[0].plane.get_stable_snapshot().get_dc("dc1")
        assert s1 >= s0
    finally:
        for srv in servers + [extra]:
            srv.close()


def test_crash_between_cutover_and_replan_resolves_via_journal(tmp_path):
    """The old owner dies AFTER pushing the partition to the new owner
    but BEFORE the global re-plan: its restart finds the handoff-out
    journal, asks the new owner, and retires behind a redirect instead
    of serving a log it no longer has (split-brain guard)."""
    servers = [
        NodeServer(f"j{i}", data_dir=str(tmp_path / f"j{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    extra = NodeServer("j2", data_dir=str(tmp_path / "j2"),
                       config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[extra])
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects([((0, "counter_pn", "b"), "increment", 5)],
                           tx)
        api.commit_transaction(tx)
        assert servers[0].node.ring[0] == "j0"

        # transfer partition 0 to j2 WITHOUT the ring_update step (the
        # driver "crashed" right after the cutover)
        cursor, base = servers[0]._rpc("j2", "handoff_begin", (0, "j0"))
        servers[0]._rpc("j0", "handoff_cutover", (0, "j2", cursor, base))
        assert servers[0].meta.get("handoff_out") == {0: "j2"}

        servers[0].close()
        j0b = NodeServer("j0", data_dir=str(tmp_path / "j0"),
                         config=_cfg())
        try:
            # the journal + peer query retired the moved partition
            assert isinstance(j0b.node.partitions[0], RemotePartition)
            assert j0b.node.ring[0] == "j2"
            tx = j0b.api.start_transaction()
            assert j0b.api.read_objects([(0, "counter_pn", "b")], tx) \
                == [5]
            j0b.api.commit_transaction(tx)
        finally:
            j0b.close()
        servers = servers[1:]
    finally:
        for srv in servers + [extra]:
            srv.close()


def test_former_owner_restart_honors_transfer(tmp_path):
    """The old owner crashes right after the transfer (before/without
    anything else happening) and restarts from its persisted plan: the
    handoff journal + peer query must keep it from serving the moved
    partition."""
    servers = [
        NodeServer(f"r{i}", data_dir=str(tmp_path / f"r{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    extra = NodeServer("r2", data_dir=str(tmp_path / "r2"),
                       config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[extra])
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects([((0, "counter_pn", "b"), "increment", 7)],
                           tx)
        api.commit_transaction(tx)

        new_ring = dict(servers[0].node.ring)
        assert new_ring[0] == "r0"
        new_ring[0] = "r2"
        servers[0].rebalance(new_ring)

        # "crash" r0 and restart it from disk
        servers[0].close()
        r0b = NodeServer("r0", data_dir=str(tmp_path / "r0"),
                         config=_cfg())
        try:
            assert r0b.node.ring[0] == "r2"
            assert isinstance(r0b.node.partitions[0], RemotePartition)
            tx = r0b.api.start_transaction()
            assert r0b.api.read_objects([(0, "counter_pn", "b")], tx) \
                == [7]
            r0b.api.commit_transaction(tx)
        finally:
            r0b.close()
        servers = servers[1:]
    finally:
        for srv in servers + [extra]:
            srv.close()


# --------------------------------------------------------------------------
# cutover failure modes (advisor r04: TOCTOU + double-owner on install loss)


def test_racing_mutator_is_redirected_not_silently_lost(tmp_path):
    """A mutating call that slipped past the drain park before the
    cutover set it (the TOCTOU window) hits the retired flag UNDER the
    partition lock and raises, instead of appending after the tail
    snapshot and being silently dropped with the log."""
    from antidote_tpu.txn.manager import PartitionRetired

    servers = [
        NodeServer(f"t{i}", data_dir=str(tmp_path / f"t{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    extra = NodeServer("t2", data_dir=str(tmp_path / "t2"),
                       config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[extra])
        pm_old = servers[0].node.partitions[0]
        assert isinstance(pm_old, PartitionManager)
        new_ring = dict(servers[0].node.ring)
        new_ring[0] = "t2"
        servers[0].rebalance(new_ring)
        # the stale pm reference a racing worker thread would hold:
        # every mutating entry point refuses under the lock
        with pytest.raises(PartitionRetired):
            pm_old.stage_update(("tx", 1), 0, "counter_pn", 1)
        with pytest.raises(PartitionRetired):
            pm_old.stage_group(("tx", 2), [(0, "counter_pn", 1)])
        from antidote_tpu.clocks import VC

        with pytest.raises(PartitionRetired):
            pm_old.prepare(("tx", 3), VC())
    finally:
        for srv in servers + [extra]:
            srv.close()


def _two_plus_receiver(tmp_path, tag):
    servers = [
        NodeServer(f"{tag}{i}", data_dir=str(tmp_path / f"{tag}{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    recv = NodeServer(f"{tag}2", data_dir=str(tmp_path / f"{tag}2"),
                      config=_cfg())
    create_dc_cluster("dc1", 8, servers, clients=[recv])
    api = servers[0].api
    tx = api.start_transaction()
    api.update_objects([((0, "counter_pn", "b"), "increment", 9)], tx)
    api.commit_transaction(tx)
    assert servers[0].node.ring[0] == f"{tag}0"
    return servers, recv


def test_install_applied_but_reply_lost_retires_old_owner(tmp_path):
    """The receiver adopts the partition but its reply is 'lost' (the
    install handler raises after applying): the old owner must NOT
    resume serving — it queries the intended owner, sees the adoption,
    and retires.  One live owner, journal kept for the re-plan."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers, recv = _two_plus_receiver(tmp_path, "a")
    try:
        orig = recv._handoff_install

        def applied_but_reply_lost(p, base_offset, tail):
            orig(p, base_offset, tail)
            raise RemoteCallError("injected: reply lost")

        recv._handoff_install = applied_but_reply_lost
        cursor, base = servers[0]._rpc("a2", "handoff_begin", (0, "a0"))
        with pytest.raises(RemoteCallError):
            servers[0]._rpc("a0", "handoff_cutover", (0, "a2", cursor, base))

        # exactly one live owner: the receiver
        assert isinstance(servers[0].node.partitions[0], RemotePartition)
        assert servers[0]._handoff[0]["state"] == "retired"
        assert isinstance(recv.node.partitions[0], PartitionManager)
        # the in-doubt journal survives until the global re-plan
        assert servers[0].meta.get("handoff_out") == {0: "a2"}
        # history is served (through the old owner's redirect too)
        tx = servers[0].api.start_transaction()
        assert servers[0].api.read_objects(
            [(0, "counter_pn", "b")], tx) == [9]
        servers[0].api.commit_transaction(tx)
    finally:
        for srv in servers + [recv]:
            srv.close()


def test_install_never_applied_resumes_ownership(tmp_path):
    """The install fails cleanly before the receiver applies anything:
    the old owner confirms non-adoption via the ring query, resumes
    serving, and forgets the intent."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers, recv = _two_plus_receiver(tmp_path, "b")
    try:
        def never_applied(p, base_offset, tail):
            raise RemoteCallError("injected: install refused")

        recv._handoff_install = never_applied
        cursor, base = servers[0]._rpc("b2", "handoff_begin", (0, "b0"))
        with pytest.raises(RemoteCallError):
            servers[0]._rpc("b0", "handoff_cutover", (0, "b2", cursor, base))

        pm = servers[0].node.partitions[0]
        assert isinstance(pm, PartitionManager)
        assert pm.retired is False
        assert 0 not in servers[0]._handoff
        assert not (servers[0].meta.get("handoff_out") or {})
        # still serving writes
        tx = servers[0].api.start_transaction()
        servers[0].api.update_objects(
            [((0, "counter_pn", "b"), "increment", 1)], tx)
        cvc = servers[0].api.commit_transaction(tx)
        tx = servers[0].api.start_transaction(clock=cvc)
        assert servers[0].api.read_objects(
            [(0, "counter_pn", "b")], tx) == [10]
        servers[0].api.commit_transaction(tx)
    finally:
        for srv in servers + [recv]:
            srv.close()


def test_install_in_doubt_parks_then_retry_resolves(tmp_path):
    """Install push fails AND the receiver is unreachable for the
    resolution query: the partition parks in doubt (no write on either
    side, journal kept) instead of resuming into a potential
    double-owner; a later retry (receiver back) completes the move."""
    from antidote_tpu.cluster.remote import RemoteCallError
    from antidote_tpu.txn.manager import PartitionRetired

    servers, recv = _two_plus_receiver(tmp_path, "c")
    try:
        def never_applied(p, base_offset, tail):
            raise RemoteCallError("injected: link dropped")

        recv._handoff_install = never_applied
        orig_req = servers[0].link.request

        def peer_gone(target, kind, payload):
            if target == "c2" and kind == "handoff_probe":
                raise ConnectionError("injected: peer gone")
            return orig_req(target, kind, payload)

        servers[0].link.request = peer_gone
        cursor, base = servers[0]._rpc("c2", "handoff_begin", (0, "c0"))
        with pytest.raises(RemoteCallError):
            servers[0]._rpc("c0", "handoff_cutover", (0, "c2", cursor, base))

        assert servers[0]._handoff[0]["state"] == "in_doubt"
        assert servers[0].meta.get("handoff_out") == {0: "c2"}
        pm = servers[0].node.partitions[0]
        assert isinstance(pm, PartitionManager)
        with pytest.raises(PartitionRetired):
            pm.stage_update(("tx", 9), 0, "counter_pn", 1)

        # receiver returns: the retry finishes the transfer
        servers[0].link.request = orig_req
        del recv._handoff_install  # restore the real bound method
        servers[0]._rpc("c0", "handoff_cutover", (0, "c2", cursor, base))
        assert servers[0]._handoff[0]["state"] == "retired"
        assert isinstance(recv.node.partitions[0], PartitionManager)
        tx = recv.api.start_transaction()
        assert recv.api.read_objects(
            [(0, "counter_pn", "b")], tx) == [9]
        recv.api.commit_transaction(tx)
    finally:
        for srv in servers + [recv]:
            srv.close()


def test_restart_with_receiver_down_parks_in_doubt(tmp_path):
    """Old owner crashes after the cutover, restarts while the receiver
    is DOWN: the journaled transfer cannot be resolved, so the
    partition parks in doubt (it must neither serve — possible double
    owner — nor crash recovery)."""
    from antidote_tpu.txn.manager import PartitionRetired

    servers = [
        NodeServer(f"d{i}", data_dir=str(tmp_path / f"d{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    extra = NodeServer("d2", data_dir=str(tmp_path / "d2"),
                       config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[extra])
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects([((0, "counter_pn", "b"), "increment", 3)],
                           tx)
        api.commit_transaction(tx)
        cursor, base = servers[0]._rpc("d2", "handoff_begin", (0, "d0"))
        servers[0]._rpc("d0", "handoff_cutover", (0, "d2", cursor, base))
        servers[0].close()
        extra.close()  # receiver gone before the old owner restarts

        d0b = NodeServer("d0", data_dir=str(tmp_path / "d0"),
                         config=_cfg())
        try:
            assert d0b._handoff[0]["state"] == "in_doubt"
            assert d0b.meta.get("handoff_out") == {0: "d2"}
            pm = d0b.node.partitions[0]
            if isinstance(pm, PartitionManager):
                with pytest.raises(PartitionRetired):
                    pm.stage_update(("tx", 1), 0, "counter_pn", 1)
                # READS park too: after the cutover renamed the real
                # log, this pm sits on a rebuilt EMPTY one — serving a
                # read would return bottom for committed keys
                with pytest.raises(PartitionRetired):
                    pm.read(0, "counter_pn", None)
                from antidote_tpu.txn.coordinator import (
                    TransactionAborted,
                )

                with pytest.raises((TransactionAborted, TimeoutError)):
                    tx = d0b.api.start_transaction()
                    d0b.api.read_objects([(0, "counter_pn", "b")], tx)
            # the stable plane is NOT pinned at bottom by the parked
            # slot: the snapshot still becomes (and stays) positive —
            # poll: the peer's first gossip to the restarted member
            # can lag under load
            s0 = d0b.plane.get_stable_snapshot().get_dc("dc1")
            deadline = time.monotonic() + 10.0
            while True:
                s1 = d0b.plane.get_stable_snapshot().get_dc("dc1")
                if s1 > 0:
                    break
                assert time.monotonic() < deadline, (s0, s1)
                time.sleep(0.05)
            assert s1 >= s0, (s0, s1)
        finally:
            d0b.close()
        servers = servers[1:]
    finally:
        for srv in servers:
            srv.close()


def test_python_fabric_multi_partition_read(tmp_path):
    """The pure-Python NodeLink fabric (no pipelined finish_many):
    remote proxies take the plain read path and local partitions still
    fuse — a multi-partition read spanning both works (regression:
    round-5 fused reads crashed on RemotePartition here)."""
    cfg = lambda: Config(n_partitions=8, heartbeat_s=0.05,
                         fabric_native=False)
    servers = [
        NodeServer(f"py{i}", data_dir=str(tmp_path / f"py{i}"),
                   config=cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        assert servers[0].fabric_kind() == "python"
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", k + 1)
             for k in range(16)], tx)
        cvc = api.commit_transaction(tx)
        tx = api.start_transaction(clock=cvc)
        vals = api.read_objects(
            [(k, "counter_pn", "b") for k in range(16)], tx)
        api.commit_transaction(tx)
        assert vals == [k + 1 for k in range(16)]
    finally:
        for srv in servers:
            srv.close()


def test_multi_partition_remote_read_is_one_rpc_per_owner(tmp_path):
    """A read spanning many remote partitions crosses the fabric ONCE
    per owner member (the per-owner batched "part_multi", fused
    per-chip server-side), not once per partition."""
    cfg = lambda: Config(n_partitions=8, heartbeat_s=0.05,
                         fabric_native=False)
    servers = [
        NodeServer(f"mo{i}", data_dir=str(tmp_path / f"mo{i}"),
                   config=cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", k + 1)
             for k in range(16)], tx)
        cvc = api.commit_transaction(tx)

        calls = []
        orig = servers[0].link.request

        def counting(target, kind, payload):
            calls.append((target, kind))
            return orig(target, kind, payload)

        servers[0].link.request = counting
        tx = api.start_transaction(clock=cvc)
        vals = api.read_objects(
            [(k, "counter_pn", "b") for k in range(16)], tx)
        api.commit_transaction(tx)
        servers[0].link.request = orig
        assert vals == [k + 1 for k in range(16)]
        reads = [c for c in calls if c[1] in ("part", "part_multi")]
        multi = [c for c in reads if c[1] == "part_multi"]
        # 16 keys span 4 partitions on the remote member: ONE batched
        # RPC, no per-partition read RPCs
        assert len(multi) == 1 and len(reads) == 1, reads
    finally:
        for srv in servers:
            srv.close()


def test_wide_txn_2pc_batches_per_owner(tmp_path):
    """A transaction updating many remote partitions crosses the
    fabric once per owner per ROUND (stage_prepare, commit), not once
    per partition (the per-owner "part_batch")."""
    servers = [
        NodeServer(f"wb{i}", data_dir=str(tmp_path / f"wb{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        api = servers[0].api
        link = servers[0].link
        if not hasattr(link, "finish_many"):
            pytest.skip("pipelined fabric unavailable")
        calls = []
        orig = link.start_request
        link.start_request = (
            lambda t, k, p: (calls.append((t, k)), orig(t, k, p))[1])
        # touches all 8 partitions: 4 local + 4 remote (one owner)
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", 1)
             for k in range(8)], tx)
        api.commit_transaction(tx)
        link.start_request = orig
        batches = [c for c in calls if c[1] == "part_batch"]
        parts = [c for c in calls if c[1] == "part"]
        # one batched frame per round (prepare + commit = 2), nothing
        # per partition
        assert len(batches) == 2 and not parts, calls
        # and the data is right
        tx = api.start_transaction()
        vals = api.read_objects(
            [(k, "counter_pn", "b") for k in range(8)], tx)
        api.commit_transaction(tx)
        assert vals == [1] * 8
    finally:
        for srv in servers:
            srv.close()


@pytest.mark.parametrize("stream", [True, False],
                         ids=["stream", "oneshot"])
def test_truncated_donor_handoff_recovers_full_state(tmp_path, stream):
    """Checkpoint-shipping handoff (ISSUE 13): the donor's ``.ckpt``
    manifest + seed segments travel WITH the log bytes, so a receiver
    adopting a TRUNCATED log recovers the below-cut history from the
    shipped seeds.  Pre-fix the checkpoint did not travel: the
    receiver full-scanned a log whose prefix was reclaimed and
    recovered suffix-only (loudly) — the final read here pins that as
    the regression (it would see only the post-truncation delta).
    Both ISSUE-19 knob positions must land the identical state: the
    segment-cursor streamed pull and the legacy one-shot bundle."""
    servers = [
        NodeServer(f"t{i}", data_dir=str(tmp_path / f"t{i}"),
                   config=Config(n_partitions=8, heartbeat_s=0.05,
                                 ckpt_stream=stream))
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        api = servers[0].api
        keys = [3, 11, 19]  # partition 3
        cvc = None
        for round_ in range(10):
            tx = api.start_transaction(clock=cvc)
            api.update_objects(
                [((k, "counter_pn", "b"), "increment", 1)
                 for k in keys], tx)
            cvc = api.commit_transaction(tx)

        donor = next(s for s in servers
                     if isinstance(s.node.partitions[3],
                                   PartitionManager))
        pm = donor.node.partitions[3]
        assert pm.checkpoint_now() is not None
        assert pm.log.log.truncated_base > 0, \
            "the donor's below-cut bytes must really be reclaimed"
        # the post-truncation delta the pre-fix receiver was LIMITED to
        tx = api.start_transaction(clock=cvc)
        api.update_objects([((3, "counter_pn", "b"), "increment", 1)],
                           tx)
        cvc = api.commit_transaction(tx)

        receiver = next(s for s in servers if s is not donor)
        new_ring = dict(servers[0].node.ring)
        new_ring[3] = receiver.node_id
        servers[0].rebalance(new_ring)

        pm2 = receiver.node.partitions[3]
        assert isinstance(pm2, PartitionManager)
        # the shipped checkpoint engaged: recovery was seeded, not a
        # full scan of a reclaimed-prefix log
        assert pm2.log.suffix_start > 0, \
            "receiver did not adopt the shipped checkpoint"
        tx = receiver.api.start_transaction(clock=cvc)
        vals = receiver.api.read_objects(
            [(k, "counter_pn", "b") for k in keys], tx)
        receiver.api.commit_transaction(tx)
        assert vals == [11, 10, 10], \
            f"below-cut history lost across the handoff: {vals}"
    finally:
        for srv in servers:
            srv.close()


def test_donor_blip_mid_streamed_pull_resumes_at_ack(tmp_path):
    """ISSUE 19: a donor blip (RemoteCallError) and a torn segment
    fetch mid-streamed-pull both re-pull and resume at the cursor's
    per-segment ack watermark — the handoff still lands the donor's
    full below-cut history, and the faults never discard acked
    progress (STREAM_RESUME_REFETCH_BYTES stays flat: the manifest
    never changed, so nothing already staged is refetched)."""
    from antidote_tpu import stats
    from antidote_tpu.cluster.remote import RemoteCallError

    def _cfg_tiny():
        # window of 1 byte: every segment is its own pull round, so
        # the ack watermark is exercised between faults
        return Config(n_partitions=8, heartbeat_s=0.05,
                      ckpt_stream_window_bytes=1)

    servers = [
        NodeServer(f"b{i}", data_dir=str(tmp_path / f"b{i}"),
                   config=_cfg_tiny())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        api = servers[0].api
        donor = next(s for s in servers
                     if isinstance(s.node.partitions[3],
                                   PartitionManager))
        pm = donor.node.partitions[3]
        cvc = None
        # three cuts over DISTINCT key sets: three live segments (no
        # superseded entries, so compaction leaves the chain alone)
        for round_ in range(3):
            keys = [3 + 8 * (3 * round_ + j) for j in range(3)]
            for _ in range(4):
                tx = api.start_transaction(clock=cvc)
                api.update_objects(
                    [((k, "counter_pn", "b"), "increment", 1)
                     for k in keys], tx)
                cvc = api.commit_transaction(tx)
            assert pm.checkpoint_now() is not None
        assert pm.log.log.truncated_base > 0
        man = pm.log.ckpt.bundle_manifest()
        assert man is not None and len(man["segments"]) >= 3, \
            "scenario needs a multi-segment bundle"

        receiver = next(s for s in servers if s is not donor)
        real = receiver._rpc
        seg_calls = [0]

        def rpc(target, kind, payload):
            if kind == "ckpt_segs":
                seg_calls[0] += 1
                if seg_calls[0] == 1:
                    raise RemoteCallError("donor vanished (test)")
                if seg_calls[0] == 2:
                    raws = real(target, kind, payload)
                    return [None if r is None else r[: len(r) // 2]
                            for r in raws]
            return real(target, kind, payload)

        receiver._rpc = rpc
        torn0 = stats.registry.stream_torn_fetches.value()
        retr0 = stats.registry.ckpt_seg_pull_retries.value()
        refetch0 = stats.registry.stream_resume_refetch_bytes.value()

        new_ring = dict(servers[0].node.ring)
        new_ring[3] = receiver.node_id
        servers[0].rebalance(new_ring)

        pm2 = receiver.node.partitions[3]
        assert isinstance(pm2, PartitionManager)
        assert pm2.log.suffix_start > 0, \
            "receiver did not adopt the streamed checkpoint"
        assert seg_calls[0] > len(man["segments"]), \
            "the faults were never injected into the segment pulls"
        assert stats.registry.stream_torn_fetches.value() == torn0 + 1
        assert stats.registry.ckpt_seg_pull_retries.value() > retr0
        assert stats.registry.stream_resume_refetch_bytes.value() \
            == refetch0, "acked progress was discarded and refetched"
        all_keys = [3 + 8 * j for j in range(9)]
        tx = receiver.api.start_transaction(clock=cvc)
        vals = receiver.api.read_objects(
            [(k, "counter_pn", "b") for k in all_keys], tx)
        receiver.api.commit_transaction(tx)
        assert vals == [4] * 9, \
            f"below-cut history lost across the faulted pull: {vals}"
    finally:
        for srv in servers:
            srv.close()

"""Cross-node handoff INSIDE a federated DC: ownership of a slice
moves between existing members while remote DCs keep replicating from
it — and keep gap-repairing through their now-STALE descriptors (the
old owner forwards repair queries to the new owner over the node
fabric, cluster/federation.py _handle_query).

The reference's analogue: riak_core ownership transfer under a
connected inter-DC mesh; repair requests hit the member the cached
descriptor names (src/inter_dc_query.erl:95-130) and must still get
answered.
"""

import time

from antidote_tpu.interdc import InProcBus

from tests.cluster.test_federation import make_dc
from antidote_tpu.cluster.federation import connect_federation


def _converge_read(srv, groups, ct, bos, want, timeout=15.0):
    deadline = time.monotonic() + timeout
    while True:
        try:
            vals, _ = srv.api.read_objects_static(ct, bos)
            assert vals == want
            return
        except TimeoutError:
            assert time.monotonic() < deadline
            for nids in groups:
                for nid in nids:
                    nid.tick_heartbeats()
                    nid.pump()
                    nid.srv.gossip_tick()


def test_replication_and_repair_survive_handoff(tmp_path):
    bus = InProcBus()
    sa, na = make_dc(bus, tmp_path, "dcA")
    sb, nb = make_dc(bus, tmp_path, "dcB")
    connect_federation([na, nb])
    try:
        # history on dcA's partition 0 (owned by member n1), replicated
        ct = sa[0].api.update_objects_static(
            None, [((0, "counter_pn", "b"), "increment", 1)])
        _converge_read(sb[0], (na, nb), ct, [(0, "counter_pn", "b")],
                       [1])

        # move partition 0 to dcA's OTHER member while federated
        old_owner = sa[0].node.ring[0]
        new_ring = dict(sa[0].node.ring)
        new_ring[0] = [s.node_id for s in sa
                       if s.node_id != old_owner][0]
        sa[0].rebalance(new_ring)
        new_srv = [s for s in sa if s.node_id == new_ring[0]][0]
        new_nid = [n for n in na if n.srv is new_srv][0]
        assert 0 in new_nid.local
        assert 0 in new_nid.senders and 0 in new_nid.gates

        # writes at the NEW owner still replicate to dcB — opid stream
        # continuity across the publisher change
        ct = new_srv.api.update_objects_static(
            ct, [((0, "counter_pn", "b"), "increment", 10)])
        _converge_read(sb[1], (na, nb), ct, [(0, "counter_pn", "b")],
                       [11])

        # now force a GAP at dcB and let repair route through the
        # STALE descriptor (it still names the old owner for slice 0)
        for nid in nb:
            bus.set_drop_rx((nid.dc_id, nid.member_index), True)
        for _ in range(3):
            ct = new_srv.api.update_objects_static(
                ct, [((0, "counter_pn", "b"), "increment", 1)])
        for nid in nb:
            bus.set_drop_rx((nid.dc_id, nid.member_index), False)
        ct = new_srv.api.update_objects_static(
            ct, [((0, "counter_pn", "b"), "increment", 1)])
        _converge_read(sb[0], (na, nb), ct, [(0, "counter_pn", "b")],
                       [15])

        # dcB -> dcA direction: dcA's new owner applies remote txns for
        # the moved slice (its sub-buffers resumed at the adopted
        # watermarks)
        ct = sb[0].api.update_objects_static(
            ct, [((0, "counter_pn", "b"), "increment", 100)])
        _converge_read(new_srv, (na, nb), ct, [(0, "counter_pn", "b")],
                       [115])
    finally:
        for nid in na + nb:
            nid.close()
        for s in sa + sb:
            s.close()

"""Causal checker during a live partition-count resize: a 2-member DC
grows 4 -> 8 partitions while the trace runs.  The resize freezes new
txns, drains in-flight ones, and swaps logs at the new width
(cluster/node.py resize_cluster); clients see retryable refusals in
the window — but every read that succeeds must still satisfy the
causal floor and snapshot closure, across the width change (rules:
tests/causal_core.py; the elasticity soak validates totals, this
validates VISIBILITY)."""

import threading
import time

import causal_core as cc
from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.config import Config
from antidote_tpu.txn.coordinator import TransactionAborted


class RetryingReader:
    """Reads hitting the resize freeze/park window retry until the
    cluster serves again; only successful reads enter the trace."""

    def __init__(self, api):
        self.api = api

    def read_objects_static(self, clock, objs):
        deadline = time.monotonic() + 30.0
        while True:
            try:
                return self.api.read_objects_static(clock, objs)
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)


def test_causal_visibility_through_resize(tmp_path):
    servers = [
        NodeServer(f"n{i + 1}", data_dir=str(tmp_path / f"n{i + 1}"),
                   config=Config(n_partitions=4, heartbeat_s=0.005,
                                 clock_wait_timeout_s=10.0))
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 4, servers)
        resized = []

        def chaos():
            time.sleep(0.3)
            servers[0].resize_cluster(8)
            resized.append(True)

        t = threading.Thread(target=chaos)
        t.start()
        writes, reads, abandoned = cc.run_trace(
            [servers[0].api, servers[1].api],
            [RetryingReader(servers[0].api),
             RetryingReader(servers[1].api)],
            retry_exc=(TransactionAborted, TimeoutError, OSError,
                       RuntimeError))
        t.join(timeout=60)
        assert resized, "resize never completed"
        assert len(writes) >= 2 * cc.N_WRITES
        cc.validate(writes, reads)
        # and the widened cluster still serves the full history
        final = RetryingReader(servers[1].api).read_objects_static(
            None, [cc.key_of(k) for k in range(cc.N_KEYS)])
        seen = set().union(*map(set, final[0]))
        recorded = {e for e, _k in writes}
        # every recorded write present; extras only from in-doubt
        # commits that turned out durable (post-decision failures)
        assert seen >= recorded
        assert seen - recorded <= abandoned, (seen - recorded, abandoned)
    finally:
        for s in servers:
            s.close()


def test_causal_visibility_through_rebalance(tmp_path):
    """Causal checker through a live ownership handoff: half of member
    1's partitions move to member 2 mid-trace (probe-fenced cutover,
    cluster/node.py rebalance).  Moved keys keep serving the complete
    causally-consistent history from their new owner."""
    servers = [
        NodeServer(f"n{i + 1}", data_dir=str(tmp_path / f"n{i + 1}"),
                   config=Config(n_partitions=4, heartbeat_s=0.005,
                                 clock_wait_timeout_s=10.0))
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 4, servers)
        moved = []

        errs = []

        def chaos():
            try:
                time.sleep(0.3)
                new_ring = dict(servers[0].node.ring)
                # move every partition member 1 owns to member 2
                owner0 = [p for p, o in new_ring.items()
                          if o == servers[0].node_id]
                for p in owner0:
                    new_ring[p] = servers[1].node_id
                servers[0].rebalance(new_ring)
                moved.extend(owner0)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=chaos)
        t.start()
        writes, reads, abandoned = cc.run_trace(
            [servers[0].api, servers[1].api],
            [RetryingReader(servers[0].api),
             RetryingReader(servers[1].api)],
            retry_exc=(TransactionAborted, TimeoutError, OSError,
                       RuntimeError))
        t.join(timeout=60)
        assert not errs, errs[0]
        assert moved, "rebalance never ran"
        assert len(writes) >= 2 * cc.N_WRITES
        cc.validate(writes, reads)
        final = RetryingReader(servers[1].api).read_objects_static(
            None, [cc.key_of(k) for k in range(cc.N_KEYS)])
        seen = set().union(*map(set, final[0]))
        recorded = {e for e, _k in writes}
        # every recorded write present; extras only from in-doubt
        # commits that turned out durable (post-decision failures)
        assert seen >= recorded
        assert seen - recorded <= abandoned, (seen - recorded, abandoned)
    finally:
        for s in servers:
            s.close()

"""Cluster-wide partition-count resize: a LIVE multi-node DC grows its
ring in place (VERDICT r04 item 5; reference riak_core resize +
handoff folds, src/logging_vnode.erl:781-812, plan/commit staged
change src/antidote_dc_manager.erl:53-81).

What must hold: a 2-node DC grows 8 -> 16 while writers commit
continuously and no committed transaction is lost; a member (or the
driver) crashing mid-resize restarts parked and a re-driven resize
converges the cluster; ownership then moves with the ordinary
rebalance."""

import threading
import time

import pytest

from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.config import Config
from antidote_tpu.txn.coordinator import TransactionAborted
from antidote_tpu.txn.manager import PartitionManager


def _cfg():
    return Config(n_partitions=8, heartbeat_s=0.05)


def _totals(api, keys):
    tx = api.start_transaction()
    vals = api.read_objects([(k, "counter_pn", "b") for k in keys], tx)
    api.commit_transaction(tx)
    return sum(vals)


def test_grow_2node_8_to_16_under_continuous_writes(tmp_path):
    servers = [
        NodeServer(f"g{i}", data_dir=str(tmp_path / f"g{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        stop = threading.Event()
        committed = [0, 0]
        errs = []

        def writer(slot, api, seed):
            k = 0
            while not stop.is_set():
                key = (seed * 37 + k) % 96
                k += 1
                try:
                    tx = api.start_transaction()
                    api.update_objects(
                        [((key, "counter_pn", "b"), "increment", 1),
                         ((500 + key, "set_aw", "b"), "add",
                          f"w{slot}.{k % 7}")], tx)
                    api.commit_transaction(tx)
                    committed[slot] += 1
                except (TransactionAborted, TimeoutError):
                    pass
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)
                    return

        threads = [threading.Thread(target=writer,
                                    args=(i, s.api, i))
                   for i, s in enumerate(servers)]
        for t in threads:
            t.start()
        time.sleep(0.4)

        new_ring = servers[0].resize_cluster(16)

        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        total = sum(committed)
        assert total > 30  # writers really ran through the resize

        # every member is at the new width with the split ring
        for srv in servers:
            assert srv.node.config.n_partitions == 16
            assert len(srv.node.ring) == 16
            for q in range(16):
                assert srv.node.ring[q] == new_ring[q]
                assert new_ring[q] == new_ring[q % 8]
        # children live on their parent's owner
        for q in range(16):
            owner = new_ring[q]
            srv = next(s for s in servers if s.node_id == owner)
            assert isinstance(srv.node.partitions[q], PartitionManager)

        # nothing lost: grand total equals committed txn count, from
        # every member
        for srv in servers:
            assert _totals(srv.api, range(96)) == total

        # the DC still serves writes at the new width
        tx = servers[1].api.start_transaction()
        servers[1].api.update_objects(
            [((7, "counter_pn", "b"), "increment", 1)], tx)
        cvc = servers[1].api.commit_transaction(tx)
        tx = servers[0].api.start_transaction(clock=cvc)
        v = servers[0].api.read_objects([(7, "counter_pn", "b")], tx)
        servers[0].api.commit_transaction(tx)
        assert v[0] >= 1
    finally:
        for srv in servers:
            srv.close()


def test_resize_then_rebalance_moves_children(tmp_path):
    """Grow 4 -> 8, then move two of the new children to a fresh
    member with the ordinary rebalance (the plan/claim separation)."""
    cfg = lambda: Config(n_partitions=4, heartbeat_s=0.05)
    servers = [
        NodeServer(f"r{i}", data_dir=str(tmp_path / f"r{i}"),
                   config=cfg())
        for i in range(2)
    ]
    s3 = NodeServer("r2", data_dir=str(tmp_path / "r2"), config=cfg())
    try:
        create_dc_cluster("dc1", 4, servers)
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", k + 1)
             for k in range(16)], tx)
        api.commit_transaction(tx)

        servers[0].resize_cluster(8)
        servers[0].add_member("r2", s3.addr)
        new_ring = dict(servers[0].node.ring)
        new_ring[5] = "r2"
        new_ring[6] = "r2"
        servers[0].rebalance(new_ring)

        assert isinstance(s3.node.partitions[5], PartitionManager)
        assert isinstance(s3.node.partitions[6], PartitionManager)
        assert _totals(s3.api, range(16)) == sum(
            k + 1 for k in range(16))
    finally:
        for srv in servers + [s3]:
            srv.close()


def test_member_crash_mid_resize_recovers(tmp_path):
    """One member commits the new width, then the 'driver crashes'
    (protocol stops) and the OTHER member 'crashes' before its commit:
    it restarts PARKED (marker), the cluster is frozen-but-consistent,
    and a re-driven resize_cluster converges both members with no
    committed write lost."""
    servers = [
        NodeServer(f"c{i}", data_dir=str(tmp_path / f"c{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", 2 * k + 1)
             for k in range(24)], tx)
        api.commit_transaction(tx)
        expect = sum(2 * k + 1 for k in range(24))

        # drive the protocol by hand up to a partial commit
        for m in ("c0", "c1"):
            servers[0]._rpc(m, "resize_prepare", (16, 6, 256))
        for m in ("c0", "c1"):
            servers[0]._rpc(m, "resize_freeze", (16,))
        for m in ("c0", "c1"):
            servers[0]._rpc(m, "resize_drain", None)
        servers[0]._rpc("c1", "resize_commit", (16,))
        assert servers[1].node.config.n_partitions == 16
        assert servers[0].node.config.n_partitions == 8

        # c0 "crashes" before its commit and restarts: parked, old
        # width, marker intact
        servers[0].close()
        c0b = NodeServer("c0", data_dir=str(tmp_path / "c0"),
                         config=_cfg())
        servers[0] = c0b
        assert c0b.meta.get("cluster_resize") == 16
        assert c0b.node.config.n_partitions == 8
        assert c0b._resize_parking

        # re-drive from the committed member: converges both
        servers[1].resize_cluster(16)
        assert c0b.node.config.n_partitions == 16
        assert not c0b._resize_parking
        assert servers[1].meta.get("cluster_resize") is None

        for srv in servers:
            assert _totals(srv.api, range(24)) == expect
    finally:
        for srv in servers:
            srv.close()


def test_member_crash_after_commit_restarts_at_new_width(tmp_path):
    """A member killed right after its commit (journal written, swap
    done) restarts at the NEW width from its persisted plan, still
    parked until a finish."""
    servers = [
        NodeServer(f"j{i}", data_dir=str(tmp_path / f"j{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", 5) for k in
             range(8)], tx)
        api.commit_transaction(tx)

        for m in ("j0", "j1"):
            servers[0]._rpc(m, "resize_prepare", (16, 6, 256))
        for m in ("j0", "j1"):
            servers[0]._rpc(m, "resize_freeze", (16,))
        for m in ("j0", "j1"):
            servers[0]._rpc(m, "resize_drain", None)
        servers[0]._rpc("j0", "resize_commit", (16,))

        servers[0].close()
        j0b = NodeServer("j0", data_dir=str(tmp_path / "j0"),
                         config=_cfg())
        servers[0] = j0b
        assert j0b.node.config.n_partitions == 16
        assert j0b._resize_parking  # marker still set until finish

        servers[1].resize_cluster(16)
        assert not j0b._resize_parking
        for srv in servers:
            assert _totals(srv.api, range(8)) == 40
    finally:
        for srv in servers:
            srv.close()


def test_resize_rejects_non_multiple_and_federated(tmp_path):
    servers = [
        NodeServer(f"v{i}", data_dir=str(tmp_path / f"v{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        with pytest.raises(ValueError):
            servers[0].resize_cluster(12)
        servers[0].source_factory = lambda p: (lambda: None)
        with pytest.raises(RuntimeError):
            servers[0].resize_cluster(16)
    finally:
        for srv in servers:
            srv.close()


# ------------------------------------------------------- true kill -9 tier


import json
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class _Proc:
    def __init__(self, node_id, data_dir, port, faults=""):
        self.proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "node_proc.py"),
             node_id, data_dir, str(port)] + ([faults] if faults
                                              else []),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        self.node_id = node_id
        ready = json.loads(self.proc.stdout.readline())
        assert ready.get("ready"), ready
        self.addr = ready["addr"]
        self.assembled = ready.get("assembled", False)

    def cmd(self, **req):
        resp = self.cmd_raw(**req)
        assert "error" not in resp, resp
        return resp

    def cmd_raw(self, **req):
        self.proc.stdin.write(json.dumps(req) + "\n")
        self.proc.stdin.flush()
        return json.loads(self.proc.stdout.readline())

    def stop(self):
        if self.proc.poll() is not None:
            return
        try:
            self.cmd_raw(cmd="exit")
        except Exception:  # noqa: BLE001
            pass
        self.proc.wait(timeout=10)


def test_kill9_in_resize_swap_recovers(tmp_path):
    """REAL kill -9 (os._exit inside the swap): member n2 dies with
    journal + new plan persisted but live logs unswapped; its restart
    resumes the swap from the journal, comes back parked, and a
    re-driven resize converges the DC with all data intact."""
    ports = [_free_port(), _free_port()]
    dirs = [str(tmp_path / "n1"), str(tmp_path / "n2")]
    procs = [
        _Proc("n1", dirs[0], ports[0]),
        _Proc("n2", dirs[1], ports[1], faults="die_in_resize_swap"),
    ]
    try:
        members = {p.node_id: p.addr for p in procs}
        ring = {str(i): f"n{(i % 2) + 1}" for i in range(4)}
        for p in procs:
            p.cmd(cmd="join", dc="dc1", ring=ring, members=members)
        ct = None
        for k in range(12):
            ct = procs[k % 2].cmd(
                cmd="update", key=k, type="counter_pn",
                op="increment", arg=k + 1,
                clock=ct)["clock"]

        # the resize drive hits n2's kill -9 mid-swap and fails
        resp = procs[0].cmd_raw(cmd="resize", n=8)
        assert "error" in resp, resp
        procs[1].proc.wait(timeout=10)
        assert procs[1].proc.returncode == 9

        # restart n2 WITHOUT the fault: journal resumes the swap; the
        # member comes back at the new width, parked until a finish
        procs[1] = _Proc("n2", dirs[1], ports[1])
        assert procs[1].assembled
        w = procs[1].cmd(cmd="width")
        assert w["n"] == 8 and w["parked"], w

        # re-drive from n1: converges and unparks
        procs[0].cmd(cmd="resize", n=8)
        for p in procs:
            w = p.cmd(cmd="width")
            assert w["n"] == 8 and not w["parked"], w

        # no committed write lost, readable from BOTH members
        for p in procs:
            total = 0
            for k in range(12):
                total += p.cmd(cmd="read", key=k, type="counter_pn",
                               clock=ct)["value"]
            assert total == sum(k + 1 for k in range(12))

        # still serving cross-node at the new width
        ct = procs[1].cmd(cmd="update", key=3, type="counter_pn",
                          op="increment", arg=10, clock=ct)["clock"]
        assert procs[0].cmd(cmd="read", key=3, type="counter_pn",
                            clock=ct)["value"] == 14
    finally:
        for p in procs:
            p.stop()

"""Cluster-wide partition-count resize: a LIVE multi-node DC grows its
ring in place (VERDICT r04 item 5; reference riak_core resize +
handoff folds, src/logging_vnode.erl:781-812, plan/commit staged
change src/antidote_dc_manager.erl:53-81).

What must hold: a 2-node DC grows 8 -> 16 while writers commit
continuously and no committed transaction is lost; a member (or the
driver) crashing mid-resize restarts parked and a re-driven resize
converges the cluster; ownership then moves with the ordinary
rebalance."""

import threading
import time

import pytest

from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.config import Config
from antidote_tpu.txn.coordinator import TransactionAborted
from antidote_tpu.txn.manager import PartitionManager


def _cfg():
    return Config(n_partitions=8, heartbeat_s=0.05)


def _totals(api, keys):
    tx = api.start_transaction()
    vals = api.read_objects([(k, "counter_pn", "b") for k in keys], tx)
    api.commit_transaction(tx)
    return sum(vals)


def test_grow_2node_8_to_16_under_continuous_writes(tmp_path):
    servers = [
        NodeServer(f"g{i}", data_dir=str(tmp_path / f"g{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        stop = threading.Event()
        committed = [0, 0]
        errs = []

        def writer(slot, api, seed):
            k = 0
            while not stop.is_set():
                key = (seed * 37 + k) % 96
                k += 1
                try:
                    tx = api.start_transaction()
                    api.update_objects(
                        [((key, "counter_pn", "b"), "increment", 1),
                         ((500 + key, "set_aw", "b"), "add",
                          f"w{slot}.{k % 7}")], tx)
                    api.commit_transaction(tx)
                    committed[slot] += 1
                except (TransactionAborted, TimeoutError):
                    pass
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)
                    return

        threads = [threading.Thread(target=writer,
                                    args=(i, s.api, i))
                   for i, s in enumerate(servers)]
        for t in threads:
            t.start()
        time.sleep(0.4)

        new_ring = servers[0].resize_cluster(16)

        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        total = sum(committed)
        assert total > 30  # writers really ran through the resize

        # every member is at the new width with the split ring
        for srv in servers:
            assert srv.node.config.n_partitions == 16
            assert len(srv.node.ring) == 16
            for q in range(16):
                assert srv.node.ring[q] == new_ring[q]
                assert new_ring[q] == new_ring[q % 8]
        # children live on their parent's owner
        for q in range(16):
            owner = new_ring[q]
            srv = next(s for s in servers if s.node_id == owner)
            assert isinstance(srv.node.partitions[q], PartitionManager)

        # nothing lost: grand total equals committed txn count, from
        # every member
        for srv in servers:
            assert _totals(srv.api, range(96)) == total

        # the DC still serves writes at the new width
        tx = servers[1].api.start_transaction()
        servers[1].api.update_objects(
            [((7, "counter_pn", "b"), "increment", 1)], tx)
        cvc = servers[1].api.commit_transaction(tx)
        tx = servers[0].api.start_transaction(clock=cvc)
        v = servers[0].api.read_objects([(7, "counter_pn", "b")], tx)
        servers[0].api.commit_transaction(tx)
        assert v[0] >= 1
    finally:
        for srv in servers:
            srv.close()


def test_resize_then_rebalance_moves_children(tmp_path):
    """Grow 4 -> 8, then move two of the new children to a fresh
    member with the ordinary rebalance (the plan/claim separation)."""
    cfg = lambda: Config(n_partitions=4, heartbeat_s=0.05)
    servers = [
        NodeServer(f"r{i}", data_dir=str(tmp_path / f"r{i}"),
                   config=cfg())
        for i in range(2)
    ]
    s3 = NodeServer("r2", data_dir=str(tmp_path / "r2"), config=cfg())
    try:
        create_dc_cluster("dc1", 4, servers)
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", k + 1)
             for k in range(16)], tx)
        api.commit_transaction(tx)

        servers[0].resize_cluster(8)
        servers[0].add_member("r2", s3.addr)
        new_ring = dict(servers[0].node.ring)
        new_ring[5] = "r2"
        new_ring[6] = "r2"
        servers[0].rebalance(new_ring)

        assert isinstance(s3.node.partitions[5], PartitionManager)
        assert isinstance(s3.node.partitions[6], PartitionManager)
        assert _totals(s3.api, range(16)) == sum(
            k + 1 for k in range(16))
    finally:
        for srv in servers + [s3]:
            srv.close()


def test_member_crash_mid_resize_recovers(tmp_path):
    """One member commits the new width, then the 'driver crashes'
    (protocol stops) and the OTHER member 'crashes' before its commit:
    it restarts PARKED (marker), the cluster is frozen-but-consistent,
    and a re-driven resize_cluster converges both members with no
    committed write lost."""
    servers = [
        NodeServer(f"c{i}", data_dir=str(tmp_path / f"c{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", 2 * k + 1)
             for k in range(24)], tx)
        api.commit_transaction(tx)
        expect = sum(2 * k + 1 for k in range(24))

        # drive the protocol by hand up to a partial commit
        for m in ("c0", "c1"):
            servers[0]._rpc(m, "resize_prepare", (16, 6, 256))
        for m in ("c0", "c1"):
            servers[0]._rpc(m, "resize_freeze", (16,))
        for m in ("c0", "c1"):
            servers[0]._rpc(m, "resize_drain", None)
        servers[0]._rpc("c1", "resize_commit", (16,))
        assert servers[1].node.config.n_partitions == 16
        assert servers[0].node.config.n_partitions == 8

        # c0 "crashes" before its commit and restarts: parked, old
        # width, marker intact
        servers[0].close()
        c0b = NodeServer("c0", data_dir=str(tmp_path / "c0"),
                         config=_cfg())
        servers[0] = c0b
        assert c0b.meta.get("cluster_resize") == 16
        assert c0b.node.config.n_partitions == 8
        assert c0b._resize_parking

        # re-drive from the committed member: converges both
        servers[1].resize_cluster(16)
        assert c0b.node.config.n_partitions == 16
        assert not c0b._resize_parking
        assert servers[1].meta.get("cluster_resize") is None

        for srv in servers:
            assert _totals(srv.api, range(24)) == expect
    finally:
        for srv in servers:
            srv.close()


def test_member_crash_after_commit_restarts_at_new_width(tmp_path):
    """A member killed right after its commit (journal written, swap
    done) restarts at the NEW width from its persisted plan, still
    parked until a finish."""
    servers = [
        NodeServer(f"j{i}", data_dir=str(tmp_path / f"j{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects(
            [((k, "counter_pn", "b"), "increment", 5) for k in
             range(8)], tx)
        api.commit_transaction(tx)

        for m in ("j0", "j1"):
            servers[0]._rpc(m, "resize_prepare", (16, 6, 256))
        for m in ("j0", "j1"):
            servers[0]._rpc(m, "resize_freeze", (16,))
        for m in ("j0", "j1"):
            servers[0]._rpc(m, "resize_drain", None)
        servers[0]._rpc("j0", "resize_commit", (16,))

        servers[0].close()
        j0b = NodeServer("j0", data_dir=str(tmp_path / "j0"),
                         config=_cfg())
        servers[0] = j0b
        assert j0b.node.config.n_partitions == 16
        assert j0b._resize_parking  # marker still set until finish

        servers[1].resize_cluster(16)
        assert not j0b._resize_parking
        for srv in servers:
            assert _totals(srv.api, range(8)) == 40
    finally:
        for srv in servers:
            srv.close()


def test_resize_rejects_non_multiple_and_federated(tmp_path):
    servers = [
        NodeServer(f"v{i}", data_dir=str(tmp_path / f"v{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        with pytest.raises(ValueError):
            servers[0].resize_cluster(12)
        servers[0].source_factory = lambda p: (lambda: None)
        with pytest.raises(RuntimeError):
            servers[0].resize_cluster(16)
    finally:
        for srv in servers:
            srv.close()


# ------------------------------------------------------- true kill -9 tier


import json
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class _Proc:
    def __init__(self, node_id, data_dir, port, faults=""):
        self.proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "node_proc.py"),
             node_id, data_dir, str(port)] + ([faults] if faults
                                              else []),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        self.node_id = node_id
        ready = json.loads(self.proc.stdout.readline())
        assert ready.get("ready"), ready
        self.addr = ready["addr"]
        self.assembled = ready.get("assembled", False)

    def cmd(self, **req):
        resp = self.cmd_raw(**req)
        assert "error" not in resp, resp
        return resp

    def cmd_raw(self, **req):
        self.proc.stdin.write(json.dumps(req) + "\n")
        self.proc.stdin.flush()
        return json.loads(self.proc.stdout.readline())

    def stop(self):
        if self.proc.poll() is not None:
            return
        try:
            self.cmd_raw(cmd="exit")
        except Exception:  # noqa: BLE001
            pass
        self.proc.wait(timeout=10)


def test_kill9_in_resize_swap_recovers(tmp_path):
    """REAL kill -9 (os._exit inside the swap): member n2 dies with
    journal + new plan persisted but live logs unswapped; its restart
    resumes the swap from the journal, comes back parked, and a
    re-driven resize converges the DC with all data intact."""
    ports = [_free_port(), _free_port()]
    dirs = [str(tmp_path / "n1"), str(tmp_path / "n2")]
    procs = [
        _Proc("n1", dirs[0], ports[0]),
        _Proc("n2", dirs[1], ports[1], faults="die_in_resize_swap"),
    ]
    try:
        members = {p.node_id: p.addr for p in procs}
        ring = {str(i): f"n{(i % 2) + 1}" for i in range(4)}
        for p in procs:
            p.cmd(cmd="join", dc="dc1", ring=ring, members=members)
        ct = None
        for k in range(12):
            ct = procs[k % 2].cmd(
                cmd="update", key=k, type="counter_pn",
                op="increment", arg=k + 1,
                clock=ct)["clock"]

        # the resize drive hits n2's kill -9 mid-swap and fails
        resp = procs[0].cmd_raw(cmd="resize", n=8)
        assert "error" in resp, resp
        procs[1].proc.wait(timeout=10)
        assert procs[1].proc.returncode == 9

        # restart n2 WITHOUT the fault: journal resumes the swap; the
        # member comes back at the new width, parked until a finish
        procs[1] = _Proc("n2", dirs[1], ports[1])
        assert procs[1].assembled
        w = procs[1].cmd(cmd="width")
        assert w["n"] == 8 and w["parked"], w

        # re-drive from n1: converges and unparks
        procs[0].cmd(cmd="resize", n=8)
        for p in procs:
            w = p.cmd(cmd="width")
            assert w["n"] == 8 and not w["parked"], w

        # no committed write lost, readable from BOTH members
        for p in procs:
            total = 0
            for k in range(12):
                total += p.cmd(cmd="read", key=k, type="counter_pn",
                               clock=ct)["value"]
            assert total == sum(k + 1 for k in range(12))

        # still serving cross-node at the new width
        ct = procs[1].cmd(cmd="update", key=3, type="counter_pn",
                          op="increment", arg=10, clock=ct)["clock"]
        assert procs[0].cmd(cmd="read", key=3, type="counter_pn",
                            clock=ct)["value"] == 14
    finally:
        for p in procs:
            p.stop()


def test_freeze_refusal_unwinds_frozen_members(tmp_path):
    """A freeze-phase refusal (a handoff raced in between prepare and
    freeze) must abort the resize WITHOUT leaving the members that
    already froze gated — previously they stayed frozen (marker set,
    gate closed) until an operator re-drove the resize."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers = [
        NodeServer(f"fz{i}", data_dir=str(tmp_path / f"fz{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        # members freeze in sorted order (fz0 first); make fz1 refuse
        real = servers[1]._resize_freeze

        def refuse(new_n):
            raise RemoteCallError("injected freeze refusal")

        servers[1]._resize_freeze = refuse
        with pytest.raises(RemoteCallError):
            servers[0].resize_cluster(16)

        # the already-frozen member was unwound: marker cleared, gate
        # open, transactions admitted immediately on BOTH members —
        # and the prepare-phase staging (child .resize logs) was
        # discarded, not leaked
        import glob

        for i, srv in enumerate(servers):
            assert srv.meta.get("cluster_resize") is None
            assert srv._resize_fold is None
            assert not glob.glob(str(tmp_path / f"fz{i}" / "*.resize"))
            tx = srv.api.start_transaction()
            srv.api.update_objects(
                [((1, "counter_pn", "b"), "increment", 1)], tx)
            srv.api.commit_transaction(tx)

        # with the refusal gone, a re-driven resize completes
        servers[1]._resize_freeze = real
        servers[0].resize_cluster(16)
        for srv in servers:
            assert srv.node.config.n_partitions == 16
    finally:
        for srv in servers:
            srv.close()


def test_stale_ring_update_refused_after_resize(tmp_path):
    """A rebalance's re-plan broadcast that lands AFTER a resize (or
    while one is mid-flight) must be refused: applying an old-width
    ring over a widened member would leave its new partitions
    permanently stale; applying any ring under the resize marker would
    desync the fold."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers = [
        NodeServer(f"su{i}", data_dir=str(tmp_path / f"su{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        old_ring = dict(servers[0].node.ring)
        members = dict(servers[0]._members)

        # marker set (mid-resize): any ring update is refused
        servers[1].meta.put("cluster_resize", 16)
        with pytest.raises(RemoteCallError, match="resize in progress"):
            servers[1]._apply_ring_update(old_ring, members, [])
        servers[1].meta.delete("cluster_resize")

        servers[0].resize_cluster(16)

        # the lagging old-width broadcast arrives after the commit:
        # width check refuses it and the 16-wide ring survives
        with pytest.raises(RemoteCallError, match="width 8"):
            servers[1]._apply_ring_update(old_ring, members, [])
        assert len(servers[1].node.ring) == 16
        assert servers[1].node.config.n_partitions == 16
    finally:
        for srv in servers:
            srv.close()


def test_cutover_backout_preserves_in_doubt_entry(tmp_path):
    """A cutover retry on a parked-in-doubt partition that backs out on
    the flag-then-check (a resize_freeze raced its marker in) must
    RESTORE the in_doubt entry — previously it popped it, leaving a
    retired/parked partition with no handoff state: callers spun on
    retryable HandoffParked forever instead of the hard in-doubt error,
    and the resize guard no longer saw the partition as busy."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers = [
        NodeServer(f"id{i}", data_dir=str(tmp_path / f"id{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        p = next(q for q, o in servers[0].node.ring.items()
                 if o == "id0")
        pm = servers[0].node.partitions[p]
        with pm._lock:
            pm.parked = True
        servers[0]._handoff[p] = {"state": "in_doubt",
                                  "new_owner": "id1"}

        # drive the exact race window: the first marker check sees no
        # resize, the flag-then-check (after the drain entry is set)
        # sees one — as if resize_freeze journaled its marker between
        # the two
        real_meta = servers[0].meta

        class RaceMeta:
            def __init__(self):
                self.calls = 0

            def get(self, key, default=None):
                if key == "cluster_resize":
                    self.calls += 1
                    return None if self.calls == 1 else 16
                return real_meta.get(key, default)

            def __getattr__(self, name):
                return getattr(real_meta, name)

        servers[0].meta = RaceMeta()
        try:
            with pytest.raises(RemoteCallError,
                               match="resize in progress"):
                servers[0]._handoff_cutover(p, "id1", 0)
        finally:
            servers[0].meta = real_meta

        # the safety state survived the back-out
        assert servers[0]._handoff[p]["state"] == "in_doubt"
        # and the resize guard still refuses while it stands
        with pytest.raises(RemoteCallError, match="handoff in flight"):
            servers[0]._refuse_if_handoff_busy()
    finally:
        for srv in servers:
            srv.close()


def test_rebalance_redrive_after_refused_broadcast(tmp_path):
    """A rebalance whose ring_update broadcast is refused on one member
    (e.g. a mid-flight resize froze it) raises a re-drive error AFTER
    applying the plan locally; re-driving the SAME rebalance converges
    the cluster — the probe skips the move whose data already
    transferred instead of re-fetching it from the retired owner."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers = [
        NodeServer(f"rd{i}", data_dir=str(tmp_path / f"rd{i}"),
                   config=_cfg())
        for i in range(3)
    ]
    try:
        create_dc_cluster("dc1", 8, servers[:2], clients=[servers[2]])
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects([((0, "counter_pn", "b"), "increment", 7)],
                           tx)
        api.commit_transaction(tx)

        p = next(q for q, o in servers[0].node.ring.items()
                 if o == "rd0")
        new_ring = dict(servers[0].node.ring)
        new_ring[p] = "rd2"

        # rd1's ring_update refuses once (as a resize-frozen member
        # would); the cutover itself has already completed
        real = servers[1]._apply_ring_update
        calls = {"n": 0}

        def refuse_once(ring, members, clients):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RemoteCallError("injected: resize in progress")
            return real(ring, members, clients)

        servers[1]._apply_ring_update = refuse_once
        with pytest.raises(RemoteCallError, match="re-drive"):
            servers[0].rebalance(new_ring)

        # the driver applied locally (it must, for the re-drive to
        # see the move as done); data moved to rd2
        assert servers[0].node.ring[p] == "rd2"
        assert servers[1].node.ring[p] == "rd0"  # the refused member

        # re-drive: probe skips the completed move, broadcast lands,
        # every member converges, the handoff journal drains
        servers[0].rebalance(new_ring)
        for srv in servers:
            assert srv.node.ring[p] == "rd2"
        assert not (servers[0].meta.get("handoff_out") or {})

        # the moved partition still serves its history and new writes
        tx = servers[1].api.start_transaction()
        v = servers[1].api.read_objects([(0, "counter_pn", "b")], tx)
        servers[1].api.commit_transaction(tx)
        assert v[0] == 7
        tx = servers[2].api.start_transaction()
        servers[2].api.update_objects(
            [((0, "counter_pn", "b"), "increment", 1)], tx)
        cvc = servers[2].api.commit_transaction(tx)
        tx = servers[0].api.start_transaction(clock=cvc)
        v = servers[0].api.read_objects([(0, "counter_pn", "b")], tx)
        servers[0].api.commit_transaction(tx)
        assert v[0] == 8
    finally:
        for srv in servers:
            srv.close()


def test_same_width_redrive_abort_leaves_cluster_serving(tmp_path):
    """An idempotent same-width re-drive that aborts at freeze must
    fully unwind: width equality alone must not classify the healthy,
    already-finished members as 'committed' (that left the whole
    cluster gated with journaled markers).  Stale on-disk staged files
    from a dead earlier attempt are swept by the abort too — a later
    resize's swap would otherwise promote them over the live logs."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers = [
        NodeServer(f"sw{i}", data_dir=str(tmp_path / f"sw{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    try:
        create_dc_cluster("dc1", 8, servers)
        servers[0].resize_cluster(16)
        for srv in servers:
            assert srv.meta.get("cluster_resize") is None

        # a stale half-folded staged file from a crashed old attempt
        stale = tmp_path / "sw0" / "dc1_p3.log.resize"
        stale.write_bytes(b"half-folded garbage")

        def refuse(new_n):
            raise RemoteCallError("injected freeze refusal")

        real = servers[1]._resize_freeze
        servers[1]._resize_freeze = refuse
        with pytest.raises(RemoteCallError):
            servers[0].resize_cluster(16)
        servers[1]._resize_freeze = real

        assert not stale.exists()
        # every member serves immediately — no marker, no gate
        for srv in servers:
            assert srv.meta.get("cluster_resize") is None
            assert not srv._resize_parking
            tx = srv.api.start_transaction()
            srv.api.update_objects(
                [((2, "counter_pn", "b"), "increment", 1)], tx)
            srv.api.commit_transaction(tx)
    finally:
        for srv in servers:
            srv.close()


def test_redrive_rebalance_settles_in_doubt_old_owner(tmp_path):
    """Receiver adopts, reply lost, AND the settlement probe cannot
    reach it -> the old owner parks in doubt.  A re-driven rebalance
    (receiver reachable again) must settle the old owner's parked copy
    — not just probe-skip the move — or its ring_update refuses
    'moved without a handoff' on every re-drive, a livelock only a
    restart could break."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers = [
        NodeServer(f"sd{i}", data_dir=str(tmp_path / f"sd{i}"),
                   config=_cfg())
        for i in range(2)
    ]
    recv = NodeServer("sd2", data_dir=str(tmp_path / "sd2"),
                      config=_cfg())
    try:
        create_dc_cluster("dc1", 8, servers, clients=[recv])
        api = servers[0].api
        tx = api.start_transaction()
        api.update_objects([((0, "counter_pn", "b"), "increment", 5)],
                           tx)
        api.commit_transaction(tx)
        p = next(q for q, o in servers[0].node.ring.items()
                 if o == "sd0")

        # install applies at the receiver but the reply is 'lost', and
        # the settlement probe is 'unreachable' exactly once
        real_install = recv._handoff_install

        def applied_reply_lost(pp, base_offset, tail):
            real_install(pp, base_offset, tail)
            raise RemoteCallError("injected: reply lost")

        # probe call order: #1 the rebalance driver's probe-skip check
        # (fresh move -> must answer), #2 the old owner's settlement
        # probe after the lost reply (-> 'unreachable'), #3+ re-drive
        real_probe = recv._handoff_probe
        calls = {"n": 0}

        def probe_flaky(pp):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RemoteCallError("injected: unreachable")
            return real_probe(pp)

        recv._handoff_install = applied_reply_lost
        recv._handoff_probe = probe_flaky
        new_ring = dict(servers[0].node.ring)
        new_ring[p] = "sd2"
        with pytest.raises(RemoteCallError):
            servers[0].rebalance(new_ring)
        recv._handoff_install = real_install

        assert servers[0]._handoff[p]["state"] == "in_doubt"

        # re-drive: probe sees adoption, the old owner's copy is
        # settled (retired), the plan lands everywhere
        servers[0].rebalance(new_ring)
        from antidote_tpu.cluster.remote import RemotePartition as _RP  # noqa: F401
        assert servers[0].node.ring[p] == "sd2"
        assert not isinstance(servers[0].node.partitions[p],
                              PartitionManager)
        for srv in servers + [recv]:
            assert srv.node.ring[p] == "sd2"
        assert not (servers[0].meta.get("handoff_out") or {})

        # history and new writes both served
        tx = recv.api.start_transaction()
        v = recv.api.read_objects([(0, "counter_pn", "b")], tx)
        recv.api.commit_transaction(tx)
        assert v[0] == 5
    finally:
        for srv in servers + [recv]:
            srv.close()


def test_resize_refuses_divergent_rings_until_rebalance_redriven(tmp_path):
    """After a partially-refused rebalance broadcast the handoff
    journal is already drained, so no per-member check sees the
    divergence — the resize pre-flight must: with one member on the
    stale ring, resize_cluster refuses; once the rebalance is
    re-driven to convergence it proceeds."""
    from antidote_tpu.cluster.remote import RemoteCallError

    servers = [
        NodeServer(f"dv{i}", data_dir=str(tmp_path / f"dv{i}"),
                   config=_cfg())
        for i in range(3)
    ]
    try:
        create_dc_cluster("dc1", 8, servers[:2], clients=[servers[2]])
        p = next(q for q, o in servers[0].node.ring.items()
                 if o == "dv0")
        new_ring = dict(servers[0].node.ring)
        new_ring[p] = "dv1"

        real = servers[2]._apply_ring_update
        calls = {"n": 0}

        def refuse_once(ring, members, clients):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RemoteCallError("injected refusal")
            return real(ring, members, clients)

        servers[2]._apply_ring_update = refuse_once
        with pytest.raises(RemoteCallError, match="re-drive"):
            servers[0].rebalance(new_ring)

        # divergence is silent: journal drained, no handoff entries
        assert not (servers[0].meta.get("handoff_out") or {})
        assert servers[2].node.ring[p] == "dv0"  # stale

        with pytest.raises(RuntimeError, match="disagree"):
            servers[0].resize_cluster(16)

        servers[0].rebalance(new_ring)  # re-drive converges
        assert servers[2].node.ring[p] == "dv1"
        servers[0].resize_cluster(16)   # now allowed
        for srv in servers:
            assert len(srv.node.ring) == 16
    finally:
        for srv in servers:
            srv.close()

"""Chaos at FEDERATION scale: 2 DCs x 2 member node-servers each,
randomized workload over (almost) every CRDT type, with inter-DC link
flaps, silent frame loss, and a member kill -15/restart — all at once,
across 3 seeds.

The reference's hardest multi-DC suite does exactly this shape (kill
BEAM nodes of a multi-node DC mid-replication and assert convergence,
reference test/multidc/multiple_dcs_node_failure_SUITE.erl:85-120).
This harness is the federation-scale extension the round-3 listener
bugs called for: a member restart re-binds its advertised address,
re-observes the federation from persisted descriptors, and its slice
gap-repairs — under load, not in isolation.  (counter_b is excluded:
its decrements legitimately abort on rights, covered by its own
suite.)
"""

import random
import time

import pytest

from antidote_tpu.clocks import vc_max
from antidote_tpu.cluster import NodeServer
from antidote_tpu.cluster.federation import NodeInterDc, connect_federation
from antidote_tpu.config import Config
from antidote_tpu.interdc import InProcBus
from antidote_tpu.txn.coordinator import TransactionAborted

from tests.cluster.test_federation import make_dc, pump_all

TYPES = ["counter_pn", "counter_fat", "set_aw", "set_rw", "set_go",
         "register_lww", "register_mv", "flag_ew", "flag_dw",
         "map_go", "map_rr", "rga"]

ELEMS = ["a", "b", "c", "d"]


def _random_update(rng, tname):
    if tname in ("counter_pn", "counter_fat"):
        return ("increment", rng.randint(1, 3))
    if tname in ("set_aw", "set_rw", "set_go"):
        if tname != "set_go" and rng.random() < 0.35:
            return ("remove", rng.choice(ELEMS))
        return ("add", rng.choice(ELEMS))
    if tname in ("register_lww", "register_mv"):
        return ("assign", rng.choice(ELEMS))
    if tname in ("flag_ew", "flag_dw"):
        return (rng.choice(["enable", "disable"]), ())
    if tname == "map_go":
        return ("update", ((("n", "counter_pn"), ("increment", 1))))
    if tname == "map_rr":
        if rng.random() < 0.25:
            return ("remove", ("tags", "set_aw"))
        return ("update", ((("tags", "set_aw"),
                            ("add", rng.choice(ELEMS)))))
    if tname == "rga":
        return ("add_right", (0, rng.choice(ELEMS)))
    raise AssertionError(tname)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_chaos_federation_all_types_converge(tmp_path, seed):
    rng = random.Random(seed)
    bus = InProcBus()
    sa, na = make_dc(bus, tmp_path, "dcA")
    sb, nb = make_dc(bus, tmp_path, "dcB")
    connect_federation([na, nb])
    apis = [s.api for s in sa + sb]
    clocks = [None] * len(apis)
    keys = [(f"chaos_{t}_{k}", t, "bkt")
            for t in TYPES for k in range(2)]
    try:
        def burst(n, causal=True, exclude=()):
            live = [i for i in range(len(apis)) if i not in exclude]
            for _ in range(n):
                i = rng.choice(live)
                key = rng.choice(keys)
                op = _random_update(rng, key[1])
                try:
                    clocks[i] = apis[i].update_objects_static(
                        clocks[i] if causal else None, [(key, *op)])
                except TransactionAborted:
                    # a key owned by a dead member: that slice of the
                    # keyspace is unavailable until the restart — the
                    # write aborts cleanly, like the reference without
                    # replicas
                    assert exclude, "abort outside the down window"
            pump_all([na, nb])

        burst(30)

        # inter-DC partition: both DCs stay available; writes in the
        # window carry no cross-DC causal floor (a floor straddling
        # the cut would correctly block until the heal)
        for a in na:
            for b in nb:
                bus.set_link((a.dc_id, a.member_index),
                             (b.dc_id, b.member_index), False)
        burst(15, causal=False)
        for a in na:
            for b in nb:
                bus.set_link((a.dc_id, a.member_index),
                             (b.dc_id, b.member_index), True)
        burst(10)

        # silent frame loss inbound to BOTH dcB members: only opid gap
        # repair can recover the stream
        for nid in nb:
            bus.set_drop_rx((nid.dc_id, nid.member_index), True)
        burst(12, causal=False)
        for nid in nb:
            bus.set_drop_rx((nid.dc_id, nid.member_index), False)
        burst(10)

        # kill -15 one dcB member mid-workload and restart it from its
        # data dir: plan reload, advertised-address rebind, federation
        # re-observe from persisted descriptors, slice catch-up (the
        # round-3 listener-shutdown bugs lived exactly here)
        victim = rng.randrange(2)
        nb[victim].close()
        sb[victim].close()
        clocks[2 + victim] = None
        burst(12, causal=False, exclude=(2 + victim,))
        name = f"dcB_n{victim + 1}"
        srv = NodeServer(name, data_dir=str(tmp_path / name),
                         config=Config(n_partitions=4,
                                       heartbeat_s=0.02,
                                       clock_wait_timeout_s=10.0))
        assert srv.node is not None  # plan reloaded from disk
        nid = NodeInterDc(srv, bus)
        assert "dcA" in nid.remote  # persisted descriptors re-observed
        nid.start()
        sb[victim], nb[victim] = srv, nid
        apis[2 + victim] = srv.api
        burst(30)

        merged = vc_max([c for c in clocks if c is not None])
        deadline = time.monotonic() + 45.0
        while True:
            views = []
            try:
                for api in apis:
                    vals, _ = api.read_objects_static(merged, keys)
                    views.append(vals)
            except TimeoutError:
                assert time.monotonic() < deadline, \
                    "replicas never covered the merged clock"
                pump_all([na, nb])
                continue
            if all(v == views[0] for v in views[1:]):
                break
            assert time.monotonic() < deadline, (
                "replicas disagree at the merged clock:\n"
                + "\n".join(repr(v) for v in views))
            pump_all([na, nb])
            time.sleep(0.01)
        # sanity: the workload actually produced state everywhere
        assert any(v not in (0, [], {}, False, None, frozenset())
                   for v in views[0])
    finally:
        for nid in na + nb:
            nid.close()
        for s in sa + sb:
            s.close()

"""Elasticity soak: every cluster reshaping operation in sequence on
ONE live DC under continuous writers — membership growth, ownership
rebalance, partition-count resize, member crash + restart — with an
exact-total oracle at every checkpoint.  The interactions between the
mechanisms (a rebalance after a resize, a restart after both, batched
2PC/read RPCs across all of it) are where composition bugs live;
the per-mechanism suites cannot see them."""

import threading
import time

from antidote_tpu.cluster import NodeServer, create_dc_cluster
from antidote_tpu.config import Config
from antidote_tpu.txn.coordinator import TransactionAborted
from antidote_tpu.txn.manager import PartitionManager


def _cfg():
    return Config(n_partitions=4, heartbeat_s=0.05)


def test_full_elasticity_soak(tmp_path):
    servers = {
        f"s{i}": NodeServer(f"s{i}", data_dir=str(tmp_path / f"s{i}"),
                            config=_cfg())
        for i in range(2)
    }
    extra = None
    stop = threading.Event()
    committed = [0, 0]
    maybes = [0, 0]  # commit raised AFTER the decision may have landed
    errs = []

    def writer(slot, api, seed):
        k = 0
        while not stop.is_set():
            key = (seed * 11 + k) % 48
            k += 1
            tx = None
            try:
                tx = api.start_transaction()
                api.update_objects(
                    [((key, "counter_pn", "b"), "increment", 1)], tx)
                api.commit_transaction(tx)
                committed[slot] += 1
            except TransactionAborted:
                pass
            except TimeoutError:
                # a timeout during COMMIT may have applied (reply
                # lost after the decision): exact equality would
                # undercount — track as in-doubt
                if tx is not None and tx.writeset:
                    maybes[slot] += 1
            except BaseException as e:  # noqa: BLE001
                errs.append(e)
                return

    def check_totals(api):
        tx = api.start_transaction()
        vals = api.read_objects(
            [(k, "counter_pn", "b") for k in range(48)], tx)
        api.commit_transaction(tx)
        lo, hi = sum(committed), sum(committed) + sum(maybes)
        assert lo <= sum(vals) <= hi, (sum(vals), lo, hi)

    try:
        create_dc_cluster("dc1", 4, list(servers.values()))
        threads = [
            threading.Thread(target=writer,
                             args=(i, servers[f"s{i}"].api, i))
            for i in range(2)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)

        # 1. grow the partition count 4 -> 8 while serving
        servers["s0"].resize_cluster(8)
        time.sleep(0.2)

        # 2. admit a third member and hand it two children
        extra = NodeServer("s2", data_dir=str(tmp_path / "s2"),
                           config=_cfg())
        servers["s0"].add_member("s2", extra.addr)
        new_ring = dict(servers["s0"].node.ring)
        new_ring[1] = "s2"
        new_ring[5] = "s2"
        servers["s0"].rebalance(new_ring)
        time.sleep(0.2)

        # 3. resize AGAIN on the reshaped 3-owner ring (8 -> 16)
        servers["s0"].resize_cluster(16)
        time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "writer wedged past the join"
        assert not errs, errs
        assert sum(committed) > 30, committed
        for srv in list(servers.values()) + [extra]:
            assert srv.node.config.n_partitions == 16
        assert isinstance(extra.node.partitions[1], PartitionManager)
        assert isinstance(extra.node.partitions[9], PartitionManager)
        check_totals(extra.api)

        # 4. crash + restart a data member; totals survive
        servers["s1"].close()
        servers["s1"] = NodeServer(
            "s1", data_dir=str(tmp_path / "s1"), config=_cfg())
        assert servers["s1"].node.config.n_partitions == 16
        check_totals(servers["s1"].api)
        check_totals(servers["s0"].api)

        # 5. the reshaped DC still serves new cross-node writes
        tx = extra.api.start_transaction()
        extra.api.update_objects(
            [((k, "counter_pn", "b"), "increment", 1)
             for k in range(16)], tx)
        cvc = extra.api.commit_transaction(tx)
        tx = servers["s0"].api.start_transaction(clock=cvc)
        vals = servers["s0"].api.read_objects(
            [(k, "counter_pn", "b") for k in range(16)], tx)
        servers["s0"].api.commit_transaction(tx)
        assert all(v >= 1 for v in vals)
    finally:
        stop.set()
        for srv in servers.values():
            srv.close()
        if extra is not None:
            extra.close()

"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a forced 8-device CPU platform (the driver separately
dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).
Must run before the first `import jax` anywhere in the test session.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

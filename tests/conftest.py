"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a forced 8-device CPU platform (the driver separately
dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).

NOTE: the environment pins JAX_PLATFORMS=axon via sitecustomize at
interpreter start, so overriding the env var here is too late — the
platform must be overridden through jax.config.  XLA_FLAGS is still read
at backend-init time, which happens after conftest import, so the forced
device count can go through the environment.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: opt-in long-running reproduction loops (flake rehit "
        "recipes, soak tests) — excluded from tier-1 via -m 'not "
        "slow'")

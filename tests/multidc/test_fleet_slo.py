"""Fleet health plane (ISSUE 17 acceptance): tools/slo_report judges
a live in-process 2-DC cluster against the default SLO registry with
error-budget arithmetic, a deliberately-degraded leg (the lying
causal-probe reader from the ISSUE-7 apparatus) flips EXACTLY the
affected objectives to failing, the knob-gated FleetScraper
federates endpoints and refreshes the SLO_* gauges, and the scrape
error path isolates a dead endpoint instead of killing the round."""

import json
import os
import sys
import time

import pytest

from antidote_tpu import stats
from antidote_tpu.config import Config
from antidote_tpu.interdc.dc import DataCenter, connect_dcs
from antidote_tpu.interdc.transport import InProcBus
from antidote_tpu.obs import fleet, probe, slo
from antidote_tpu.obs.spans import tracer

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "tools"))
import slo_report  # noqa: E402

KEY = ("fleet_k", "set_aw", "bkt")


@pytest.fixture
def fleet2(tmp_path):
    """Two connected DCs with the causal probe armed, plus a live
    metrics server over the process-global registry."""
    saved_rate = tracer.sample_rate
    tracer.clear()
    bus = InProcBus()
    dcs = []
    for i in range(2):
        cfg = Config(n_partitions=2, heartbeat_s=0.02,
                     clock_wait_timeout_s=10.0,
                     trace_sample_rate=1.0,
                     obs_causal_probe_s=0.05,
                     flight_recorder_dir=str(tmp_path / "flightrec"))
        dcs.append(DataCenter(f"dc{i + 1}", bus, config=cfg,
                              data_dir=str(tmp_path / f"dc{i + 1}")))
    connect_dcs(dcs)
    for dc in dcs:
        dc.start_bg_processes()
    srv = stats.MetricsServer(port=0).start()
    yield dcs, f"http://127.0.0.1:{srv.port}"
    srv.stop()
    for dc in dcs:
        dc.close()
    tracer.sample_rate = saved_rate
    tracer.clear()


def _commit(dc1, dc2, elem):
    tx = dc1.start_transaction()
    dc1.update_objects([(KEY, "add", elem)], tx)
    ct = dc1.commit_transaction(tx)
    vals, _ = dc2.read_objects_static(ct, [KEY])
    assert elem in vals[0]


class _LyingReader:
    """Peer facade whose causal read omits the probe element — the
    ISSUE-7 violation apparatus, reused as the degraded leg."""

    def __init__(self, real):
        self.node = real.node
        self._real = real

    def read_objects_static(self, clock, objs):
        vals, vc = self._real.read_objects_static(clock, objs)
        return [set()], vc


def _budget_arithmetic_holds(verdict):
    for name, v in verdict["objectives"].items():
        assert v["burn_rate"] >= 0.0, (name, v)
        assert 0.0 <= v["budget_remaining"] <= 1.0, (name, v)
        assert v["budget_remaining"] == pytest.approx(
            max(0.0, 1.0 - v["burn_rate"])), (name, v)
        assert v["ok"] == (v["burn_rate"] <= v["burn_threshold"]), \
            (name, v)


class TestSloReportCli:
    def test_healthy_cluster_verdict(self, fleet2, tmp_path, capsys):
        """The acceptance run: slo_report --cluster against the live
        endpoint covers >= 6 objectives with coherent error-budget
        arithmetic, and a healthy window exits 0."""
        (dc1, dc2), url = fleet2
        for i in range(3):
            _commit(dc1, dc2, f"h{i}")
        base = str(tmp_path / "base.json")
        # window start: snapshot the cumulative families (the global
        # registry carries every prior test's history — an absolute
        # verdict would judge ancient probe violations)
        rc = slo_report.main(["--cluster", url,
                              "--save-baseline", base, "--json"])
        capsys.readouterr()
        assert rc in (0, 1)
        for _ in range(3):
            _commit(dc1, dc2, f"w{time.monotonic_ns()}")
        rc = slo_report.main(["--cluster", url, "--baseline", base,
                              "--json"])
        verdict = json.loads(capsys.readouterr().out)
        assert rc == 0, verdict["failing"]
        assert verdict["ok"] is True and verdict["failing"] == []
        assert len(verdict["objectives"]) >= 6
        _budget_arithmetic_holds(verdict)
        # the commit traffic actually reached the judged window
        commit = verdict["objectives"]["commit_latency_p99"]
        assert not commit["no_data"] and commit["observations"] >= 3

    def test_degraded_leg_flips_exactly_the_affected_objectives(
            self, fleet2, tmp_path, capsys):
        (dc1, dc2), url = fleet2
        _commit(dc1, dc2, "d0")
        base = str(tmp_path / "base.json")
        slo_report.main(["--cluster", url, "--save-baseline", base,
                         "--json"])
        capsys.readouterr()
        rc = slo_report.main(["--cluster", url, "--baseline", base,
                              "--json"])
        healthy = json.loads(capsys.readouterr().out)
        assert rc == 0, healthy["failing"]

        # degrade ONE leg: a lying reader trips the causal-probe
        # violation counter (zero-target objective — any event burns
        # the whole budget)
        p = probe.CausalProbe(dc1, period_s=60.0)
        lying = _LyingReader(p._peers()[0])
        p._peers = lambda: [lying]
        assert p.run_once() == 1

        rc = slo_report.main(["--cluster", url, "--baseline", base,
                              "--json"])
        degraded = json.loads(capsys.readouterr().out)
        assert rc == 1
        flipped = set(degraded["failing"]) - set(healthy["failing"])
        assert flipped == {"probe_violations"}, degraded["failing"]
        pv = degraded["objectives"]["probe_violations"]
        assert pv["ok"] is False and pv["value"] >= 1
        assert pv["budget_remaining"] == 0.0
        _budget_arithmetic_holds(degraded)
        # the human rendering carries the same verdict
        rc = slo_report.main(["--cluster", url, "--baseline", base])
        out = capsys.readouterr().out
        assert rc == 1 and "BREACHED" in out \
            and "probe_violations" in out

    def test_no_reachable_source_is_exit_2(self, capsys):
        rc = slo_report.main(["--cluster",
                              "http://127.0.0.1:1/nope", "--json"])
        capsys.readouterr()
        assert rc == 2


class TestFleetScraper:
    def test_scrape_once_federates_and_refreshes_gauges(self, fleet2):
        (dc1, dc2), url = fleet2
        _commit(dc1, dc2, "s0")
        scraper = fleet.FleetScraper(endpoints=[url],
                                     include_local=False,
                                     name="t")
        snap = scraper.scrape_once()
        assert snap["errors"] == {}
        assert url in snap["sources"]
        src = snap["sources"][url]
        assert "antidote_txn_commit_latency_seconds_count" \
            in src["metrics"]
        # the remote pipeline snapshot rode along, probe section and
        # all (the /debug/pipeline best-effort leg)
        assert "probe" in src["pipeline"]["dcs"]["dc1"]
        # the verdict was computed and the SLO_* gauges refreshed
        assert scraper.rounds == 1
        assert len(scraper.last_verdict["objectives"]) >= 6
        reg = stats.registry
        assert reg.fleet_sources.value() == 1.0
        assert reg.fleet_scrape_age.value() == 0.0  # first round
        for name in scraper.last_verdict["objectives"]:
            assert reg.slo_ok.value(objective=name) in (0.0, 1.0)
            assert reg.slo_burn_rate.value(objective=name) is not None
        # merged samples graft the src label
        merged = fleet.merged_metrics(snap)
        fam = merged["antidote_txn_commit_latency_seconds_count"]
        assert all(labels.get("src") == url for labels, _ in fam)

    def test_dead_endpoint_is_isolated_not_fatal(self, fleet2):
        (_dc1, _dc2), url = fleet2
        dead = "http://127.0.0.1:1"
        before = stats.registry.fleet_scrape_errors.value(source=dead)
        scraper = fleet.FleetScraper(endpoints=[url, dead],
                                     include_local=False, name="t2")
        snap = scraper.scrape_once()
        assert url in snap["sources"]
        assert dead in snap["errors"]
        assert stats.registry.fleet_scrape_errors.value(source=dead) \
            == before + 1
        # the verdict still landed from the live source
        assert scraper.last_verdict is not None

    def test_knob_gated_loop_rides_the_dc_lifecycle(self, tmp_path):
        """fleet_scrape_s > 0 elects the background loop on
        start_bg_processes (the obs_causal_probe_s mold) and
        _stop_bg_processes reaps it; the default keeps it off."""
        import threading

        bus = InProcBus()
        cfg = Config(n_partitions=2, heartbeat_s=0.02,
                     clock_wait_timeout_s=10.0,
                     fleet_scrape_s=0.05)
        dc = DataCenter("dcF", bus, config=cfg,
                        data_dir=str(tmp_path / "dcF"))
        dc.start_bg_processes()
        try:
            assert dc._fleet_scraper is not None
            names = [t.name for t in threading.enumerate()]
            assert any(n == "fleet-scrape-dcF" for n in names), names
            deadline = time.monotonic() + 10.0
            while dc._fleet_scraper.rounds < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert dc._fleet_scraper.rounds >= 2
        finally:
            dc.close()
        assert dc._fleet_scraper is None
        assert not any(t.name == "fleet-scrape-dcF"
                       for t in threading.enumerate())

    def test_knob_off_means_no_thread(self, tmp_path):
        import threading

        bus = InProcBus()
        dc = DataCenter("dcG", bus,
                        config=Config(n_partitions=2,
                                      heartbeat_s=0.02,
                                      clock_wait_timeout_s=10.0),
                        data_dir=str(tmp_path / "dcG"))
        dc.start_bg_processes()
        try:
            assert dc._fleet_scraper is None
            assert not any(t.name.startswith("fleet-scrape-")
                           for t in threading.enumerate())
        finally:
            dc.close()

"""Multi-DC GentleRain tests — the multidc gr_SUITE analogue
(reference test/multidc/gr_SUITE.erl): cross-DC reads at an all-GST
snapshot, with the GST advanced by heartbeats from every peer.
"""

import time

from tests.multidc.conftest import make_cluster


def test_gr_replicated_read(bus, tmp_path):
    dcs = make_cluster(bus, tmp_path, 3, txn_prot="gr")
    try:
        dc1, dc2, _dc3 = dcs
        bo = ("gr_multi", "counter_pn", "bkt")
        ct = dc1.update_objects_static(None, [(bo, "increment", 4)])

        # a GR read at dc1 with its own commit clock blocks until every
        # peer's heartbeat pushes the GST past the commit time, then the
        # all-GST snapshot includes the write
        vals, rvc = dc1.read_objects_static(ct, [bo])
        assert vals == [4]
        assert len(set(dict(rvc).values())) == 1

        # at dc2 the value arrives over replication; GR reads converge
        deadline = time.monotonic() + 10.0
        while True:
            vals, _ = dc2.read_objects_static(None, [bo])
            if vals == [4]:
                break
            assert time.monotonic() < deadline, "GR read never converged"
            time.sleep(0.01)

        # chaining: dc2 updates on top of its GR read clock; dc1's GR
        # wait rule only covers dc1's own entry (reference
        # gr_snapshot_obtain checks Dt = ClientClock[local dc]), so
        # dc2's fresh commit becomes visible once the GST passes its
        # commit time — poll to convergence, as GentleRain promises
        ct2 = dc2.update_objects_static(rvc, [(bo, "increment", 1)])
        deadline = time.monotonic() + 10.0
        while True:
            vals, _ = dc1.read_objects_static(ct2, [bo])
            if vals == [5]:
                break
            assert time.monotonic() < deadline, "chained GR read stale"
            time.sleep(0.01)
    finally:
        for dc in dcs:
            dc.close()

"""Transaction-journey plane (ISSUE 7 acceptance): a sampled txn's
span tree stitches across a 2-DC federation (origin + remote halves
share the txid correlator), the VIS_* visibility-latency families
populate from the carried origin-commit wallclock, /debug/pipeline
serves the one-object pipeline snapshot, tools/txn_journey.py
reconstructs the commit→visible chain from a recorded trace, and the
causal-probe auditor measures real write→remote-read staleness (and
alarms on a causal-order violation)."""

import json
import time
import urllib.request

import pytest

from antidote_tpu import stats
from antidote_tpu.config import Config
from antidote_tpu.interdc.dc import DataCenter, connect_dcs
from antidote_tpu.interdc.transport import InProcBus
from antidote_tpu.obs import pipeline, probe
from antidote_tpu.obs.events import recorder
from antidote_tpu.obs.spans import tracer

KEY = ("jk", "set_aw", "bkt")

#: the journey's remote half — every name must appear for a sampled
#: txn that replicated (the tentpole's stitched-tree contract)
REMOTE_STAGES = {"interdc_rx", "subbuf_admit", "interdc_deliver",
                 "depgate_admit", "interdc_visible"}
ORIGIN_STAGES = {"txn_start", "txn_commit", "interdc_ship_stage"}


@pytest.fixture
def journey2(tmp_path):
    """Two connected DCs, tracing at 1.0, fast samplers, probe armed."""
    saved = (tracer.sample_rate, recorder.dump_dir)
    tracer.clear()
    recorder.clear()
    bus = InProcBus()
    dcs = []
    for i in range(2):
        cfg = Config(n_partitions=2, heartbeat_s=0.02,
                     clock_wait_timeout_s=10.0,
                     trace_sample_rate=1.0,
                     staleness_sample_s=0.05,
                     obs_causal_probe_s=0.05,
                     flight_recorder_dir=str(tmp_path / "flightrec"))
        dcs.append(DataCenter(f"dc{i + 1}", bus, config=cfg,
                              data_dir=str(tmp_path / f"dc{i + 1}")))
    connect_dcs(dcs)
    for dc in dcs:
        dc.start_bg_processes()
    yield dcs
    for dc in dcs:
        dc.close()
    (tracer.sample_rate, recorder.dump_dir) = saved
    tracer.clear()
    recorder.clear()


def _await(predicate, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _commit_and_replicate(dc1, dc2, elem="alpha"):
    tx = dc1.start_transaction()
    dc1.update_objects([(KEY, "add", elem)], tx)
    ct = dc1.commit_transaction(tx)
    vals, _ = dc2.read_objects_static(ct, [KEY])
    assert elem in vals[0]
    _await(lambda: tracer.spans(txid=tx.txid, name="interdc_visible"),
           what="remote visible instant")
    return tx.txid, ct


class TestStitchedSpanTree:
    def test_origin_and_remote_halves_share_the_txid(self, journey2):
        dc1, dc2 = journey2
        txid, _ct = _commit_and_replicate(dc1, dc2)
        names = {s.name for s in tracer.spans(txid=txid)}
        assert ORIGIN_STAGES <= names, names
        assert REMOTE_STAGES <= names, names
        # one trace id across both halves: every span carries it
        assert all(s.txid == txid for s in tracer.spans(txid=txid))
        # and the chain is ordered: commit (origin) precedes the wire
        # rx, which precedes the visible instant (remote)
        t = {n: min(s.start_us for s in tracer.spans(txid=txid, name=n))
             for n in ("txn_commit", "interdc_rx", "interdc_visible")}
        assert t["txn_commit"] <= t["interdc_rx"] <= t["interdc_visible"]

    def test_visible_instant_carries_the_measured_lag(self, journey2):
        """The visible instant carries a REAL measured lag: finite,
        non-negative, and the same sample lands in the vis_lag
        histogram (bucket population, not an absolute wall-clock
        bound — on a loaded box the in-process bus can legitimately
        take longer than any fixed cap, which tripped the PR-11
        tier-1 run)."""
        dc1, dc2 = journey2
        before = stats.registry.vis_lag.count(dc="dc2", peer="dc1")
        txid, _ct = _commit_and_replicate(dc1, dc2, elem="beta")
        vis = tracer.spans(txid=txid, name="interdc_visible")
        assert vis and vis[0].args["origin"] == "dc1"
        lag = vis[0].args["vis_lag_s"]
        assert lag >= 0.0 and lag == lag and lag != float("inf")
        # structural: the histogram observed the sample — some bucket
        # population grew and the running bucket sum equals the count
        assert stats.registry.vis_lag.count(dc="dc2", peer="dc1") \
            > before
        counts = stats.registry.vis_lag.counts(dc="dc2", peer="dc1")
        assert sum(counts) == stats.registry.vis_lag.count(
            dc="dc2", peer="dc1")

    def test_origin_sampling_decision_propagates(self, journey2):
        """A receiver at a LOW local rate still records the remote half
        of a txn the origin sampled: the frame trace header carries the
        origin's rate, and the receiver replays its deterministic
        decision (tracer.adopt)."""
        dc1, dc2 = journey2
        from antidote_tpu.obs.spans import txid_decision

        # origin keeps rate 1.0 (the fixture); drop the receiver-side
        # DECISION regime to partial by flipping the global rate right
        # before delivery would decide.  The tracer is process-global,
        # so emulate the cross-process case through adopt() directly:
        txid = ("adopted", "txn")
        assert not txid_decision(txid, 0.004)  # unsampled at 0.4%
        tracer.sample_rate = 0.004
        assert tracer.sampled(txid) is False
        tracer.adopt(txid, True)  # the origin's carried decision
        assert tracer.sampled(txid) is True
        tracer.instant("remote_half", "interdc", txid=txid)
        assert tracer.spans(txid=txid, name="remote_half")
        tracer.sample_rate = 1.0

    def test_non_tracing_origin_never_pins_local_sampling(self):
        """A permille-0 trace header means the origin was NOT tracing
        — there is no origin decision to replay, and seeding False
        would silently disable this DC's own partial-rate sampling
        for the whole stream (review finding)."""

        class FakeTxn:
            def __init__(self, txid):
                self.records = [type("R", (), {"txid": txid})()]

        saved = tracer.sample_rate
        try:
            # a txid the local 60% rate DOES sample
            from antidote_tpu.obs.spans import txid_decision

            txid = next(("t", i) for i in range(1000)
                        if txid_decision(("t", i), 0.6))
            tracer.sample_rate = 0.6
            tracer.adopt_from_wire((0, 123), [FakeTxn(txid)])
            assert tracer.sampled(txid) is True, \
                "permille-0 header must not override local sampling"
            # a real origin decision (permille 1000) DOES seed
            unsampled = next(("u", i) for i in range(1000)
                             if not txid_decision(("u", i), 0.6))
            tracer.adopt_from_wire((1000, 123), [FakeTxn(unsampled)])
            assert tracer.sampled(unsampled) is True
        finally:
            tracer.sample_rate = saved


class TestVisibilityMetrics:
    def test_visibility_lag_histogram_populates_per_peer(self, journey2):
        dc1, dc2 = journey2
        for i in range(3):
            _commit_and_replicate(dc1, dc2, elem=f"v{i}")
        h = stats.registry.vis_lag
        assert h.count(dc="dc2", peer="dc1") >= 3
        # cumulative bucket monotonicity (the panel contract):
        # per-bucket raw counts are non-negative, so the running sum
        # never decreases and ends at the count
        counts = h.counts(dc="dc2", peer="dc1")
        assert all(c >= 0 for c in counts)
        cum = 0
        for c in counts:
            cum += c
        assert cum == h.count(dc="dc2", peer="dc1")
        text = stats.registry.exposition()
        assert ('antidote_vis_visibility_lag_seconds_bucket'
                '{dc="dc2",peer="dc1",le="+Inf"}') in text

    def test_safe_time_lag_gauge_per_partition(self, journey2):
        dc1, _dc2 = journey2
        _await(lambda: stats.registry.vis_safe_time_lag.value(
            dc="dc1", partition="0") is not None,
            what="safe-time-lag sample")
        for p in ("0", "1"):
            lag = stats.registry.vis_safe_time_lag.value(
                dc="dc1", partition=p)
            assert lag is not None and lag >= 0.0

    def test_histogram_is_monotone_under_load(self, journey2):
        """Observing more txns never decreases any cumulative bucket
        (VIS_* monotonicity — the satellite's explicit check)."""
        dc1, dc2 = journey2

        def cumulative():
            counts = stats.registry.vis_lag.counts(dc="dc2", peer="dc1")
            out, cum = [], 0
            for c in counts:
                cum += c
                out.append(cum)
            return out

        _commit_and_replicate(dc1, dc2, elem="m0")
        before = cumulative()
        _commit_and_replicate(dc1, dc2, elem="m1")
        after = cumulative()
        assert all(b >= a for a, b in zip(before, after))
        assert after[-1] > before[-1]


class TestPipelineSnapshot:
    SECTIONS = {"ship", "sub_bufs", "gates", "ingest", "log", "stable",
                "fabric", "native", "probe", "connected_dcs"}

    def test_snapshot_schema(self, journey2):
        dc1, dc2 = journey2
        _commit_and_replicate(dc1, dc2, elem="p0")
        snap = pipeline.snapshot()
        assert set(snap) == {"at_us", "dcs", "threads"}
        assert {"dc1", "dc2"} <= set(snap["dcs"])
        for name in ("dc1", "dc2"):
            d = snap["dcs"][name]
            assert set(d) == self.SECTIONS, d.keys()
            for p in ("0", "1"):
                lg = d["log"][p]
                assert lg["enabled"]
                assert {"group", "staged_records", "staged_bytes",
                        "oldest_staged_age_us", "written_end",
                        "synced_end", "end", "fsyncs",
                        "drained_records"} <= set(lg)
            for p in ("0", "1"):
                ship = d["ship"][p]
                assert {"staged_txns", "staged_bytes", "oldest_age_us",
                        "outbox_frames", "draining",
                        "last_sent_opid"} <= set(ship)
                gate = d["gates"][p]
                assert {"pending", "queues", "applied_vc",
                        "ring"} <= set(gate)
            for stream in d["sub_bufs"].values():
                assert {"state", "buffered_txns",
                        "last_opid"} <= set(stream)
            assert "snapshot" in d["stable"]
            assert set(d["stable"]["per_partition"]) == {"0", "1"}
            # the probe section (ISSUE 17): armed by the fixture's
            # obs_causal_probe_s, carries the per-peer depth
            pr = d["probe"]
            assert pr["enabled"] is True
            assert {"period_s", "rounds", "violations",
                    "last_violation_at_us", "peers"} <= set(pr)
        # the origin actually shipped: its stream watermark moved
        assert any(s["last_sent_opid"] > 0
                   for s in snap["dcs"]["dc1"]["ship"].values())

    def test_debug_pipeline_endpoint(self, journey2):
        dc1, dc2 = journey2
        _commit_and_replicate(dc1, dc2, elem="p1")
        srv = stats.MetricsServer(port=0).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/pipeline",
                    timeout=10) as r:
                doc = json.load(r)
            assert {"dc1", "dc2"} <= set(doc["dcs"])
            assert set(doc["dcs"]["dc1"]) == self.SECTIONS
            # the same server now answers /debug/health (ISSUE 17)
            # with the SLO verdict over its own registry
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/health",
                    timeout=10) as r:
                health = json.load(r)
            assert {"at_us", "ok", "failing",
                    "objectives"} <= set(health)
            assert isinstance(health["ok"], bool)
            assert isinstance(health["failing"], list)
            assert len(health["objectives"]) >= 6
            for name, obj in health["objectives"].items():
                assert {"ok", "kind", "family", "target",
                        "burn_rate", "budget_remaining",
                        "no_data"} <= set(obj), (name, obj)
        finally:
            srv.stop()


class TestTxnJourneyCli:
    def test_cli_prints_full_chain_with_latencies(self, journey2,
                                                  tmp_path, capsys):
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "..",
            "tools"))
        import txn_journey

        dc1, dc2 = journey2
        txid, _ct = _commit_and_replicate(dc1, dc2, elem="cli")
        path = tracer.save(str(tmp_path / "spans.json"))
        rc = txn_journey.main([json.dumps(list(txid)), "--file", path])
        out = capsys.readouterr().out
        assert rc == 0
        for stage in ("txn_commit", "interdc_ship_stage", "interdc_rx",
                      "subbuf_admit", "depgate_admit",
                      "interdc_visible"):
            assert stage in out, out
        assert "commit -> visible:" in out
        assert "ms" in out  # per-stage latencies are printed

        # --json emits machine-readable rows with the same chain
        rc = txn_journey.main([json.dumps(list(txid)), "--file", path,
                               "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["commit_to_visible_us"] > 0
        stages = [r["stage"] for r in doc["stages"]]
        assert stages.index("txn_commit") \
            < stages.index("interdc_visible")

        # --list surfaces the txid for operators who only have a dump
        rc = txn_journey.main(["--list", "--file", path])
        assert rc == 0
        assert json.dumps(list(txid)) in capsys.readouterr().out

    def test_cluster_mode_stitches_two_live_endpoints(self, journey2,
                                                      capsys):
        """--cluster url1,url2 (ISSUE 17): one cross-DC txn's origin
        and remote spans fetched from two live /debug/spans endpoints
        merge into a single tree with per-stage deltas.  Both
        endpoints here serve the same process-global tracer — the
        dedup by (name, ts, dur, pid, tid) must keep the merged
        chain identical to a single endpoint's, not doubled."""
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "..",
            "tools"))
        import txn_journey

        dc1, dc2 = journey2
        txid, _ct = _commit_and_replicate(dc1, dc2, elem="fleet")
        s1 = stats.MetricsServer(port=0).start()
        s2 = stats.MetricsServer(port=0).start()
        try:
            u1 = f"http://127.0.0.1:{s1.port}"
            u2 = f"http://127.0.0.1:{s2.port}"
            rc = txn_journey.main([json.dumps(list(txid)),
                                   "--cluster", f"{u1},{u2}",
                                   "--json"])
            doc = json.loads(capsys.readouterr().out)
            assert rc == 0
            stages = [r["stage"] for r in doc["stages"]]
            # the stitched tree covers BOTH halves of the journey
            assert ORIGIN_STAGES <= set(stages), stages
            assert REMOTE_STAGES <= set(stages), stages
            assert stages.index("txn_commit") \
                < stages.index("interdc_visible")
            assert doc["commit_to_visible_us"] > 0
            # per-stage deltas are present and non-negative
            assert all(r["delta_us"] is None or r["delta_us"] >= 0
                       for r in doc["stages"])

            # dedup: the 2-endpoint merge equals the 1-endpoint view
            rc = txn_journey.main([json.dumps(list(txid)),
                                   "--cluster", u1, "--json"])
            single = json.loads(capsys.readouterr().out)
            assert rc == 0
            assert [r["stage"] for r in single["stages"]] == stages
        finally:
            s1.stop()
            s2.stop()


class TestGapForensics:
    def test_gap_and_repair_recorded_even_unsampled(self):
        """Gap/repair events are rare diagnostics: they must reach the
        flight recorder UNCONDITIONALLY, not ride the span sampler
        (at the default 0.05 rate an untagged instant is thinned
        ~19/20 — review finding)."""
        from antidote_tpu.interdc.sub_buf import SubBuf
        from antidote_tpu.interdc.wire import InterDcTxn
        from antidote_tpu.oplog.records import LogRecord, OpId

        saved = tracer.sample_rate
        recorder.clear()
        tracer.sample_rate = 0.0  # spans fully off
        try:
            def txn(prev, op, ts):
                recs = [LogRecord(OpId("o", op), ("t", op),
                                  ("commit", ("o", ts), None))]
                return InterDcTxn(dc_id="o", partition=0,
                                  prev_log_opid=prev, snapshot_vc=None,
                                  timestamp=ts, records=recs)

            delivered = []
            buf = SubBuf("o", 0, deliver=delivered.append,
                         fetch_range=lambda *a: [txn(0, 1, 10)])
            buf.process(txn(1, 2, 20))  # gap: expected prev 0, got 1
            assert len(delivered) == 2  # repair filled the hole
            gaps = recorder.events("interdc", "subbuf_gap")
            assert gaps and gaps[0][2]["expected"] == 0 \
                and gaps[0][2]["got"] == 1
            repairs = recorder.events("interdc", "subbuf_repair")
            assert repairs and repairs[0][2]["fetched"] == 1 \
                and repairs[0][2]["reachable"] is True
        finally:
            tracer.sample_rate = saved
            recorder.clear()


class TestCausalProbe:
    def test_probe_measures_staleness_cleanly(self, journey2):
        before = stats.registry.vis_probe_violations.value()
        _await(lambda: recorder.events("probe", "causal_probe"),
               what="a causal probe round")
        assert stats.registry.vis_probe_staleness.count >= 1
        assert stats.registry.vis_probe_violations.value() == before
        ev = recorder.events("probe", "causal_probe")[-1][2]
        assert ev["staleness_s"] >= 0.0
        assert {ev["dc"], ev["peer"]} == {"dc1", "dc2"}

    def test_probe_violation_alarms_and_dumps(self, journey2,
                                              tmp_path):
        """A reader that drops the probe element trips the violation
        path: counter bump + forced flight-recorder dump embedding the
        pipeline snapshot."""
        dc1, _dc2 = journey2

        class LyingReader:
            """Peer facade whose causal read omits the element."""

            def __init__(self, real):
                self.node = real.node
                self._real = real

            def read_objects_static(self, clock, objs):
                vals, vc = self._real.read_objects_static(clock, objs)
                return [set()], vc

        p = probe.CausalProbe(dc1, period_s=60.0)
        real_peer = p._peers()[0]
        lying = LyingReader(real_peer)
        p._peers = lambda: [lying]
        before = stats.registry.vis_probe_violations.value()
        n_dumps = len(recorder.dumps)
        assert p.run_once() == 1
        assert stats.registry.vis_probe_violations.value() == before + 1
        new = recorder.dumps[n_dumps:]
        assert any("causal_probe" in d for d in new), new
        body = json.load(open([d for d in new
                               if "causal_probe" in d][-1]))
        assert body["extra"]["writer_dc"] == "dc1"
        assert "pipeline" in body["extra"]

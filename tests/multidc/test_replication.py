"""Multi-DC replication tests — the multiple_dcs_SUITE /
inter_dc_repl_SUITE analogues (reference
test/multidc/multiple_dcs_SUITE.erl:80-86,
test/multidc/inter_dc_repl_SUITE.erl:79-84).
"""

import threading

import pytest

from antidote_tpu.clocks import VC, vc_max


def update_counter(dc, key, n=1, clock=None, bucket="bkt"):
    return dc.update_objects_static(
        clock, [((key, "counter_pn", bucket), "increment", n)])


def read_counter(dc, key, clock, bucket="bkt"):
    vals, _cvc = dc.read_objects_static(clock, [(key, "counter_pn", bucket)])
    return vals[0]


class TestSimpleReplication:
    """reference simple_replication_test
    (test/multidc/multiple_dcs_SUITE.erl:89-118)."""

    def test_counter_replicates_and_chains(self, cluster3):
        dc1, dc2, dc3 = cluster3
        key = "simple_replication_test"
        update_counter(dc1, key)
        update_counter(dc1, key)
        ct = update_counter(dc1, key)

        assert read_counter(dc1, key, ct) == 3
        assert read_counter(dc3, key, ct) == 3
        assert read_counter(dc2, key, ct) == 3

        ct2 = update_counter(dc2, key, clock=ct)
        ct3 = update_counter(dc3, key, clock=ct2)
        for dc in cluster3:
            assert read_counter(dc, key, ct3) == 5


class TestParallelWrites:
    """reference parallel_writes_test
    (test/multidc/multiple_dcs_SUITE.erl:120-150)."""

    def test_concurrent_writers_converge(self, cluster3):
        key = "parallel_writes_test"
        times = [None] * 3

        def writer(i, dc):
            ct = None
            for _ in range(5):
                ct = update_counter(dc, key, clock=ct)
            times[i] = ct

        threads = [threading.Thread(target=writer, args=(i, dc))
                   for i, dc in enumerate(cluster3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        merged = vc_max(times)
        for dc in cluster3:
            assert read_counter(dc, key, merged) == 15


class TestCausality:
    """reference inter_dc_repl_SUITE causality + atomicity."""

    def test_read_your_cross_dc_writes(self, cluster3):
        dc1, dc2, _ = cluster3
        ct1 = update_counter(dc1, "causal_key")
        # a client carrying ct1 to dc2 must see the write
        assert read_counter(dc2, "causal_key", ct1) == 1
        # and a write at dc2 causally after it is ordered behind it at dc1
        ct2 = update_counter(dc2, "causal_key", clock=ct1)
        assert read_counter(dc1, "causal_key", ct2) == 2

    def test_atomic_multikey_replication(self, cluster3):
        """A multi-partition txn's effects become visible together at a
        remote DC (commit VC gates all of them)."""
        dc1, dc2, _ = cluster3
        tx = dc1.start_transaction()
        dc1.update_objects(
            [((f"atomic_k{i}", "counter_pn", "b"), "increment", 1)
             for i in range(8)], tx)  # spreads over all 4 partitions
        ct = dc1.commit_transaction(tx)

        vals, _ = dc2.read_objects_static(
            ct, [(f"atomic_k{i}", "counter_pn", "b") for i in range(8)])
        assert vals == [1] * 8


class TestReplicatedSet:
    """reference replicated_set_test
    (test/multidc/multiple_dcs_SUITE.erl:247-280)."""

    def test_orset_add_remove_across_dcs(self, cluster3):
        dc1, dc2, dc3 = cluster3
        key = ("replicated_set", "set_aw", "b")
        ct = None
        for i in range(10):
            ct = dc1.update_objects_static(ct, [(key, "add", f"e{i}")])
        vals, _ = dc2.read_objects_static(ct, [key])
        assert sorted(vals[0]) == sorted(f"e{i}" for i in range(10))

        ct2 = dc2.update_objects_static(ct, [(key, "remove", "e5")])
        vals, _ = dc3.read_objects_static(ct2, [key])
        assert "e5" not in vals[0] and len(vals[0]) == 9


class TestBlocking:
    """reference blocking_test (test/multidc/multiple_dcs_SUITE.erl:205-243):
    a DC whose inbound heartbeats are dropped cannot serve snapshots that
    depend on the stalled origins until pings resume."""

    def test_stalled_gst_blocks_then_recovers(self, cluster3):
        dc1, dc2, dc3 = cluster3
        dc3.drop_ping = True
        key = "blocking_test"
        # updates at a partition dc3 hears nothing about (no heartbeats,
        # and ONLY txn frames for the touched partition)
        ct1 = update_counter(dc1, key)
        ct2 = update_counter(dc2, key, clock=ct1)
        merged = vc_max([ct1, ct2])
        assert read_counter(dc1, key, merged) == 2
        assert read_counter(dc2, key, merged) == 2

        # at dc3 the other partitions' dc1/dc2 entries are stuck at the
        # last pre-drop heartbeat, so the GST cannot cover `merged`
        probe = VC(merged)
        with pytest.raises(TimeoutError):
            dc3.node.config.clock_wait_timeout_s = 0.4
            read_counter(dc3, key, probe)
        dc3.node.config.clock_wait_timeout_s = 10.0

        dc3.drop_ping = False
        assert read_counter(dc3, key, merged) == 2


class TestGapRepair:
    """Message-loss repair via opid watermarks + log-range refetch
    (reference inter_dc_sub_buf, src/inter_dc_sub_buf.erl:98-158)."""

    def test_lost_frames_are_refetched(self, bus, cluster3):
        dc1, dc2, _ = cluster3
        key = 7  # integer key -> deterministic partition (7 % 4 = 3)
        ct = update_counter(dc1, key)
        assert read_counter(dc2, key, ct) == 1

        # drop all pub/sub frames inbound to dc2 while dc1 commits
        bus.set_drop_rx("dc2", True)
        for _ in range(5):
            ct = update_counter(dc1, key, clock=ct)
        bus.set_drop_rx("dc2", False)

        # next frame (heartbeat or txn) reveals the gap; the sub_buf
        # fetches the missing range over the query channel
        ct = update_counter(dc1, key, clock=ct)
        assert read_counter(dc2, key, ct) == 7

    def test_repair_waits_out_partition(self, bus, cluster3):
        dc1, dc2, _ = cluster3
        key = 11
        ct = update_counter(dc1, key)
        assert read_counter(dc2, key, ct) == 1

        # full partition: pub/sub AND query channel down
        bus.set_link("dc1", "dc2", up=False)
        for _ in range(3):
            ct = update_counter(dc1, key, clock=ct)
        # dc2 can't see them and can't repair (link down)
        dc2.node.config.clock_wait_timeout_s = 0.4
        with pytest.raises(TimeoutError):
            read_counter(dc2, key, ct)
        dc2.node.config.clock_wait_timeout_s = 10.0

        # heal; repair completes on the next inbound frame
        bus.set_link("dc1", "dc2", up=True)
        ct = update_counter(dc1, key, clock=ct)
        assert read_counter(dc2, key, ct) == 5


class TestReplicatedNewTypes:
    """Cross-DC semantics of the types that joined the device plane in
    this round: remove-wins conflict resolution, disable-wins flags,
    and recursive-reset maps, through the full replication stack."""

    def test_rwset_concurrent_add_remove_remove_wins(self, cluster3):
        dc1, dc2, dc3 = cluster3
        key = ("rw_conflict", "set_rw", "b")
        ct = dc1.update_objects_static(None, [(key, "add", "x")])
        # make both DCs observe the same baseline, then write
        # concurrently: dc1 re-adds (observing nothing new), dc2
        # removes — remove must win at every replica
        vals, _ = dc2.read_objects_static(ct, [key])
        assert vals[0] == ["x"]
        ct1 = dc1.update_objects_static(ct, [(key, "add", "x")])
        ct2 = dc2.update_objects_static(ct, [(key, "remove", "x")])
        merged = vc_max([ct1, ct2])
        for dc in cluster3:
            vals, _ = dc.read_objects_static(merged, [key])
            assert vals[0] == [], f"{dc.dc_id}: {vals[0]}"
        # a remove-observing re-add resurrects everywhere
        ct3 = dc3.update_objects_static(merged, [(key, "add", "x")])
        for dc in cluster3:
            vals, _ = dc.read_objects_static(ct3, [key])
            assert vals[0] == ["x"]

    def test_flag_dw_concurrent_enable_disable(self, cluster3):
        dc1, dc2, _ = cluster3
        key = ("dw_conflict", "flag_dw", "b")
        ct = dc1.update_objects_static(None, [(key, "enable", ())])
        dc2.read_objects_static(ct, [key])
        ct1 = dc1.update_objects_static(ct, [(key, "enable", ())])
        ct2 = dc2.update_objects_static(ct, [(key, "disable", ())])
        merged = vc_max([ct1, ct2])
        for dc in cluster3:
            vals, _ = dc.read_objects_static(merged, [key])
            assert vals[0] is False, dc.dc_id  # disable wins

    def test_map_rr_replicates_and_removes(self, cluster3):
        dc1, dc2, _ = cluster3
        key = ("rr_map", "map_rr", "b")
        ct = dc1.update_objects_static(None, [
            (key, "update", [(("tags", "set_aw"), ("add_all", ["a", "b"])),
                             (("on", "flag_ew"), ("enable", ()))])])
        vals, _ = dc2.read_objects_static(ct, [key])
        assert vals[0] == {("tags", "set_aw"): ["a", "b"],
                           ("on", "flag_ew"): True}
        ct2 = dc2.update_objects_static(ct, [
            (key, "remove", ("tags", "set_aw"))])
        for dc in cluster3:
            vals, _ = dc.read_objects_static(ct2, [key])
            assert vals[0] == {("on", "flag_ew"): True}, dc.dc_id

    def test_set_go_replicates(self, cluster3):
        dc1, dc2, _ = cluster3
        key = ("go_set", "set_go", "b")
        ct = dc1.update_objects_static(None, [(key, "add_all", ["p", "q"])])
        ct2 = dc2.update_objects_static(ct, [(key, "add", "r")])
        vals, _ = dc1.read_objects_static(ct2, [key])
        assert vals[0] == ["p", "q", "r"]


class TestExactDownstreamState:
    """Downstream effects must be generated from EXACT CRDT state (full
    per-DC dot sets), never from the device fold's per-(elem, plane, DC)
    max-seq collapse.

    set_rw / flag_dw accumulate multiple live dots per DC column (their
    host update does ``adds | {dot}`` with no self-supersede), so an
    effect generated from a collapsed state observes only the newest dot
    and under-cancels at any exact replica — permanent cross-DC value
    divergence (round-2 advisor finding, mat/device_plane.py RwsetPlane).
    Each test forces a cold value cache between ops so the downstream
    read cannot ride a warm exact state, then compares the device-served
    origin against a host-exact replica (key evicted to the host store,
    which rebuilds from a full log replay)."""

    @staticmethod
    def _chill(dc):
        """Drop every warm value-cache entry (restart / retirement / cache
        -pressure stand-in)."""
        for pm in dc.node.partitions:
            with pm._lock:
                pm._val_cache.clear()

    @staticmethod
    def _host_serve(dc, key, type_name):
        """Force the key onto the host path at this DC: the migration
        replays the full log, so the host state is exact by construction."""
        pm = dc.node.partition_of(key)
        with pm._lock:
            if pm.device is not None and pm.device.owns(type_name, key):
                pm._wait_device_quiesce()
                pm.device.planes[type_name].evict(key)

    def test_set_rw_remove_remove_add_converges(self, cluster3):
        dc1, dc2, _ = cluster3
        bo = ("exact_rw", "set_rw", "b")
        ct = dc1.update_objects_static(None, [(bo, "remove", "x")])
        self._chill(dc1)
        ct = dc1.update_objects_static(ct, [(bo, "remove", "x")])
        self._chill(dc1)
        # the add must observe BOTH remove dots; a collapsed read lists
        # only the newest, leaving the older one live at exact replicas
        ct = dc1.update_objects_static(ct, [(bo, "add", "x")])
        self._host_serve(dc2, "exact_rw", "set_rw")
        v1, _ = dc1.read_objects_static(ct, [bo])
        v2, _ = dc2.read_objects_static(ct, [bo])
        assert v1[0] == v2[0] == ["x"]

    def test_set_rw_reset_converges(self, cluster3):
        dc1, dc2, _ = cluster3
        bo = ("exact_rw_reset", "set_rw", "b")
        ct = dc1.update_objects_static(None, [(bo, "add", "x")])
        self._chill(dc1)
        ct = dc1.update_objects_static(ct, [(bo, "add", "x")])
        self._chill(dc1)
        ct = dc1.update_objects_static(ct, [(bo, "reset", ())])
        self._host_serve(dc2, "exact_rw_reset", "set_rw")
        v1, _ = dc1.read_objects_static(ct, [bo])
        v2, _ = dc2.read_objects_static(ct, [bo])
        assert v1[0] == v2[0] == []

    def test_flag_dw_disable_disable_enable_converges(self, cluster3):
        dc1, dc2, _ = cluster3
        bo = ("exact_dw", "flag_dw", "b")
        ct = dc1.update_objects_static(None, [(bo, "disable", ())])
        self._chill(dc1)
        ct = dc1.update_objects_static(ct, [(bo, "disable", ())])
        self._chill(dc1)
        ct = dc1.update_objects_static(ct, [(bo, "enable", ())])
        self._host_serve(dc2, "exact_dw", "flag_dw")
        v1, _ = dc1.read_objects_static(ct, [bo])
        v2, _ = dc2.read_objects_static(ct, [bo])
        assert v1[0] is True and v2[0] is True

    def test_map_nested_set_rw_converges(self, cluster3):
        dc1, dc2, _ = cluster3
        bo = ("exact_map", "map_rr", "b")
        fld = ("s", "set_rw")
        ct = dc1.update_objects_static(
            None, [(bo, "update", (fld, ("remove", "x")))])
        self._chill(dc1)
        ct = dc1.update_objects_static(
            ct, [(bo, "update", (fld, ("remove", "x")))])
        self._chill(dc1)
        ct = dc1.update_objects_static(
            ct, [(bo, "update", (fld, ("add", "x")))])
        self._host_serve(dc2, "exact_map", "map_rr")
        v1, _ = dc1.read_objects_static(ct, [bo])
        v2, _ = dc2.read_objects_static(ct, [bo])
        assert v1[0] == v2[0] == {fld: ["x"]}


class TestReplicatedRGA:
    """Live device-served RGA across DCs (round-3: rga joined the device
    plane; reference serves every type through one materializer path,
    src/materializer_vnode.erl:56-110)."""

    def test_collaborative_edits_replicate(self, cluster3):
        dc1, dc2, dc3 = cluster3
        key = ("doc", "rga", "b")
        ct = dc1.update_objects_static(
            None, [(key, "add_right", (0, "h"))])
        ct = dc1.update_objects_static(ct, [(key, "add_right", (1, "i"))])
        # dc2 extends causally after seeing dc1's edits
        ct = dc2.update_objects_static(ct, [(key, "add_right", (2, "!"))])
        for dc in cluster3:
            vals, _ = dc.read_objects_static(ct, [key])
            assert vals[0] == ["h", "i", "!"], dc.dc_id

    def test_remove_tombstones_replicate(self, cluster3):
        dc1, dc2, _ = cluster3
        key = ("doc_rm", "rga", "b")
        ct = None
        for i, ch in enumerate("abcd"):
            ct = dc1.update_objects_static(
                ct, [(key, "add_right", (i, ch))])
        ct = dc2.update_objects_static(ct, [(key, "remove", 2)])
        for dc in cluster3:
            vals, _ = dc.read_objects_static(ct, [key])
            assert vals[0] == ["a", "c", "d"], dc.dc_id
        # a later insert anchored right of the tombstoned position
        ct = dc1.update_objects_static(ct, [(key, "add_right", (1, "X"))])
        vals, _ = dc2.read_objects_static(ct, [key])
        assert vals[0] == ["a", "X", "c", "d"]

    def test_concurrent_inserts_converge(self, cluster3):
        dc1, dc2, dc3 = cluster3
        key = ("doc_cc", "rga", "b")
        base = dc1.update_objects_static(
            None, [(key, "add_right", (0, "s"))])
        # both DCs insert at the head concurrently (same causal base)
        ct1 = dc1.update_objects_static(base, [(key, "add_right", (0, "1"))])
        ct2 = dc2.update_objects_static(base, [(key, "add_right", (0, "2"))])
        merged = vc_max([ct1, ct2])
        views = []
        for dc in cluster3:
            vals, _ = dc.read_objects_static(merged, [key])
            views.append(vals[0])
        assert views[0] == views[1] == views[2]
        assert sorted(views[0]) == ["1", "2", "s"]

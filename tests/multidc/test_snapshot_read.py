"""Inter-DC remote snapshot reads (ISSUE 8): the SNAPSHOT_READ query
kind serves a causal one-shot read at a clock through the remote DC's
read serve plane — the value-question counterpart of the log-range
repair read."""

import pytest

from antidote_tpu.interdc import query as idc_query

from .conftest import make_cluster


@pytest.fixture
def cluster2(bus, tmp_path):
    dcs = make_cluster(bus, tmp_path, 2)
    yield dcs
    for dc in dcs:
        dc.close()


def _settle(dcs, ct, key):
    """Pump replication until dc2 causally serves the write."""
    vals, _ = dcs[1].read_objects_static(ct, [key])
    return vals


def test_remote_snapshot_read_at_clock(cluster2, bus):
    dc1, dc2 = cluster2
    key = ("rk", "counter_pn", "b")
    ct = dc1.update_objects_static(None, [(key, "increment", 41)])
    # replication has landed once a local causal read serves it
    assert _settle(cluster2, ct, key) == [41]
    # now ask dc2 for the value OVER THE QUERY CHANNEL, at the commit
    # clock — answered through dc2's read serve plane
    got = idc_query.fetch_snapshot_read(
        bus, dc1.node.dc_id, dc2.node.dc_id, [key], ct)
    assert got is not None
    values, vc = got
    assert values == [41]
    assert vc.ge(ct)


def test_remote_snapshot_read_clockless_and_unreachable(cluster2, bus):
    dc1, dc2 = cluster2
    key = ("rk2", "counter_pn", "b")
    ct = dc1.update_objects_static(None, [(key, "increment", 7)])
    assert _settle(cluster2, ct, key) == [7]
    got = idc_query.fetch_snapshot_read(
        bus, dc2.node.dc_id, dc1.node.dc_id, [key], None)
    assert got is not None
    values, _vc = got
    assert values == [7]
    # an unknown origin is unreachable, not an exception
    assert idc_query.fetch_snapshot_read(
        bus, dc1.node.dc_id, "no_such_dc", [key], None) is None

"""Ring placement composed with the OTHER planes: inter-DC
replication and GentleRain must work unchanged when the data plane
(and the stable fold) live on the device mesh — the round-5
device-collective GST serves the same contract the host fold did."""

import pytest

from antidote_tpu.clocks import VC
from antidote_tpu.config import Config
from antidote_tpu.interdc.dc import DataCenter, connect_dcs
from antidote_tpu.interdc.transport import InProcBus
from antidote_tpu.meta.device_stable import DeviceStableTimeTracker


def _cfg(tmp_path, name):
    return Config(n_partitions=8, data_dir=str(tmp_path / name),
                  heartbeat_s=0.05, device_placement="ring",
                  device_flush_ops=8)


def test_federated_ring_placed_dcs_replicate(tmp_path):
    bus = InProcBus()
    a = DataCenter("dcA", bus, config=_cfg(tmp_path, "a"))
    b = DataCenter("dcB", bus, config=_cfg(tmp_path, "b"))
    try:
        assert isinstance(a.stable, DeviceStableTimeTracker)
        connect_dcs([a, b])
        a.start_bg_processes()
        b.start_bg_processes()

        ct = a.update_objects_static(None, [
            ((k, "counter_pn", "b"), "increment", k + 1)
            for k in range(16)])
        # B serves A's writes at the causal clock — the dependency
        # gate + device GST must let the snapshot advance
        vals, _ = b.read_objects_static(
            ct, [(k, "counter_pn", "b") for k in range(16)])
        assert vals == [k + 1 for k in range(16)]

        # and the device/host stable folds agree on BOTH members
        for dc in (a, b):
            dev, host = dc.stable.snapshot_pair()
            assert dict(dev.items()) == dict(host.items())
    finally:
        a.close()
        b.close()


def test_gentlerain_on_ring_placed_node(tmp_path):
    """txn_prot='gr' reads the scalar GST through the collective
    tracker (get_scalar_stable_time -> get_stable_snapshot)."""
    from antidote_tpu.api import AntidoteTPU

    cfg = _cfg(tmp_path, "gr")
    cfg.txn_prot = "gr"
    db = AntidoteTPU(config=cfg)
    try:
        assert isinstance(db.node.stable_tracker,
                          DeviceStableTimeTracker)
        tx = db.start_transaction()
        db.update_objects(
            [((k, "set_aw", "b"), "add", f"e{k}") for k in range(12)],
            tx)
        cvc = db.commit_transaction(tx)
        tx = db.start_transaction(clock=cvc)
        vals = db.read_objects(
            [(k, "set_aw", "b") for k in range(12)], tx)
        db.commit_transaction(tx)
        assert vals == [[f"e{k}"] for k in range(12)]
    finally:
        db.close()

"""Opt-in (``-m slow``) reproduction loop for the round-5 KNOWN ISSUE:
transient device-fold under-inclusion (CHANGES_r05.md) — a
device-served set_aw read missing ONE old element during a concurrent
same-key publish+flush burst, surfacing in the ring causal checker as
a session-monotonicity or causal-floor violation whose missing
element's commit VC is dominated by the session clock.

This lands the CHANGES_r05 shell-loop recipe (run the ring checker ~10
times and keep the dumps) as a single pytest node, and points the same
trace at BOTH device planes:

- ``ring``: the round-5 shape itself — per-partition single-chip
  planes, the configuration the ~1/10 flake was measured on;
- ``podshard``: the pod-scale materializer (ISSUE 20,
  ``mat_sharded=True``) — the fold horizon is the sharded store's
  collective ``gc_at`` and reads assemble cross-chip, so a hit here
  says the under-inclusion window survived the re-architecture, and a
  clean loop says the sharded fold path does not widen it.

Every iteration uses fresh data dirs (the interleaving is
thread-timing driven, not seeded — iteration count is the only
variable), and any violation auto-dumps the flight recorder plus the
full pipeline and fold-inclusion snapshot to
``flightrec_causal_checker_*.json`` (tests/causal_core.py forensics)
before the assert fires; the failure message names the iteration so
the hit rate is legible.

Run it::

    JAX_PLATFORMS=cpu python -m pytest \
      tests/multidc/test_causal_flake_loop.py -m slow -q -p no:randomly
"""

import pytest

import causal_core as cc
from antidote_tpu.config import Config
from antidote_tpu.interdc.dc import DataCenter, connect_dcs
from antidote_tpu.interdc.transport import InProcBus

#: ~1/10 per-run hit rate measured in round 5: a dozen runs give a
#: ~72% rehit chance per invocation while keeping the loop under the
#: soak-style budgets
ITERS = 12


def _variant_cfg(variant: str, tmp_path, name: str) -> Config:
    kw = {"device_placement": "ring", "device_flush_ops": 8} \
        if variant == "ring" else \
        {"mat_sharded": True, "device_flush_ops": 8}
    return Config(n_partitions=4, data_dir=str(tmp_path / name),
                  heartbeat_s=0.005, **kw)


@pytest.mark.slow
@pytest.mark.parametrize("variant", ["ring", "podshard"])
def test_device_fold_under_inclusion_loop(tmp_path, variant):
    for i in range(ITERS):
        bus = InProcBus()
        a = DataCenter("dcA", bus, config=_variant_cfg(
            variant, tmp_path, f"a{i}"))
        b = DataCenter("dcB", bus, config=_variant_cfg(
            variant, tmp_path, f"b{i}"))
        try:
            connect_dcs([a, b])
            a.start_bg_processes()
            b.start_bg_processes()
            try:
                # a violation dumps forensics itself (causal_core
                # forensics()) before raising — whether it fires in a
                # reader thread inside run_trace or in the final
                # validate pass, we only annotate the iteration so the
                # observed hit rate is in the report
                writes, reads, _abandoned = cc.run_trace([a, b], [a, b])
                assert len(writes) >= 2 * cc.N_WRITES
                cc.validate(writes, reads)
            except AssertionError as e:
                raise AssertionError(
                    f"[{variant}] causal violation on loop iteration "
                    f"{i + 1}/{ITERS} — forensics dump path is in the "
                    f"original message below\n{e}") from None
        finally:
            a.close()
            b.close()

"""Bounded-counter manager tests — the bcountermgr_SUITE analogue
(reference test/multidc/bcountermgr_SUITE.erl): decrements bounded by
local rights, no_permissions abort, and cross-DC permission transfer via
the periodic transfer pass.
"""

import time

import pytest

from antidote_tpu.api import TransactionAborted


BOUND = ("bc_key", "counter_b", "bkt")


def incr(dc, n, clock=None, bound=BOUND):
    return dc.update_objects_static(clock, [(bound, "increment", n)])


def decr(dc, n, clock=None, bound=BOUND):
    return dc.update_objects_static(clock, [(bound, "decrement", n)])


def value(dc, clock, bound=BOUND):
    vals, _ = dc.read_objects_static(clock, [bound])
    return vals[0]


def wait_value(dc, clock, want, bound=BOUND, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if value(dc, clock, bound) == want:
            return
        time.sleep(0.01)
    assert value(dc, clock, bound) == want


class TestLocalBounds:
    """reference new_bcounter_test / test_dec_success / test_dec_fail
    (test/multidc/bcountermgr_SUITE.erl:84-131)."""

    def test_new_counter_is_zero(self, cluster3):
        dc1 = cluster3[0]
        assert value(dc1, None, ("fresh_bc", "counter_b", "bkt")) == 0

    def test_decrement_within_rights_succeeds(self, cluster3):
        dc1 = cluster3[0]
        bound = ("bc_dec_ok", "counter_b", "bkt")
        ct = incr(dc1, 10, bound=bound)
        ct = decr(dc1, 4, clock=ct, bound=bound)
        assert value(dc1, ct, bound) == 6

    def test_decrement_beyond_rights_aborts(self, cluster3):
        dc1 = cluster3[0]
        bound = ("bc_dec_fail", "counter_b", "bkt")
        ct = incr(dc1, 3, bound=bound)
        with pytest.raises(TransactionAborted, match="no_permissions"):
            decr(dc1, 5, clock=ct, bound=bound)
        assert value(dc1, ct, bound) == 3

    def test_conditional_write_skew_prevented(self, cluster3):
        """Two DCs can never jointly overdraw: each decrement is checked
        against that DC's own rights (reference
        conditional_write_test_run, bcountermgr_SUITE)."""
        dc1, dc2, _ = cluster3
        bound = ("bc_skew", "counter_b", "bkt")
        ct = incr(dc1, 5, bound=bound)
        wait_value(dc2, ct, 5, bound)
        # dc2 holds no rights — all 5 were minted by dc1
        with pytest.raises(TransactionAborted, match="no_permissions"):
            decr(dc2, 5, clock=ct, bound=bound)
        ct = decr(dc1, 5, clock=ct, bound=bound)
        for dc in cluster3:
            wait_value(dc, ct, 0, bound)


class TestPermissionTransfer:
    """reference transfer_test (test/multidc/bcountermgr_SUITE.erl:133-160):
    a failed decrement at a poor DC triggers a rights transfer from the
    richest DC; the retried decrement then succeeds."""

    def test_failed_decrement_triggers_transfer(self, cluster3):
        dc1, dc2, _ = cluster3
        bound = ("bc_transfer", "counter_b", "bkt")
        ct = incr(dc1, 10, bound=bound)
        wait_value(dc2, ct, 10, bound)

        # dc2 has no rights yet: the decrement aborts but queues a request
        with pytest.raises(TransactionAborted, match="no_permissions"):
            decr(dc2, 6, clock=ct, bound=bound)

        # retry until the transfer lands (background tickers run the
        # transfer pass and replicate the grant), as the reference client
        # does (bcountermgr_SUITE decrement retry loop)
        deadline = time.monotonic() + 10.0
        ct2 = None
        while ct2 is None:
            try:
                ct2 = decr(dc2, 6, clock=ct, bound=bound)
            except TransactionAborted:
                assert time.monotonic() < deadline, \
                    "transfer never arrived at dc2"
                time.sleep(0.05)
        for dc in cluster3:
            wait_value(dc, ct2, 4, bound)

    def test_malformed_op_aborts_cleanly(self, cluster3):
        """Bad args abort as TransactionAborted (not a raw unpack error)
        and must NOT queue a transfer request."""
        dc1 = cluster3[0]
        mgr = dc1.node.bcounter_mgr
        bound = ("bc_malformed", "counter_b", "bkt")
        with pytest.raises(TransactionAborted):
            dc1.update_objects_static(None, [(bound, "decrement", "abc")])
        with pytest.raises(TransactionAborted):
            dc1.update_objects_static(None, [(bound, "decrement", 0)])
        assert ("bc_malformed", "bkt") not in mgr._requests

    def test_grace_period_suppresses_repeat_grants(self, cluster3):
        dc1, dc2, _ = cluster3
        mgr = dc1.node.bcounter_mgr
        bound_key = ("bc_grace", "bkt")
        incr(dc1, 8, bound=("bc_grace", "counter_b", "bkt"))
        assert mgr.handle_remote_request(
            "dc2", ("bc_grace", "bkt", 2, "dc2")) is True
        # immediate repeat inside the grace period is refused
        assert mgr.handle_remote_request(
            "dc2", ("bc_grace", "bkt", 2, "dc2")) is False
        # a different requester is unaffected
        assert mgr.handle_remote_request(
            "dc3", ("bc_grace", "bkt", 2, "dc3")) is True


class TestBcounterMetrics:
    """ISSUE 17 satellite: the rights-transfer economy is observable —
    BCOUNTER_* families move with denials, grants, grace suppression
    and transfer requests (deltas against the process-global registry,
    which carries every prior test's history)."""

    def test_denial_bumps_counter_and_rights_gauge(self, cluster3):
        from antidote_tpu import stats

        dc1 = cluster3[0]
        bound = ("bc_met_deny", "counter_b", "bkt")
        ct = incr(dc1, 3, bound=bound)
        before = stats.registry.bcounter_denials.value()
        with pytest.raises(TransactionAborted, match="no_permissions"):
            decr(dc1, 5, clock=ct, bound=bound)
        assert stats.registry.bcounter_denials.value() == before + 1
        # the denial path refreshed the last-observed rights gauge
        held = stats.registry.bcounter_rights_held.value(dc="dc1")
        assert held is not None and held >= 0.0

    def test_successful_decrement_updates_rights_gauge(self, cluster3):
        from antidote_tpu import stats

        dc1 = cluster3[0]
        bound = ("bc_met_ok", "counter_b", "bkt")
        ct = incr(dc1, 10, bound=bound)
        decr(dc1, 4, clock=ct, bound=bound)
        # after spending 4 of 10 freshly-minted rights the gauge
        # reflects the remainder of the LAST counter touched
        assert stats.registry.bcounter_rights_held.value(dc="dc1") \
            == 6.0

    def test_grant_and_grace_counters(self, cluster3):
        from antidote_tpu import stats

        dc1 = cluster3[0]
        mgr = dc1.node.bcounter_mgr
        reg = stats.registry
        incr(dc1, 8, bound=("bc_met_grace", "counter_b", "bkt"))
        granted0 = reg.bcounter_transfers_granted.value(peer="dc2")
        suppressed0 = reg.bcounter_grace_suppressed.value()
        assert mgr.handle_remote_request(
            "dc2", ("bc_met_grace", "bkt", 2, "dc2")) is True
        assert reg.bcounter_transfers_granted.value(peer="dc2") \
            == granted0 + 1
        assert reg.bcounter_grace_suppressed.value() == suppressed0
        # the grace-period refusal is counted as suppression, not
        # as another grant
        assert mgr.handle_remote_request(
            "dc2", ("bc_met_grace", "bkt", 2, "dc2")) is False
        assert reg.bcounter_transfers_granted.value(peer="dc2") \
            == granted0 + 1
        assert reg.bcounter_grace_suppressed.value() == suppressed0 + 1

    def test_transfer_request_counted_at_the_asker(self, cluster3):
        from antidote_tpu import stats

        dc1, dc2, dc3 = cluster3
        reg = stats.registry
        bound = ("bc_met_req", "counter_b", "bkt")
        before = sum(
            reg.bcounter_transfer_requests.value(peer=p)
            for p in ("dc1", "dc2", "dc3"))
        ct = incr(dc1, 10, bound=bound)
        wait_value(dc2, ct, 10, bound)
        with pytest.raises(TransactionAborted, match="no_permissions"):
            decr(dc2, 6, clock=ct, bound=bound)
        # the queued request goes out on the next transfer pass
        deadline = time.monotonic() + 10.0
        while sum(reg.bcounter_transfer_requests.value(peer=p)
                  for p in ("dc1", "dc2", "dc3")) == before:
            assert time.monotonic() < deadline, \
                "no transfer request was ever counted"
            time.sleep(0.05)


class TestCheckpointSeededRecovery:
    """ISSUE 13 satellite: bounded-counter PERMISSION state must
    survive a checkpoint-seeded restart — rights live in the
    counter_b CRDT state, and a recovery that lost the below-cut
    history would grant from (or refuse on) a phantom rights table.
    The leg is seed → restart → cross-DC transfer succeeds."""

    def test_transfer_succeeds_after_seeded_restart(self, tmp_path):
        import pytest as _pytest

        from antidote_tpu.config import Config
        from antidote_tpu.interdc.dc import DataCenter, connect_dcs
        from antidote_tpu.interdc.transport import InProcBus

        bus = InProcBus()
        kw = dict(n_partitions=2, device_store=False, ckpt=True,
                  ckpt_truncate=True, ckpt_retain_ops=0,
                  heartbeat_s=0.02, clock_wait_timeout_s=10.0)
        dcs = [DataCenter(f"dc{i + 1}", bus, config=Config(**kw),
                          data_dir=str(tmp_path / f"dc{i + 1}"))
               for i in range(2)]
        connect_dcs(dcs)
        for dc in dcs:
            dc.start_bg_processes()
        try:
            dc1, dc2 = dcs
            bound = ("bc_seeded", "counter_b", "bkt")
            ct = incr(dc1, 10, bound=bound)  # rights minted at dc1
            wait_value(dc2, ct, 10, bound)
            # cut + truncate: the rights history now lives ONLY in
            # dc1's checkpoint seeds
            for pm in dc1.node.partitions:
                assert pm.checkpoint_now() is not None
            assert any(pm.log.log.truncated_base > 0
                       for pm in dc1.node.partitions), \
                "the increment history was not truncated"
            dcs[0].close()
            dc1b = DataCenter("dc1", bus, config=Config(**kw),
                              data_dir=str(tmp_path / "dc1"))
            dcs[0] = dc1b
            dc1b.start_bg_processes()
            # the restarted holder still sees its rights
            assert value(dc1b, ct, bound) == 10

            # dc2 has no local rights: the decrement aborts, queues a
            # transfer request, and the RESTARTED dc1 must grant from
            # its seeded permission state
            with _pytest.raises(TransactionAborted,
                                match="no_permissions"):
                decr(dc2, 6, clock=ct, bound=bound)
            deadline = time.monotonic() + 10.0
            ct2 = None
            while ct2 is None:
                try:
                    ct2 = decr(dc2, 6, clock=ct, bound=bound)
                except TransactionAborted:
                    assert time.monotonic() < deadline, \
                        "transfer never arrived from the restarted dc1"
                    time.sleep(0.05)
            for dc in dcs:
                wait_value(dc, ct2, 4, bound)
        finally:
            for dc in dcs:
                dc.close()

"""Multi-DC harness: simulated DCs on an in-process bus.

The reference's analogue boots ct_slave BEAM peers with real sockets on
one host (test/utils/test_utils.erl:110-165); here each "DC" is a
DataCenter instance sharing an InProcBus, with background delivery +
heartbeat threads running at a fast tick so causal waits resolve quickly.
"""

import pytest

from antidote_tpu.config import Config
from antidote_tpu.interdc import InProcBus
from antidote_tpu.interdc.dc import DataCenter, connect_dcs


@pytest.fixture
def bus():
    return InProcBus()


def make_cluster(bus, tmp_path, n_dcs=3, connect=True, **cfg_kw):
    cfg_kw.setdefault("n_partitions", 4)
    cfg_kw.setdefault("heartbeat_s", 0.02)
    cfg_kw.setdefault("clock_wait_timeout_s", 10.0)
    dcs = []
    for i in range(n_dcs):
        cfg = Config(**cfg_kw)
        dc = DataCenter(f"dc{i + 1}", bus, config=cfg,
                        data_dir=str(tmp_path / f"dc{i + 1}"))
        dcs.append(dc)
    if connect:
        connect_dcs(dcs)
    for dc in dcs:
        dc.start_bg_processes()
    return dcs


@pytest.fixture
def cluster3(bus, tmp_path):
    dcs = make_cluster(bus, tmp_path, 3)
    yield dcs
    for dc in dcs:
        dc.close()

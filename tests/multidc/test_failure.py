"""Node-failure and network-partition tests — the
multiple_dcs_node_failure_SUITE analogue (reference
test/multidc/multiple_dcs_node_failure_SUITE.erl:85-120: kill nodes,
restart, assert log-recovered state and continued replication) and the
cookie-partition helpers (reference test_utils partition_cluster /
heal_cluster, test/utils/test_utils.erl:239-256).
"""

import time

import pytest

from antidote_tpu.config import Config
from antidote_tpu.interdc.dc import DataCenter

from tests.multidc.conftest import make_cluster


def _upd(dc, key, n=1, clock=None):
    return dc.update_objects_static(
        clock, [((key, "counter_pn", "bkt"), "increment", n)])


def _read(dc, key, clock):
    vals, _ = dc.read_objects_static(clock, [(key, "counter_pn", "bkt")])
    return vals[0]


def _wait(dc, key, want, clock=None, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _read(dc, key, clock) == want:
            return
        time.sleep(0.01)
    assert _read(dc, key, clock) == want


def test_dc_restart_recovers_state_and_replication(bus, tmp_path):
    """Kill dc1, write at dc2 while it is down, restart dc1 from its
    data dir: recovered local state + gap-repaired remote stream
    (reference failure_test, multiple_dcs_node_failure_SUITE.erl:85-120)."""
    dcs = make_cluster(bus, tmp_path, 3)
    dc1, dc2, dc3 = dcs
    try:
        key = "fail_key"
        ct = _upd(dc1, key, 3)
        for dc in dcs:
            _wait(dc, key, 3, ct)

        # "kill -15" dc1
        dc1.close()

        # dc2 keeps committing while dc1 is down; these frames are lost
        # to dc1 (its subscription is gone)
        ct2 = _upd(dc2, key, 2, clock=None)

        # restart dc1 from the same data dir: meta re-joins known DCs,
        # logs replay, sender watermarks and dependency clocks reseed
        dc1b = DataCenter("dc1", bus, config=dc2.node.config.__class__(
            n_partitions=4, heartbeat_s=0.02, clock_wait_timeout_s=10.0),
            data_dir=str(tmp_path / "dc1"))
        dcs[0] = dc1b
        dc1b.start_bg_processes()

        # pre-kill state recovered from the durable log.  Not instant:
        # the op's dependency VC covers dc2, so it stays (correctly)
        # invisible until dc2's heartbeats re-advance dc1's stable
        # snapshot past it — hence a poll, like the reference's
        # wait_until assertions.
        deadline = time.monotonic() + 10.0
        while _read(dc1b, key, None) < 3:
            assert time.monotonic() < deadline, "recovered state invisible"
            time.sleep(0.01)

        # a fresh dc2 commit triggers the opid gap check at dc1, which
        # repairs the missed range via the log-read RPC
        ct3 = _upd(dc2, key, 1, clock=ct2)
        _wait(dc1b, key, 6, timeout=15.0)

        # and dc1's own new writes still replicate out
        ct4 = _upd(dc1b, key, 1, clock=None)
        for dc in (dc2, dc3):
            _wait(dc, key, 7, timeout=15.0)
    finally:
        for dc in dcs:
            dc.close()


def test_network_partition_and_heal(bus, tmp_path):
    """Cut the dc1<->dc2 link: updates stop flowing but both sides stay
    available; heal: convergence resumes (reference partition_cluster /
    heal_cluster, test/utils/test_utils.erl:239-256)."""
    dcs = make_cluster(bus, tmp_path, 2)
    dc1, dc2 = dcs
    try:
        key = "part_key"
        ct = _upd(dc1, key, 1)
        _wait(dc2, key, 1, ct)

        bus.set_link("dc1", "dc2", False)
        bus.set_link("dc2", "dc1", False)

        _upd(dc1, key, 1)
        # dc2 never observes the partitioned write (ungated read)
        time.sleep(0.2)
        assert _read(dc2, key, None) == 1
        # both sides remain available for local work
        _upd(dc2, "local_key", 5)

        bus.set_link("dc1", "dc2", True)
        bus.set_link("dc2", "dc1", True)

        # after heal, the next frames trigger gap repair and both sides
        # converge
        _upd(dc1, key, 1)
        _wait(dc2, key, 3, timeout=15.0)
        _wait(dc1, "local_key", 5, timeout=15.0)
    finally:
        for dc in dcs:
            dc.close()


def _wait_converged(dcs, merged, objs, types, timeout=30.0):
    """Poll until every replica reads identical values at ``merged``;
    clock-wait timeouts keep polling (a replica may still be
    gap-repairing), so only true divergence — reported per type —
    fails."""
    deadline = time.monotonic() + timeout
    while True:
        views = []
        for dc in dcs:
            try:
                vals, _ = dc.read_objects_static(merged, objs)
            except TimeoutError:
                views = None
                break
            views.append(vals)
        if views is not None and all(v == views[0] for v in views[1:]):
            return views
        assert time.monotonic() < deadline, (
            "replicas did not converge: "
            + ("a replica's clock wait kept timing out"
               if views is None else
               "; ".join(f"{t}: " + "/".join(repr(v[i]) for v in views)
                         for i, t in enumerate(types)
                         if any(v[i] != views[0][i] for v in views))))
        time.sleep(0.05)


@pytest.mark.parametrize("seed", [11, 23, 37])
def test_chaos_all_types_converge(bus, tmp_path, seed):
    """Randomized workload over (almost) every CRDT type across 3 DCs
    with a link flap, a lost-frames window (drop_rx), and a mid-stream
    DC restart: all replicas converge to identical values at the merged
    causal clock — dependency gating, gap repair, recovery, and every
    materializer path exercised at once.  (counter_b is excluded: its
    decrements legitimately abort on rights, covered by its own suite.)
    This harness found the cross-origin dependency-gate deadlock the
    blocked-head rule now fixes (interdc/dep.py)."""
    import random

    from antidote_tpu.clocks import vc_max

    rng = random.Random(seed)
    dcs = make_cluster(bus, tmp_path, 3)
    try:
        elems = ["a", "b", "c", "d"]

        def random_update(tname):
            if tname in ("counter_pn", "counter_fat"):
                return ("increment", rng.randint(1, 3))
            if tname in ("set_aw", "set_rw", "set_go"):
                if tname != "set_go" and rng.random() < 0.35:
                    return ("remove", rng.choice(elems))
                return ("add", rng.choice(elems))
            if tname in ("register_lww", "register_mv"):
                return ("assign", rng.choice(elems))
            if tname in ("flag_ew", "flag_dw"):
                return (rng.choice(["enable", "disable"]), ())
            if tname == "map_go":
                return ("update", ((("n", "counter_pn"),
                                    ("increment", 1))))
            if tname == "map_rr":
                if rng.random() < 0.25:
                    return ("remove", ("tags", "set_aw"))
                return ("update", ((("tags", "set_aw"),
                                    ("add", rng.choice(elems)))))
            if tname == "rga":
                return ("add_right", (0, rng.choice(elems)))
            raise AssertionError(tname)

        types = ["counter_pn", "counter_fat", "set_aw", "set_rw",
                 "set_go", "register_lww", "register_mv", "flag_ew",
                 "flag_dw", "map_go", "map_rr", "rga"]
        clocks = [None, None, None]

        def burst(n, causal=True):
            for _ in range(n):
                i = rng.randrange(3)
                tname = rng.choice(types)
                key = (f"chaos_{tname}", tname, "bkt")
                op = random_update(tname)
                clocks[i] = dcs[i].update_objects_static(
                    clocks[i] if causal else None, [(key, *op)])

        burst(40)
        # cut dc1<->dc2: both stay available, but a causal floor that
        # straddles the cut would (correctly) block Clock-SI until the
        # heal — so the partition-window writes carry no floor
        bus.set_link("dc1", "dc2", False)
        burst(20, causal=False)
        bus.set_link("dc1", "dc2", True)   # heal: gap repair refetches
        burst(20)
        # silently drop frames INBOUND to dc2 (lost messages without a
        # link cut: the senders see nothing; only opid gap repair can
        # recover the stream)
        bus.set_drop_rx("dc2", True)
        burst(15, causal=False)
        bus.set_drop_rx("dc2", False)
        burst(15)
        # hard restart dc3 from its data dir mid-workload
        dcs[2].close()
        dcs[2] = DataCenter(
            "dc3", bus,
            config=Config(n_partitions=4, heartbeat_s=0.02,
                          clock_wait_timeout_s=10.0),
            data_dir=str(tmp_path / "dc3"))
        dcs[2].start_bg_processes()
        clocks[2] = None
        burst(40)

        merged = vc_max([c for c in clocks if c is not None])
        objs = [(f"chaos_{t}", t, "bkt") for t in types]
        views = _wait_converged(dcs, merged, objs, types)
        # sanity: the workload actually produced state everywhere
        assert any(v not in (0, [], {}, False, None) for v in views[0])
    finally:
        for dc in dcs:
            dc.close()


def test_chaos_concurrent_writers_converge(bus, tmp_path):
    """Three writer THREADS (one per DC) run causal chains of mixed-type
    updates while the main thread injects a link flap and a lost-frames
    window; afterwards every replica converges at the merged clock.
    Exercises the locking seams the sequential chaos cannot: concurrent
    publish vs device flush/GC quiesce, warm-cache applies under the
    partition lock, and gate processing against live appenders."""
    import random
    import threading

    from antidote_tpu.clocks import vc_max

    dcs = make_cluster(bus, tmp_path, 3)
    try:
        types = ["counter_pn", "set_aw", "set_rw", "flag_dw", "map_rr",
                 "register_mv"]
        elems = ["a", "b", "c"]
        finals = [None, None, None]
        errs = []

        stop_writers = threading.Event()

        def writer(i):
            rng = random.Random(100 + i)
            dc = dcs[i]
            ct = None
            try:
                # run until the injector has finished its windows (a
                # fixed op count races the machine's speed: fast runs
                # finished before the drop window, failing the overlap
                # assertion vacuously)
                while not stop_writers.is_set():
                    t = rng.choice(types)
                    key = (f"cc_{t}", t, "bkt")
                    if t == "counter_pn":
                        op = ("increment", 1)
                    elif t in ("set_aw", "set_rw"):
                        op = (rng.choice(["add", "remove"]),
                              rng.choice(elems))
                    elif t == "flag_dw":
                        op = (rng.choice(["enable", "disable"]), ())
                    elif t == "map_rr":
                        op = ("update", ((("s", "set_aw"),
                                          ("add", rng.choice(elems)))))
                    else:
                        op = ("assign", rng.choice(elems))
                    try:
                        ct = dc.update_objects_static(ct, [(key, *op)])
                        # record every successful commit: the merged
                        # convergence clock must cover this DC's tail
                        # even if a LATER op times out
                        finals[i] = ct
                    except TimeoutError:
                        # a causal floor straddling an injected fault
                        # window blocks (correct Clock-SI); shed the
                        # floor and continue like a reconnecting client
                        ct = None
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append((i, e))

        threads = [threading.Thread(target=writer, args=(i,),
                                    daemon=True)  # a wedged writer must
                   for i in range(3)]             # not hang the process
        for t in threads:
            t.start()
        # fault injection against the live writers; assert the windows
        # actually overlapped live writes (otherwise the test passes
        # vacuously on a fast machine)
        time.sleep(0.3)
        assert any(t.is_alive() for t in threads), \
            "writers finished before fault injection began"
        bus.set_link("dc1", "dc2", False)
        time.sleep(0.4)
        bus.set_link("dc1", "dc2", True)
        time.sleep(0.2)
        bus.set_drop_rx("dc3", True)
        time.sleep(0.4)
        overlapped = any(t.is_alive() for t in threads)
        bus.set_drop_rx("dc3", False)
        stop_writers.set()
        assert overlapped, \
            "writers finished before the drop window ended"
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "writer wedged"
        assert not errs, errs

        merged = vc_max([c for c in finals if c is not None])
        objs = [(f"cc_{t}", t, "bkt") for t in types]
        _wait_converged(dcs, merged, objs, types)
    finally:
        for dc in dcs:
            dc.close()

"""Inter-DC gap repair through the per-origin op-id offset index
(ISSUE 9): the repaired range must be byte-identical to the legacy
full-scan answer, and repair cost must stop scaling with UNRELATED log
volume (other origins' records, other txns outside the range).
"""

import pytest

from antidote_tpu.clocks import VC
from antidote_tpu.interdc import query as idc_query
from antidote_tpu.interdc.sub_buf import SubBuf
from antidote_tpu.interdc.wire import InterDcTxn
from antidote_tpu.oplog.partition import PartitionLog
from antidote_tpu.oplog.records import (
    LogRecord,
    OpId,
    commit_record,
    update_record,
)


def build_log(tmp_path, name="gap", local_txns=30, remote_txns=30):
    """A partition log mixing local (dc1) committed txns with remote
    (dcR) replicated groups — the remote volume is the 'unrelated'
    growth a dc1 repair read must not pay for."""
    plog = PartitionLog(str(tmp_path / name), partition=0)
    t = 1000
    for i in range(local_txns):
        t += 10
        txid = ("dc1", 50_000 + i)
        plog.append_update("dc1", txid, f"k{i % 7}", "counter_pn", i)
        if i % 3 == 0:
            plog.append_update("dc1", txid, f"k{(i + 1) % 7}",
                               "counter_pn", -i)
        plog.append_commit("dc1", txid, t, VC({"dc1": t - 5}))
    n = 0
    for i in range(remote_txns):
        t += 10
        txid = ("dcR", 70_000 + i)
        n += 1
        recs = [LogRecord(OpId("dcR", n), txid,
                          ("update", f"rk{i % 5}", "counter_pn", i))]
        n += 1
        recs.append(LogRecord(
            OpId("dcR", n), txid,
            ("commit", ("dcR", t), VC({"dcR": t - 5}), True)))
        plog.append_remote_group(recs)
    return plog


def rec_bytes(records):
    return [r.to_bytes() for r in records]


def test_repaired_range_byte_identical_to_scan(tmp_path):
    plog = build_log(tmp_path)
    last = plog.op_counters["dc1"]
    for first, hi in [(1, last), (5, 17), (last, last), (1, 1),
                      (last + 1, last + 10)]:
        idx = plog.committed_txns_in_range("dc1", first, hi)
        scan = plog.committed_txns_in_range("dc1", first, hi, scan=True)
        assert [p for p, _r in idx] == [p for p, _r in scan]
        assert [rec_bytes(r) for _p, r in idx] == \
            [rec_bytes(r) for _p, r in scan]
    # the raw record range too (both origins)
    for dc in ("dc1", "dcR"):
        hi = plog.op_counters[dc]
        got = plog.records_in_range(dc, 3, hi - 2)
        oracle = plog._records_in_range_scan(dc, 3, hi - 2)
        assert rec_bytes(got) == rec_bytes(oracle)
    plog.close()


def test_answer_log_read_equals_legacy_answer(tmp_path):
    plog = build_log(tmp_path)
    last = plog.op_counters["dc1"]
    ans = idc_query.answer_log_read(plog, "dc1", 0, 4, last - 3)
    legacy = [InterDcTxn.from_ops("dc1", 0, prev, done)
              for prev, done in plog.committed_txns_in_range(
                  "dc1", 4, last - 3, scan=True)]
    assert len(ans) == len(legacy) > 0
    for a, b in zip(ans, legacy):
        assert (a.dc_id, a.partition, a.prev_log_opid,
                a.timestamp) == (b.dc_id, b.partition, b.prev_log_opid,
                                 b.timestamp)
        assert rec_bytes(a.records) == rec_bytes(b.records)
    plog.close()


def test_repair_cost_does_not_scale_with_unrelated_volume(tmp_path):
    """Fetching one txn's range reads O(its records), however much
    unrelated history the partition holds."""
    small = build_log(tmp_path, "small", local_txns=5, remote_txns=0)
    big = build_log(tmp_path, "big", local_txns=200, remote_txns=300)

    def count_reads(plog, first, last):
        n = 0
        orig = plog.log.read

        def counting(off):
            nonlocal n
            n += 1
            return orig(off)

        plog.log.read = counting
        try:
            got = plog.committed_txns_in_range("dc1", first, last)
        finally:
            plog.log.read = orig
        return n, got

    n_small, got_small = count_reads(small, 4, 6)
    n_big, got_big = count_reads(big, 4, 6)
    assert got_small and got_big
    # identical requested shape => identical read count, 60x the log
    assert n_big == n_small
    # and far below the full-scan record count
    assert n_big < 12
    small.close()
    big.close()


def test_recovery_rebuilds_the_index(tmp_path):
    plog = build_log(tmp_path, "reco")
    last = plog.op_counters["dc1"]
    want = [(p, rec_bytes(r))
            for p, r in plog.committed_txns_in_range("dc1", 2, last)]
    plog.close()
    re = PartitionLog(str(tmp_path / "reco"), partition=0)
    got = [(p, rec_bytes(r))
           for p, r in re.committed_txns_in_range("dc1", 2, last)]
    assert got == want
    # the rebuilt op index serves ranges too
    assert rec_bytes(re.records_in_range("dcR", 1, 4)) == \
        rec_bytes(re._records_in_range_scan("dcR", 1, 4))
    re.close()


def test_irregular_origin_falls_back_to_scan(tmp_path):
    """Out-of-order op ids from an origin poison its index; range
    reads must fall back to the scan, not serve a wrong answer."""
    plog = PartitionLog(str(tmp_path / "irr"), partition=0)
    # opids arrive 2,3 then 1 (a replay after repair): order broken
    plog.append_remote_group([
        LogRecord(OpId("dcX", 2), "t1", ("update", "k", "counter_pn", 1)),
        LogRecord(OpId("dcX", 3), "t1",
                  ("commit", ("dcX", 10), VC({"dcX": 9}), True)),
    ])
    plog.append_remote_group([
        LogRecord(OpId("dcX", 1), "t0", ("update", "k", "counter_pn", 9)),
    ])
    assert "dcX" in plog._index_irregular
    got = plog.records_in_range("dcX", 1, 3)
    oracle = plog._records_in_range_scan("dcX", 1, 3)
    assert rec_bytes(got) == rec_bytes(oracle)
    assert plog.committed_txns_in_range("dcX", 1, 3) == \
        plog.committed_txns_in_range("dcX", 1, 3, scan=True)
    plog.close()


def test_subbuf_gap_repairs_through_the_index(tmp_path):
    """End to end: drop frames from a live stream, let the SubBuf's
    repair fetch answer from the origin's log THROUGH the index, and
    assert delivery is byte-identical to the undropped stream."""
    plog = build_log(tmp_path, "live", local_txns=20, remote_txns=10)

    last = plog.op_counters["dc1"]
    full = idc_query.answer_log_read(plog, "dc1", 0, 1, last)
    assert len(full) == 20

    def run(drop_every):
        delivered = []
        fetches = []

        def fetch_range(origin, partition, first, hi):
            fetches.append((first, hi))
            return idc_query.answer_log_read(plog, "dc1", 0, first, hi)

        buf = SubBuf("dc1", 0, deliver=delivered.append,
                     fetch_range=fetch_range)
        for i, txn in enumerate(full):
            # never drop the final frame: a trailing loss has nothing
            # after it to trigger the repair (protocol-correct; the
            # next live frame or heartbeat would)
            if drop_every and i % drop_every == 1 and i < len(full) - 1:
                continue  # lost frame
            buf.process(txn)
        return delivered, fetches

    want, no_fetches = run(0)
    assert no_fetches == []
    got, fetches = run(3)
    assert fetches, "dropped frames must trigger repair fetches"
    assert len(got) == len(want)
    for a, b in zip(got, want):
        assert rec_bytes(a.records) == rec_bytes(b.records)
        assert a.timestamp == b.timestamp
    plog.close()

"""Causal-consistency checker: concurrent cross-DC traces validated
against the Clock-SI visibility rules (the test class that catches
clock/visibility races directly — the round-5 heartbeat/commit race
produced exactly a causal-floor violation of the kind checked here;
resurrecting that bug under a monkeypatch makes these tests fail).

Writers add UNIQUE elements to set_aw keys (each write's returned
commit VC identifies it exactly); readers snapshot-read concurrently
at varied causal clocks.  Post-hoc, every observed visibility set must
satisfy:

1. **Causal floor**: a read at client clock c sees every write w with
   commit_vc(w) <= c (the wait_for_clock promise,
   reference src/clocksi_interactive_coord.erl:915-926).
2. **Downward closure** (snapshot semantics): the visible set equals
   {w : commit_vc(w) <= s} for SOME snapshot s — so if w2 is visible
   and commit_vc(w1) <= commit_vc(w2), w1 must be visible (reference
   materializer snapshot rule, src/materializer.erl:101-106).
3. **Session monotonicity**: within one reader session (each read
   seeded with the previous read's returned clock), visibility never
   shrinks.

Rule definitions and the trace generator live in tests/causal_core.py
(shared with the federation-scale variant,
tests/cluster/test_causal_federation.py).

FLAKE NOTE (~1/10 heavy-concurrency runs on a 1-core box): the
round-5 KNOWN ISSUE — a device fold transiently losing an old op
during concurrent same-key publish+flush (CHANGES_r05.md) — fires
here as a session-monotonicity or causal-floor violation whose
missing element's commit VC IS dominated by the session clock.  Since
ISSUE 7 every checker failure dumps the flight recorder plus the full
pipeline snapshot (ship buffers, SubBuf gap state, gate backlogs,
ingest staging, stable watermarks) to
``flightrec_causal_checker_*.json`` under the recorder's dump dir
(default ``<tempdir>/antidote_obs/``) — attach that file when filing.

RERUN NOTE: the interleaving is thread-timing driven, NOT seeded —
there is no ``--seed`` that reproduces a failure deterministically.
To rehit it, loop the test on a loaded box and keep the dumps::

    for i in $(seq 20); do \
      JAX_PLATFORMS=cpu python -m pytest \
        tests/multidc/test_causal_checker.py -q -p no:randomly || break; \
    done

(``-p no:randomly`` pins pytest-level ordering so iteration count is
the only variable; the dump distinguishes the KNOWN ISSUE's signature
from a new regression.)
"""

import pytest

import causal_core as cc
from antidote_tpu.config import Config
from antidote_tpu.interdc.dc import DataCenter, connect_dcs
from antidote_tpu.interdc.transport import InProcBus


def _cfg(tmp_path, name, **kw):
    return Config(n_partitions=4, data_dir=str(tmp_path / name),
                  heartbeat_s=0.005, **kw)


@pytest.mark.parametrize("placement", ["none", "ring"])
def test_causal_visibility_two_dcs(tmp_path, placement):
    kw = {"device_placement": "ring", "device_flush_ops": 8} \
        if placement == "ring" else {}
    bus = InProcBus()
    a = DataCenter("dcA", bus, config=_cfg(tmp_path, "a", **kw))
    b = DataCenter("dcB", bus, config=_cfg(tmp_path, "b", **kw))
    try:
        connect_dcs([a, b])
        a.start_bg_processes()
        b.start_bg_processes()
        writes, reads, abandoned = cc.run_trace([a, b], [a, b])
        assert len(writes) >= 2 * cc.N_WRITES
        cc.validate(writes, reads)
    finally:
        a.close()
        b.close()


def test_causal_visibility_gentlerain(tmp_path):
    """Same trace under txn_prot='gr': snapshot semantics (downward
    closure) and session monotonicity must hold at the scalar-GST
    snapshot too (reference gr_snapshot_obtain, src/cure.erl:233-257).
    The entry-wise causal floor is Clock-SI's rule, not GentleRain's
    (GR waits only on the client's own-DC entry vs the GST)."""
    bus = InProcBus()
    ca = _cfg(tmp_path, "a")
    cb = _cfg(tmp_path, "b")
    ca.txn_prot = "gr"
    cb.txn_prot = "gr"
    a = DataCenter("dcA", bus, config=ca)
    b = DataCenter("dcB", bus, config=cb)
    try:
        connect_dcs([a, b])
        a.start_bg_processes()
        b.start_bg_processes()
        writes, reads, abandoned = cc.run_trace([a, b], [a, b])
        assert len(writes) >= 2 * cc.N_WRITES
        cc.validate(writes, reads, causal_floor=False)
    finally:
        a.close()
        b.close()

"""Causal-consistency checker: concurrent cross-DC traces validated
against the Clock-SI visibility rules (the test class that catches
clock/visibility races directly — the round-5 heartbeat/commit race
produced exactly a causal-floor violation of the kind checked here).

Writers add UNIQUE elements to set_aw keys (each write's returned
commit VC identifies it exactly); readers snapshot-read concurrently
at varied causal clocks.  Post-hoc, every observed visibility set must
satisfy:

1. **Causal floor**: a read at client clock c sees every write w with
   commit_vc(w) <= c (the wait_for_clock promise,
   reference src/clocksi_interactive_coord.erl:915-926).
2. **Downward closure** (snapshot semantics): the visible set equals
   {w : commit_vc(w) <= s} for SOME snapshot s — so if w2 is visible
   and commit_vc(w1) <= commit_vc(w2), w1 must be visible (reference
   materializer snapshot rule, src/materializer.erl:101-106).
3. **Session monotonicity**: within one reader session (each read
   seeded with the previous read's returned clock), visibility never
   shrinks.
"""

import threading
import time

import pytest

from antidote_tpu.clocks import VC
from antidote_tpu.txn.coordinator import TransactionAborted
from antidote_tpu.config import Config
from antidote_tpu.interdc.dc import DataCenter, connect_dcs
from antidote_tpu.interdc.transport import InProcBus

N_KEYS = 4
N_WRITES = 24  # per DC
N_READS = 30   # per reader session


def _cfg(tmp_path, name, **kw):
    return Config(n_partitions=4, data_dir=str(tmp_path / name),
                  heartbeat_s=0.005, **kw)


def _key(i):
    return (f"ck{i % N_KEYS}", "set_aw", "b")


def _run_trace(a, b):
    """Concurrent writers on both DCs + reader sessions on both;
    returns (writes {elem: commit_vc}, reads [(clock, vc, elems)])."""
    writes = {}
    w_lock = threading.Lock()
    reads = []
    r_lock = threading.Lock()
    errs = []

    def _commit_retry(dc, updates):
        # certification aborts are correct behavior under concurrent
        # same-key writers at lagging snapshots (GR's scalar GST);
        # clients retry exactly as the reference's clients do
        for _ in range(200):
            try:
                return dc.update_objects_static(None, updates)
            except TransactionAborted:
                # let the stable tick advance past the conflicting
                # commit before retrying (GR snapshots move with the
                # gossiped GST, not per-commit)
                time.sleep(0.005)
        raise AssertionError("writer starved by certification aborts")

    def writer(dc, tag):
        try:
            for i in range(N_WRITES):
                if i % 3 == 2:
                    # multi-partition txn: commit time = max(prepare
                    # times) — the shape whose heartbeat can carry the
                    # exact pending commit time (the round-5 race)
                    elems = [f"{tag}{i}k{k}".encode()
                             for k in range(N_KEYS)]
                    ct = _commit_retry(
                        dc, [(_key(k), "add", e)
                             for k, e in enumerate(elems)])
                    with w_lock:
                        for k, e in enumerate(elems):
                            writes[(e, k % N_KEYS)] = ct
                else:
                    elem = f"{tag}{i}".encode()
                    ct = _commit_retry(dc, [(_key(i), "add", elem)])
                    with w_lock:
                        writes[(elem, i % N_KEYS)] = ct
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    def reader(dc, follow):
        """One session: each read's clock = previous returned vc; every
        few reads jump to a fresh remote commit clock (the cross-DC
        causal handoff that exposed the heartbeat race)."""
        try:
            clock = None
            prev = {}  # key -> frozenset of last seen elems
            for i in range(N_READS):
                if i % 2 == 1:
                    with w_lock:
                        if writes:
                            newest = max(writes.values(),
                                         key=lambda v: sorted(v.items()))
                    clock = newest if writes else clock
                objs = [_key(k) for k in range(N_KEYS)]
                vals, vc = dc.read_objects_static(clock, objs)
                snap = {o: frozenset(v) for o, v in zip(objs, vals)}
                with r_lock:
                    reads.append((clock, vc, snap))
                for o, seen in snap.items():
                    if follow and not seen >= prev.get(o, frozenset()):
                        raise AssertionError(
                            f"session visibility shrank for {o}: "
                            f"{prev[o] - seen} disappeared")
                prev = snap
                clock = vc
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(a, "a")),
               threading.Thread(target=writer, args=(b, "b")),
               threading.Thread(target=reader, args=(a, True)),
               threading.Thread(target=reader, args=(b, True))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[0]
    return writes, reads


def _validate(writes, reads, causal_floor=True):
    """The post-hoc rules over every recorded read.  ``causal_floor``
    is the Clock-SI promise (wait_for_clock dominates the whole client
    clock); GentleRain waits only on the scalar GST, so its floor is
    not entry-wise — rules 2-3 still apply."""
    for clock, _vc, snap in reads:
        for key_i in range(N_KEYS):
            key = _key(key_i)
            visible = snap[key]
            owners = {e: v for (e, ki), v in writes.items()
                      if ki == key_i}
            # 1. causal floor: clock-dominated writes must be visible
            if causal_floor and clock is not None:
                for e, wvc in owners.items():
                    if wvc.le(clock):
                        assert e in visible, (
                            f"causal floor violated: write {e} with "
                            f"commit {dict(wvc.items())} <= read clock "
                            f"{dict(clock.items())} is missing")
            # 2. downward closure: visibility is a VC-order down-set
            # (a reader can glimpse an element a writer thread has not
            # recorded yet — its commit VC is unknown; skip those)
            for e2 in visible:
                v2 = owners.get(e2)
                if v2 is None:
                    continue
                for e1, v1 in owners.items():
                    if e1 not in visible and v1.le(v2):
                        raise AssertionError(
                            f"snapshot not downward closed: {e2} "
                            f"visible but earlier {e1} missing")


@pytest.mark.parametrize("placement", ["none", "ring"])
def test_causal_visibility_two_dcs(tmp_path, placement):
    kw = {"device_placement": "ring", "device_flush_ops": 8} \
        if placement == "ring" else {}
    bus = InProcBus()
    a = DataCenter("dcA", bus, config=_cfg(tmp_path, "a", **kw))
    b = DataCenter("dcB", bus, config=_cfg(tmp_path, "b", **kw))
    try:
        connect_dcs([a, b])
        a.start_bg_processes()
        b.start_bg_processes()
        writes, reads = _run_trace(a, b)
        assert len(writes) >= 2 * N_WRITES
        _validate(writes, reads)
    finally:
        a.close()
        b.close()


def test_causal_visibility_gentlerain(tmp_path):
    """Same trace under txn_prot='gr': snapshot semantics (downward
    closure) and session monotonicity must hold at the scalar-GST
    snapshot too (reference gr_snapshot_obtain, src/cure.erl:233-257).
    The entry-wise causal floor is Clock-SI's rule, not GentleRain's
    (GR waits only on the client's own-DC entry vs the GST)."""
    bus = InProcBus()
    ca = _cfg(tmp_path, "a")
    cb = _cfg(tmp_path, "b")
    ca.txn_prot = "gr"
    cb.txn_prot = "gr"
    a = DataCenter("dcA", bus, config=ca)
    b = DataCenter("dcB", bus, config=cb)
    try:
        connect_dcs([a, b])
        a.start_bg_processes()
        b.start_bg_processes()
        writes, reads = _run_trace(a, b)
        assert len(writes) >= 2 * N_WRITES
        _validate(writes, reads, causal_floor=False)
    finally:
        a.close()
        b.close()
